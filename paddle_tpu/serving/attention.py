"""Ragged paged attention over the flat page pool.

The op behind `attend_with_cache` when the cache is a `PagedLayerCache`:
write this step's K/V into the pool at each row's own position, then
attend each query over exactly its sequence's pages (rows sit at DIFFERENT
positions — the batch is ragged, Ragged Paged Attention's setting).

Two paths, mirroring ops/pallas_kernels.py's selection policy:
- a pure-jnp reference path (gather pages via the page table, mask by
  per-row length, reuse F.scaled_dot_product_attention) — numerically the
  twin of the static-cache `attend_with_cache`, runs everywhere;
- a Pallas decode kernel gated on backend: grid (batch, kv_head, page),
  the page table rides in SMEM via scalar prefetch and the BlockSpec index
  map gathers one (page_size, head_dim) K/V tile per step straight from
  the pool (no host-side gather), online-softmax accumulation in VMEM.

Both steps stay inside ONE jitted call per decode (T3's single-dispatch
rule, arxiv 2401.16677): the write, the gather and the softmax never
bounce logits or pages to the host.

Tensor parallelism (serving.tp) needs no changes here: under shard_map
each shard traces this op with the SAME code on shard-local shapes —
kv pool slabs of num_kv_heads/tp heads, queries of num_heads/tp heads —
while page tables, positions and lengths arrive replicated. Attention is
embarrassingly parallel over heads, so the shard-local result is exact;
the block's single psum lives downstream in the row-parallel O
projection, never in the attention op itself. That stays true under
collective/compute overlap (serving.overlap): the ring-split reduction
replaces only the downstream psum — this op's output just becomes the
partial the ring chunks, transports and reduces while the next matmuls
run.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .kv_cache import NULL_PAGE, PagedLayerCache, overflow_position

__all__ = ["paged_attend", "paged_decode_attention",
           "paged_decode_available", "ragged_paged_attention",
           "ragged_attention_available", "advance_positions", "KERNEL_MODE"]

# "auto": Pallas kernel on TPU, jnp reference elsewhere; "off": always the
# reference; "interpret": run the Pallas kernel in interpret mode (hermetic
# CPU testing of the kernel itself — slow, test-only)
KERNEL_MODE = "auto"


def _on_tpu() -> bool:
    from ..ops.pallas_kernels import _on_tpu as on_tpu

    return on_tpu()


def _count_dispatch(path: str) -> None:
    """Trace-time dispatch accounting: which paged-attention path a
    jitted step compiled against (Pallas kernel / interpret / jnp
    reference / prefill variants). This runs only while a step is being
    TRACED — steady-state dispatches replay the compiled program and pay
    nothing — so the process-global observability registry ends up with
    one count per (executable, layer), a cheap cross-check that TPU runs
    really lowered the kernel path."""
    from ..observability import global_registry

    global_registry().counter(
        "serving_attention_dispatch_total",
        "trace-time paged-attention path selections",
        labels={"path": path}).inc()


def paged_decode_available(page_size: int, head_dim: int) -> bool:
    """Shape gates for the Pallas decode kernel: page rows must tile the
    8-sublane axis, head_dim anything pad-able to 128 lanes."""
    return page_size % 8 == 0 and 8 <= head_dim <= 256


def _quant_kernel_ok(page_size: int) -> bool:
    """Extra shape gate for DEQUANTIZING kernels on real TPUs: int8/fp8
    pool tiles need a 32-sublane page axis (Mosaic's narrow-dtype tile is
    (32, 128); fp32/bf16 get away with 8/16). Interpret mode skips Mosaic
    and accepts any page size."""
    return page_size % 32 == 0


def advance_positions(positions, live, max_pages: int,
                      page_size: int) -> jnp.ndarray:
    """Device-side position advance for the multi-step decode horizon:
    live rows step to the next token position; dead rows (EOS emitted,
    budget exhausted, batch padding) park at the table-overflow position,
    which `paged_attend` routes to the null page — so a fused decode
    block never needs a host decision to stop a finished row's writes.

    positions: (b,) int32 current write positions; live: (b,) bool.
    """
    park = jnp.int32(overflow_position(max_pages, page_size))
    return jnp.where(live, positions + jnp.int32(1), park)


def _positions(start_pos, b: int, s: int) -> jnp.ndarray:
    """(b, s) int32 global positions for this step's tokens. `start_pos`
    is a scalar (uniform prefill), a (b,) vector (ragged decode), or a
    (b, s) matrix that already IS the positions (flat ragged batch)."""
    start = start_pos._data if hasattr(start_pos, "_data") else start_pos
    start = jnp.asarray(start, jnp.int32)
    if start.ndim == 2:
        return start
    offs = jnp.arange(s, dtype=jnp.int32)
    if start.ndim == 0:
        return jnp.broadcast_to(start + offs, (b, s))
    return start[:, None] + offs[None, :]


def _write_pages(pool, vals, entries, slots):
    """Scatter (b*s, kvh, hd) token K/V rows into the (kvh, P, ps, hd)
    pool at (entries, slots). Rows mapped to the null page collide there
    harmlessly — nothing reads page 0 through a real page table."""
    flat = jnp.transpose(vals, (1, 0, 2))            # (kvh, b*s, hd)
    return pool.at[:, entries, slots].set(flat)


def paged_attend(q, k, v, cache: PagedLayerCache, start_pos, rep,
                 bias=None):
    """The paged twin of `attend_with_cache`: write K/V into the pool,
    attend q over the page table. Returns (ctx Tensor, new cache view).

    q: Tensor (b, s, heads, hd); k/v: Tensor (b, s, kv_heads, hd);
    start_pos: scalar (prefill, whole batch at offset 0) or (b,) int32
    (decode, one token per row at its own position); bias: optional
    additive (1, heads, s, L) attention bias, cropped/zero-padded on its
    key axis to this step's key length.
    """
    kp, vp = cache.k_pool, cache.v_pool
    page_table = cache.page_table
    ps = cache.page_size
    b, s = q.shape[0], q.shape[1]
    max_pages = page_table.shape[1]

    kd_raw = k._data if hasattr(k, "_data") else k
    vd_raw = v._data if hasattr(v, "_data") else v
    if cache.quantized:
        # quantized pools: fresh K/V is quantized ONCE here, at page-write
        # time, so every later read — decode, chunked prefill, ragged,
        # prefix-cache reuse — sees the identical bytes (lazy import: an
        # fp32/bf16 cache never reaches this branch)
        from .quant import quantize_tokens
        spec = _pool_quant_spec(kp.dtype)
        kd, k_sc = quantize_tokens(kd_raw, spec)
        vd, v_sc = quantize_tokens(vd_raw, spec)
    else:
        kd = kd_raw.astype(kp.dtype)
        vd = vd_raw.astype(vp.dtype)
    pos = _positions(start_pos, b, s)                # (b, s)
    page_idx = pos // ps
    if cache.row_ids is not None:
        # flat ragged batch (b == 1, s == T): token t writes through the
        # page table ROW it belongs to, not batch row 0
        pt_rows = page_table[cache.row_ids]          # (T, maxP)
        entries = jnp.take_along_axis(
            pt_rows, jnp.clip(page_idx[0], 0, max_pages - 1)[:, None],
            axis=1)[:, 0][None]                      # (1, T)
    else:
        entries = jnp.take_along_axis(
            page_table, jnp.clip(page_idx, 0, max_pages - 1), axis=1)
    # padding rows whose position overflows the table (suffix prefill:
    # offset + bucket may exceed max_pages * page_size) must land in the
    # null page — clipping the index instead would alias them onto the
    # sequence's REAL last page and corrupt it
    entries = jnp.where(page_idx >= max_pages, NULL_PAGE, entries)
    slots = pos % ps
    kp = _write_pages(kp, kd.reshape(b * s, *kd.shape[2:]),
                      entries.reshape(-1), slots.reshape(-1))
    vp = _write_pages(vp, vd.reshape(b * s, *vd.shape[2:]),
                      entries.reshape(-1), slots.reshape(-1))
    ks_pool, vs_pool = cache.k_scale, cache.v_scale
    if cache.quantized:
        # the scale slab is scattered with the SAME entries/slots as the
        # data slab — the null-page/overflow routing above covers both
        ks_pool = _write_pages(ks_pool, k_sc.reshape(b * s, -1, 1),
                               entries.reshape(-1), slots.reshape(-1))
        vs_pool = _write_pages(vs_pool, v_sc.reshape(b * s, -1, 1),
                               entries.reshape(-1), slots.reshape(-1))
    new_cache = PagedLayerCache(kp, vp, page_table, cache.row_ids,
                                k_scale=ks_pool, v_scale=vs_pool)

    raw_start = start_pos._data if hasattr(start_pos, "_data") else start_pos
    static_zero = isinstance(raw_start, int) and raw_start == 0
    if cache.row_ids is not None:
        ctx = ragged_paged_attention(q, new_cache, pos, rep, bias=bias)
    elif s == 1:
        ctx = paged_decode_attention(q, new_cache, pos[:, 0], rep,
                                     bias=bias)
    elif static_zero and not cache.quantized:
        _count_dispatch("prefill")
        ctx = _prefill_attention(q, kd, vd, pos, rep, bias=bias)
    elif static_zero:
        # quantized pools route EVERY multi-token prefill through the
        # paged gather: the exact path would read the un-quantized fresh
        # K/V and diverge from what chunked/prefix/migration legs read
        # back from the pool — within a quantized mode, all paths must
        # see the same quantized bytes
        _count_dispatch("prefill_paged_quant")
        ctx = _prefill_attention_paged(q, new_cache, pos, rep, bias=bias)
    else:
        # prefill at a TRACED (or nonzero) offset: earlier K/V lives
        # only in the pool's pages, so attend through the page table.
        # Both offset prefills land here — a prefix-cache suffix prefill
        # AND every chunk of a chunked prefill (its offset is traced, so
        # even a first chunk at offset 0 takes this path; that is what
        # lets one chunked executable serve every chunk of every prompt)
        _count_dispatch("prefill_paged_quant" if cache.quantized
                        else "prefill_paged")
        ctx = _prefill_attention_paged(q, new_cache, pos, rep, bias=bias)
    return ctx, new_cache


def _pool_quant_spec(storage_dtype):
    """KVQuantSpec for a quantized pool's storage dtype (trace-time only,
    reached exclusively from quantized branches)."""
    from .quant import resolve_kv_dtype
    name = ("int8" if jnp.dtype(storage_dtype) == jnp.dtype(jnp.int8)
            else "fp8")
    return resolve_kv_dtype(name)


def _expand_kv(x, rep):
    return jnp.repeat(x, rep, axis=2) if rep > 1 else x


def _crop_bias(bias, length: int) -> jnp.ndarray:
    """Additive bias (1, heads, s, L) -> (1, heads, s, length): crop or
    zero-pad the key axis (the paged step's key extent is maxP*page_size,
    not the bias builder's max_len)."""
    bias_d = bias._data if hasattr(bias, "_data") else bias
    have = bias_d.shape[-1]
    if have >= length:
        return bias_d[..., :length]
    return jnp.pad(bias_d, ((0, 0),) * (bias_d.ndim - 1)
                   + ((0, length - have),))


def _prefill_attention(q, kd, vd, pos, rep, bias=None):
    """Prefill attends over this step's own K/V block (the sequence starts
    at position 0, so the block IS the cache) — same mask arithmetic as
    the static-cache path for exact parity."""
    from ..nn import functional as F

    s = kd.shape[1]
    kf = _expand_kv(kd, rep)
    vf = _expand_kv(vd, rep)
    # query at global pos[i, r] sees keys at pos[i, c] <= pos[i, r]; with
    # a shared offset this is plain causal, kept per-row for generality
    allowed = pos[:, None, :] <= pos[:, :, None]          # (b, s, s)
    mask = jnp.where(allowed, 0.0, -1e9).astype(jnp.float32)[:, None]
    if bias is not None:
        mask = mask + _crop_bias(bias, s).astype(jnp.float32)
    return F.scaled_dot_product_attention(
        q, Tensor(kf), Tensor(vf), attn_mask=Tensor(mask), is_causal=False)


def _prefill_attention_paged(q, cache: PagedLayerCache, pos, rep,
                             bias=None):
    """Multi-token prefill at a NONZERO offset (prefix-cache hit): the
    queries' earlier keys are cached pages written by another request, so
    gather the whole sequence through the page table — the pool already
    holds this step's suffix K/V — and mask causally by global position.
    Reference path (jnp gather + sdpa), the s>1 twin of
    `_paged_decode_reference`; the Pallas kernel stays decode-only."""
    from ..nn import functional as F

    kp, vp, page_table = cache.k_pool, cache.v_pool, cache.page_table
    b = page_table.shape[0]
    ps = cache.page_size
    length = page_table.shape[1] * ps

    def gather(pool, scale=None):
        g = pool[:, page_table]                  # (kvh, b, maxP, ps, hd)
        kvh, _, mp, _, hd = g.shape
        out = jnp.transpose(g, (1, 2, 3, 0, 4)).reshape(
            b, mp * ps, kvh, hd)
        if scale is None:
            return out
        # quantized pool: dequantize against the gathered scale slab
        # ((kvh, b, maxP, ps, 1) -> (b, L, kvh, 1) by the same permute)
        return out.astype(jnp.float32) * gather(scale)

    kf = _expand_kv(gather(kp, cache.k_scale), rep)
    vf = _expand_kv(gather(vp, cache.v_scale), rep)
    # query at global pos[i, r] sees pool column j iff j <= pos[i, r];
    # pool padding (null page, beyond-length slots) masks to the same
    # -1e9 floor as the reference decode path
    allowed = (jnp.arange(length, dtype=jnp.int32)[None, None, :]
               <= pos[:, :, None])                       # (b, s, L)
    mask = jnp.where(allowed, 0.0, -1e9).astype(jnp.float32)[:, None]
    if bias is not None:
        mask = mask + _crop_bias(bias, length).astype(jnp.float32)
    return F.scaled_dot_product_attention(
        q, Tensor(kf), Tensor(vf), attn_mask=Tensor(mask), is_causal=False)


def paged_decode_attention(q, cache: PagedLayerCache, pos, rep,
                           bias=None):
    """One-token-per-row ragged attention over the page pool.

    q: Tensor (b, 1, heads, hd); pos: (b,) int32 — each row's token
    position (its key length minus one). Returns ctx Tensor (b, 1, heads,
    hd).
    """
    hd = q.shape[-1]
    use_kernel = (KERNEL_MODE != "off" and bias is None
                  and paged_decode_available(cache.page_size, hd)
                  and (not cache.quantized
                       or KERNEL_MODE == "interpret"
                       or _quant_kernel_ok(cache.page_size))
                  and (KERNEL_MODE == "interpret" or _on_tpu()))
    if use_kernel:
        tag = ("decode_pallas_interpret"
               if KERNEL_MODE == "interpret" else "decode_pallas")
        _count_dispatch(tag + "_quant" if cache.quantized else tag)
        qd = q._data if hasattr(q, "_data") else q
        out = _paged_decode_pallas(qd, cache.k_pool, cache.v_pool,
                                   cache.page_table, pos,
                                   k_scale=cache.k_scale,
                                   v_scale=cache.v_scale,
                                   interpret=KERNEL_MODE == "interpret")
        return Tensor(out)
    _count_dispatch("decode_reference_quant" if cache.quantized
                    else "decode_reference")
    return _paged_decode_reference(q, cache, pos, rep, bias)


def _paged_decode_reference(q, cache, pos, rep, bias=None):
    """Gather the sequence's pages into a contiguous (b, L, kvh, hd) view
    and run the reference sdpa with a per-row length mask — bit-for-bit
    the static cache computation, with the pool's exact-zero padded
    columns masked to the same -1e9 floor."""
    from ..nn import functional as F

    kp, vp, page_table = cache.k_pool, cache.v_pool, cache.page_table
    b = page_table.shape[0]
    ps = cache.page_size
    length = page_table.shape[1] * ps
    # (kvh, b, maxP, ps, hd) -> (b, L, kvh, hd)
    def gather(pool, scale=None):
        g = pool[:, page_table]
        kvh, _, mp, _, hd = g.shape
        out = jnp.transpose(g, (1, 2, 3, 0, 4)).reshape(
            b, mp * ps, kvh, hd)
        if scale is None:
            return out
        return out.astype(jnp.float32) * gather(scale)

    kf = _expand_kv(gather(kp, cache.k_scale), rep)
    vf = _expand_kv(gather(vp, cache.v_scale), rep)
    allowed = jnp.arange(length, dtype=jnp.int32)[None, :] <= pos[:, None]
    mask = jnp.where(allowed, 0.0, -1e9).astype(
        jnp.float32)[:, None, None, :]                    # (b, 1, 1, L)
    if bias is not None:
        mask = mask + _crop_bias(bias, length).astype(jnp.float32)
    return F.scaled_dot_product_attention(
        q, Tensor(kf), Tensor(vf), attn_mask=Tensor(mask), is_causal=False)


# ------------------------------------------------------ ragged flat batch

def ragged_attention_available(page_size: int, head_dim: int) -> bool:
    """Shape gates for the Pallas ragged kernel — identical to the decode
    kernel's (same tile geometry, one more prefetched scalar array)."""
    return paged_decode_available(page_size, head_dim)


def ragged_paged_attention(q, cache: PagedLayerCache, pos, rep, bias=None):
    """Flat ragged attention: ALL rows' tokens of a mixed prefill/decode
    step ride one (1, T) sequence axis; `cache.row_ids[t]` names token
    t's page-table row and `pos[0, t]` its global position (= its kv
    length minus one). Decode rows contribute one token, prefill chunks a
    contiguous run; padding tokens park at the table-overflow position
    and attend nothing.

    q: Tensor (1, T, heads, hd); pos: (1, T) int32. Returns ctx Tensor
    (1, T, heads, hd).
    """
    hd = q.shape[-1]
    use_kernel = (KERNEL_MODE != "off" and bias is None
                  and ragged_attention_available(cache.page_size, hd)
                  and (not cache.quantized
                       or KERNEL_MODE == "interpret"
                       or _quant_kernel_ok(cache.page_size))
                  and (KERNEL_MODE == "interpret" or _on_tpu()))
    if use_kernel:
        tag = ("ragged_pallas_interpret"
               if KERNEL_MODE == "interpret" else "ragged_pallas")
        _count_dispatch(tag + "_quant" if cache.quantized else tag)
        qd = q._data if hasattr(q, "_data") else q
        out = _ragged_paged_pallas(qd, cache.k_pool, cache.v_pool,
                                   cache.page_table, pos[0],
                                   cache.row_ids,
                                   k_scale=cache.k_scale,
                                   v_scale=cache.v_scale,
                                   interpret=KERNEL_MODE == "interpret")
        return Tensor(out)
    _count_dispatch("ragged_reference_quant" if cache.quantized
                    else "ragged_reference")
    return _ragged_attention_reference(q, cache, pos, rep, bias)


def _ragged_attention_reference(q, cache, pos, rep, bias=None):
    """Per-token twin of `_paged_decode_reference`: gather each TOKEN's
    page-table row into a contiguous (T, L, kvh, hd) view and run the
    reference sdpa with the same per-token position mask — so a decode
    row's token here is bit-for-bit the (b, 1) decode computation, and a
    chunk's tokens match the chunked-prefill paged gather. Padding
    tokens (position == table capacity) mask everything and produce
    garbage rows the caller never reads."""
    from ..nn import functional as F

    if bias is not None:
        raise NotImplementedError(
            "ragged flat attention does not take an attention bias")
    kp, vp, page_table = cache.k_pool, cache.v_pool, cache.page_table
    rows = cache.row_ids                              # (T,)
    ps = cache.page_size
    t = q.shape[1]
    length = page_table.shape[1] * ps
    pt = page_table[rows]                             # (T, maxP)

    def gather(pool, scale=None):
        g = pool[:, pt]                    # (kvh, T, maxP, pgsz, hd)
        kvh, _, mp, _, hd = g.shape
        out = jnp.transpose(g, (1, 2, 3, 0, 4)).reshape(
            t, mp * ps, kvh, hd)
        if scale is None:
            return out
        return out.astype(jnp.float32) * gather(scale)

    kf = _expand_kv(gather(kp, cache.k_scale), rep)
    vf = _expand_kv(gather(vp, cache.v_scale), rep)
    qd = q._data if hasattr(q, "_data") else q
    qt = Tensor(qd[0][:, None])                       # (T, 1, heads, hd)
    allowed = (jnp.arange(length, dtype=jnp.int32)[None, :]
               <= pos[0][:, None])                    # (T, L)
    mask = jnp.where(allowed, 0.0, -1e9).astype(
        jnp.float32)[:, None, None, :]                # (T, 1, 1, L)
    ctx = F.scaled_dot_product_attention(
        qt, Tensor(kf), Tensor(vf), attn_mask=Tensor(mask),
        is_causal=False)
    cd = ctx._data if hasattr(ctx, "_data") else ctx
    return Tensor(cd[:, 0][None])                     # (1, T, heads, hd)


# ------------------------------------------------------- Pallas decode path

def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                         ps, scale, n_pages, quantized=False):
    """Grid (batch, kv_head, page): one (page_size, head_dim) K/V tile per
    step, gathered by the BlockSpec index map from the scalar-prefetched
    page table; online softmax in fp32 VMEM scratch (flash structure).
    Pages wholly past the row's position are skipped splash-style.

    Quantized pools add two (page_size, 1) fp32 scale tiles gathered by
    the same index map; K/V tiles dequantize in-register (one cast + one
    lane-broadcast multiply per tile) before the unchanged flash loop —
    the unquantized trace is byte-identical to before."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest

    b_ = pl.program_id(0)
    pi = pl.program_id(2)
    pos = pos_ref[b_]

    @pl.when(pi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        if quantized:
            qblk = q_ref[0, 0].astype(jnp.float32)
            kblk = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]
        else:
            qblk = q_ref[0, 0]
            kblk = k_ref[0, 0]
        # (G, ps) scores: the q group rides the MXU in the input dtype
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale
        cols = pi * ps + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols <= pos, s, -jnp.inf)
        m_prev = m_ref[...]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # no jnp.isfinite (its primitive has no Mosaic lowering on some
        # jax versions): m_safe only needs the all-masked guard, and
        # exp(-inf - finite) is already an exact 0 for masked columns
        # and never-seen rows alike
        m_safe = jnp.where(m_cur == -jnp.inf, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(m_prev - m_safe)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_cur
        vblk = v_ref[0, 0]
        if quantized:
            vblk = vblk.astype(jnp.float32) * vs_ref[0, 0]
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)

    pl.when(pi * ps <= pos)(_compute)

    @pl.when(pi == n_pages - 1)
    def _done():
        l_fin = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_fin).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pool, v_pool, page_table, pos,
                         k_scale=None, v_scale=None, interpret=False):
    """q: (b, 1, heads, hd); pools: (kvh, P, ps, hd); page_table: (b,
    maxP) i32; pos: (b,) i32; k_scale/v_scale: optional (kvh, P, ps, 1)
    fp32 scale slabs (quantized pools). Returns (b, 1, heads, hd)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, _, heads, hd = q.shape
    kvh, _, ps, _ = k_pool.shape
    rep = heads // kvh
    max_pages = page_table.shape[1]
    scale = 1.0 / (hd ** 0.5)
    quantized = k_scale is not None

    d_p = _round_up(hd, 128)
    g_p = _round_up(rep, 8)
    # (b, kvh, G, hd): q head h*rep + g attends kv head h — matches the
    # repeat(axis=2) expansion of the reference path
    qg = q.reshape(b, kvh, rep, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, g_p - rep), (0, d_p - hd)))
    kp = jnp.pad(k_pool, ((0, 0), (0, 0), (0, 0), (0, d_p - hd)))
    vp = jnp.pad(v_pool, ((0, 0), (0, 0), (0, 0), (0, d_p - hd)))

    q_spec = pl.BlockSpec((1, 1, g_p, d_p),
                          lambda b_, h_, pi, pt, ps_: (b_, h_, 0, 0))
    kv_spec = pl.BlockSpec((1, 1, ps, d_p),
                           lambda b_, h_, pi, pt, ps_: (h_, pt[b_, pi],
                                                        0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qg, kp, vp]
    if quantized:
        sc_spec = pl.BlockSpec((1, 1, ps, 1),
                               lambda b_, h_, pi, pt, ps_: (h_, pt[b_, pi],
                                                            0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, max_pages),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((g_p, d_p), jnp.float32),
            pltpu.VMEM((g_p, 1), jnp.float32),
            pltpu.VMEM((g_p, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, ps=ps, scale=scale,
                          n_pages=max_pages, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, g_p, d_p), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), pos.astype(jnp.int32), *operands)
    return out[:, :, :rep, :hd].reshape(b, 1, heads, hd)


# ------------------------------------------------------- Pallas ragged path

def _ragged_attend_kernel(pt_ref, pos_ref, row_ref, q_ref, k_ref, v_ref,
                          *rest, ps, scale, n_pages, quantized=False):
    """Grid (token, kv_head, page): the decode kernel's flash loop with the
    batch axis replaced by a flat TOKEN axis — the BlockSpec index map
    gathers page `pi` of token t's OWN page-table row (row_ref, scalar-
    prefetched alongside the table). Pages wholly past the token's
    position are skipped splash-style, and tokens parked at the table
    capacity (flat-batch padding) skip every page and emit zeros.
    Quantized pools dequantize each K/V tile in-register against the
    (page_size, 1) scale tiles, as in the decode kernel."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest

    t_ = pl.program_id(0)
    pi = pl.program_id(2)
    pos = pos_ref[t_]

    @pl.when(pi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        if quantized:
            qblk = q_ref[0, 0].astype(jnp.float32)
            kblk = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]
        else:
            qblk = q_ref[0, 0]
            kblk = k_ref[0, 0]
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale
        cols = pi * ps + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols <= pos, s, -jnp.inf)
        m_prev = m_ref[...]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # same all-masked guard as the decode kernel: no jnp.isfinite
        # (no Mosaic lowering on some jax versions)
        m_safe = jnp.where(m_cur == -jnp.inf, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(m_prev - m_safe)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_cur
        vblk = v_ref[0, 0]
        if quantized:
            vblk = vblk.astype(jnp.float32) * vs_ref[0, 0]
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)

    # padding tokens sit exactly AT the table capacity (n_pages * ps), so
    # the second clause skips all their pages; real tokens always sit
    # below it
    pl.when((pi * ps <= pos) & (pos < n_pages * ps))(_compute)

    @pl.when(pi == n_pages - 1)
    def _done():
        l_fin = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_fin).astype(o_ref.dtype)


def _ragged_paged_pallas(q, k_pool, v_pool, page_table, pos, row_ids,
                         k_scale=None, v_scale=None, interpret=False):
    """q: (1, T, heads, hd); pools: (kvh, P, ps, hd); page_table:
    (B, maxP) i32; pos/row_ids: (T,) i32; k_scale/v_scale: optional
    (kvh, P, ps, 1) fp32 scale slabs. Returns (1, T, heads, hd)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, t, heads, hd = q.shape
    kvh, _, ps, _ = k_pool.shape
    rep = heads // kvh
    max_pages = page_table.shape[1]
    scale = 1.0 / (hd ** 0.5)
    quantized = k_scale is not None

    d_p = _round_up(hd, 128)
    g_p = _round_up(rep, 8)
    # (T, kvh, G, hd): q head h*rep + g attends kv head h, exactly the
    # decode kernel's grouping with tokens in place of batch rows
    qg = q.reshape(t, kvh, rep, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, g_p - rep), (0, d_p - hd)))
    kp = jnp.pad(k_pool, ((0, 0), (0, 0), (0, 0), (0, d_p - hd)))
    vp = jnp.pad(v_pool, ((0, 0), (0, 0), (0, 0), (0, d_p - hd)))

    q_spec = pl.BlockSpec((1, 1, g_p, d_p),
                          lambda t_, h_, pi, pt, ps_, rw: (t_, h_, 0, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, ps, d_p),
        lambda t_, h_, pi, pt, ps_, rw: (h_, pt[rw[t_], pi], 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qg, kp, vp]
    if quantized:
        sc_spec = pl.BlockSpec(
            (1, 1, ps, 1),
            lambda t_, h_, pi, pt, ps_, rw: (h_, pt[rw[t_], pi], 0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t, kvh, max_pages),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((g_p, d_p), jnp.float32),
            pltpu.VMEM((g_p, 1), jnp.float32),
            pltpu.VMEM((g_p, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_attend_kernel, ps=ps, scale=scale,
                          n_pages=max_pages, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, kvh, g_p, d_p), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), pos.astype(jnp.int32),
      row_ids.astype(jnp.int32), *operands)
    return out[:, :, :rep, :hd].reshape(1, t, heads, hd)
