"""Replicated serving: N supervised engines behind one front door.

PR 5 made a single `ServingEngine` isolate failures; PR 7 made the
engine itself replaceable (`EngineSupervisor`: journal, snapshot,
rebuild, exactly-once delivery). But one engine is still one blast
radius and one queue — the operability tier that systems like vLLM and
Orca assume exists ABOVE the engine (many workers, any of which may
die, behind one job-level API) is this module. `ServingCluster` owns N
`EngineSupervisor`-wrapped replicas and presents the single-engine API
(`add_request` / `cancel` / `status` / `output` / `step` / `stream` /
`run` / `stats`) so existing callers are drop-in. Three pillars:

- **Router** — load-aware placement over the replicas that accept new
  work: candidates are ordered healthy-before-degraded, then by a load
  score combining waiting-queue depth (dominant), in-flight decode
  budget, and KV page pressure. With `prefix_affinity=True` the router
  first steers a request toward the replica already holding its longest
  full-page prompt prefix: a live `PrefixCache` is probed read-only
  (`peek` — no refs, no LRU ticks), and an LRU table of prefix-hash →
  replica covers engines without prefix caching. A replica raising
  `EngineOverloaded` at admission spills the request to the next
  candidate; only when EVERY candidate is full does the overload reach
  the caller.

- **Health + failover** — per-replica `healthy | degraded | draining |
  dead`, driven by the supervisor's own signals: a restart (watchdog,
  fault storm, fatal fault) or `degrade_after_faults` engine faults
  inside `degrade_window_steps` marks a replica degraded; it heals
  after `degrade_recovery_steps` clean steps. `drain(i)` stops
  placement while in-flight work finishes; `resume(i)` re-enables.
  When a supervisor exhausts `max_restarts` it raises `EngineDead` —
  the cluster catches it mid-`step`, and MIGRATES: every journal-live
  request of the dead replica is re-admitted on the best survivor as a
  folded prompt (original prompt + delivered tokens, PRNG chain
  replayed by `replay_key_state`, original request id preserved via
  `reserve_request_ids`), so the consumer's token stream continues
  bit-identically and exactly-once — delivered tokens are never
  re-delivered, undelivered ones are recomputed.

- **Cluster resilience policy** — `max_dead_replicas` bounds how many
  replicas may die before the cluster itself raises `EngineDead`;
  `hedge_after_s` re-dispatches a request stuck on a degraded replica
  as a clone on another replica (both race; streams are bit-identical
  by construction, so the first copy to produce a NEW token wins and
  the loser is cancelled through its journal — the consumer sees one
  stream); `chaos_seed=` derives one deterministic `FaultInjector` per
  replica from a single seed (sha512-stable, like the injector's own
  per-site streams) for kill-anything cluster chaos tests.

What migration preserves: the token stream (bit-identical, greedy and
seeded-stochastic), the request id, the remaining budget, the absolute
wall-clock deadline, exactly-once delivery. What it does not: KV pages
(the fold re-prefills on the survivor — cost is a re-prefill, never a
re-decode), engine-local latency state (TTFT on the dead replica is
journal history, not carried), and queue position (migrated requests
re-enter admission like restore()'s re-admissions, ahead of the
bounded-queue check).

Zero cost when unused: a plain `ServingEngine` (or a bare supervisor)
executes none of this module — tests pin that with a raise-on-touch
guard over every cluster entry point.
"""
from __future__ import annotations

import dataclasses
import hashlib
import inspect
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, \
    Tuple

import numpy as np

from ..observability import MetricsRegistry
from ..profiler import add_host_span
from .recovery import EngineSupervisor, RequestJournal
from .resilience import EngineDead, EngineOverloaded, FaultInjector, \
    TERMINAL_STATUSES

__all__ = ["ClusterRequest", "ReplicaHandle", "ServingCluster"]

HEALTH_STATES = ("healthy", "degraded", "draining", "dead")
_HEALTH_CODE = {s: i for i, s in enumerate(HEALTH_STATES)}


@dataclasses.dataclass
class _Copy:
    """One engine-level incarnation of a cluster request: the primary,
    a migrated re-admission, or a hedge clone. `base` is how many
    cluster-delivered tokens were folded into this copy's prompt;
    `emitted` counts tokens the copy has produced since, so the copy's
    i-th token is absolute stream position `base + emitted`."""

    replica: int
    base: int
    emitted: int = 0


@dataclasses.dataclass
class ClusterRequest:
    """Cluster-level view of one request: the consumer-visible stream
    (`delivered`), the submission metadata every copy is folded from,
    and which engine-level copies currently carry it."""

    request_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    eos_token_id: Optional[int]
    deadline_wall: Optional[float]
    arrival_wall: float
    delivered: List[int] = dataclasses.field(default_factory=list)
    status: Optional[str] = None      # terminal status, None while live
    error: Optional[str] = None
    replica: int = -1                 # current owner replica index
    copies: Dict[int, _Copy] = dataclasses.field(default_factory=dict)
    placed_t: float = 0.0
    last_progress_t: float = 0.0
    migrations: int = 0
    hedges: int = 0

    @property
    def live(self) -> bool:
        return self.status is None


class ReplicaHandle:
    """One replica's cluster-side bookkeeping: the supervisor, its
    (optional) chaos injector, and the health state machine's inputs —
    restart/fault watermarks and the clean-step recovery counter."""

    def __init__(self, index: int, supervisor: EngineSupervisor,
                 injector: Optional[FaultInjector],
                 fault_window_steps: int):
        self.index = index
        self.supervisor = supervisor
        self.injector = injector
        self.health = "healthy"
        self.seen_restarts = 0
        self.last_fault_events = 0
        self.fault_window: deque = deque(maxlen=max(fault_window_steps, 1))
        self.clean_steps = 0

    @property
    def journal(self) -> RequestJournal:
        return self.supervisor.journal

    def __repr__(self) -> str:
        return f"ReplicaHandle(r{self.index}, {self.health})"


class ServingCluster:
    """N supervised `ServingEngine` replicas behind the single-engine
    API — see the module docstring for the router / health / policy
    design. `factory` builds one engine; it may be zero-arg, or accept
    `replica=` (the replica index) and/or `fault_injector=` keyword
    arguments — the cluster passes whichever the signature admits, and
    the per-replica supervisor reuses the same closure for rebuilds, so
    an injector's call counts span engine incarnations exactly like the
    single-supervisor chaos tests.

    `placement` is `"load"` (default: healthy-first, then the load
    score) or `"round_robin"` (ignore load; still healthy-first).
    `prefix_affinity` steers shared-prefix requests onto the replica
    whose cache holds the prefix. `hedge_after_s=None` disables
    hedging. `max_dead_replicas` defaults to `num_replicas - 1`: the
    cluster survives anything short of losing every replica.
    """

    def __init__(self, factory: Callable[..., object], *,
                 num_replicas: int = 2,
                 placement: str = "load",
                 prefix_affinity: bool = True,
                 hedge_after_s: Optional[float] = None,
                 max_dead_replicas: Optional[int] = None,
                 degrade_after_faults: int = 3,
                 degrade_window_steps: int = 32,
                 degrade_recovery_steps: int = 16,
                 affinity_table_size: int = 4096,
                 metrics: Optional[MetricsRegistry] = None,
                 enable_metrics: bool = True,
                 supervisor_kw: Optional[dict] = None,
                 fault_injectors: Optional[Sequence[FaultInjector]] = None,
                 chaos_seed: Optional[int] = None,
                 journal_paths: Optional[Sequence[str]] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 tp_size: int = 1,
                 devices: Optional[Sequence] = None,
                 postmortem_dir: Optional[str] = None):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if placement not in ("load", "round_robin"):
            raise ValueError(
                f"unknown placement {placement!r}; "
                "one of ('load', 'round_robin')")
        if fault_injectors is not None \
                and len(fault_injectors) != num_replicas:
            raise ValueError(
                f"fault_injectors has {len(fault_injectors)} entries "
                f"for {num_replicas} replicas")
        if journal_paths is not None \
                and len(journal_paths) != num_replicas:
            raise ValueError(
                f"journal_paths has {len(journal_paths)} entries "
                f"for {num_replicas} replicas")
        self.num_replicas = num_replicas
        self.placement = placement
        self.prefix_affinity = bool(prefix_affinity)
        self.hedge_after_s = hedge_after_s
        self.max_dead_replicas = (num_replicas - 1
                                  if max_dead_replicas is None
                                  else int(max_dead_replicas))
        self.degrade_after_faults = int(degrade_after_faults)
        self.degrade_recovery_steps = int(degrade_recovery_steps)
        self._clock = clock
        if fault_injectors is None and chaos_seed is not None:
            fault_injectors = self.chaos_injectors(chaos_seed,
                                                   num_replicas)
        self.fault_injectors = (list(fault_injectors)
                                if fault_injectors is not None else None)
        self.metrics = metrics if metrics is not None else (
            MetricsRegistry() if enable_metrics else None)
        self._init_metrics()
        # tensor parallelism (ISSUE 10): carve the local device list —
        # SORTED by device id, so every process carves identically no
        # matter how its jax.devices() happens to be ordered — into
        # num_replicas disjoint tp_size-wide sub-meshes; replica i gets
        # devices [i*tp : (i+1)*tp]. tp_size=1 touches zero TP code.
        self.tp_size = int(tp_size)
        if self.tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {tp_size}")
        if self.tp_size > 1:
            from ..parallel.mesh import carve_submeshes

            self._replica_devices: Optional[List[tuple]] = carve_submeshes(
                num_replicas, self.tp_size, devices)
        else:
            self._replica_devices = None
        # factory protocol: pass only what the signature admits
        params = inspect.signature(factory).parameters
        varkw = any(p.kind == inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
        self._factory_kw = {
            "replica": varkw or "replica" in params,
            "fault_injector": varkw or "fault_injector" in params,
            "tp_size": varkw or "tp_size" in params,
            "devices": varkw or "devices" in params,
        }
        if self.tp_size > 1 and not (self._factory_kw["tp_size"]
                                     and self._factory_kw["devices"]):
            raise ValueError(
                "ServingCluster(tp_size>1) needs a factory that accepts "
                "tp_size= and devices= keywords (or **kwargs) so each "
                "replica's engine lands on its carved sub-mesh")
        self._factory = factory
        sup_kw = dict(supervisor_kw or {})
        self.replicas: List[ReplicaHandle] = []
        for i in range(num_replicas):
            journal = RequestJournal(journal_paths[i]
                                     if journal_paths is not None
                                     else None)
            sup = EngineSupervisor(
                self._engine_factory(i), journal=journal,
                metrics=self.metrics, **sup_kw)
            self.replicas.append(ReplicaHandle(
                i, sup,
                (self.fault_injectors[i]
                 if self.fault_injectors is not None else None),
                degrade_window_steps))
        # consumer-facing request table + engine-rid -> consumer-rid
        # aliases (hedge clones / re-minted migrations); alias entries
        # outlive their copies so a cancelled loser's late-drained
        # tokens still resolve (and are dropped) instead of leaking
        # through as a phantom request
        self._records: Dict[int, ClusterRequest] = {}
        self._alias: Dict[int, int] = {}
        # prefix-hash -> replica affinity table (LRU-capped), used for
        # replicas without a live PrefixCache to probe
        self._affinity: "OrderedDict[str, int]" = OrderedDict()
        self._affinity_cap = int(affinity_table_size)
        self._page_size = int(
            self.replicas[0].supervisor.engine.page_size)
        self._rr = 0                   # round-robin cursor
        self._step_count = 0
        self.dead_replicas = 0
        # forensics (ISSUE 13): replica deaths dump a post-mortem bundle
        # here — the supervisor's bundle refreshed with the migration
        # events the cluster appended to the dead engine's ring. None =
        # bundles stay in memory (rep.supervisor.postmortem).
        self.postmortem_dir = postmortem_dir
        self.postmortem_paths: List[str] = []

    # ------------------------------------------------------------ metrics
    def _init_metrics(self) -> None:
        m = self.metrics
        if m is None:
            self._m_routed = self._m_aff_hit = self._m_aff_miss = None
            self._m_spill = self._m_shed = self._m_migrations = None
            self._m_migrated_tokens = self._m_migration_s = None
            self._m_hedges = self._m_hedge_cancels = None
            self._m_deaths = self._m_health = None
            self._m_free_pages = self._m_queue_depth = None
            return
        n = self.num_replicas

        def per_replica(cls, name, help):
            return [cls(name, help, labels={"replica": str(i)})
                    for i in range(n)]

        self._m_routed = per_replica(
            m.counter, "serving_cluster_requests_routed_total",
            "requests placed, by replica")
        self._m_aff_hit = m.counter(
            "serving_cluster_affinity_hits_total",
            "placements steered to a replica holding the prefix")
        self._m_aff_miss = m.counter(
            "serving_cluster_affinity_misses_total",
            "placements with no cached prefix anywhere")
        self._m_spill = m.counter(
            "serving_cluster_spillovers_total",
            "admissions retried on another replica after "
            "EngineOverloaded")
        self._m_shed = m.counter(
            "serving_cluster_shed_total",
            "admissions refused by every placeable replica")
        self._m_migrations = m.counter(
            "serving_cluster_migrations_total",
            "requests re-admitted on a survivor after replica death")
        self._m_migrated_tokens = m.counter(
            "serving_cluster_migrated_tokens_total",
            "folded prompt+delivered tokens re-prefilled by migrations")
        self._m_migration_s = m.histogram(
            "serving_cluster_migration_seconds",
            "journal-replay + re-admission wall time per dead replica")
        self._m_hedges = m.counter(
            "serving_cluster_hedges_total",
            "stuck requests re-dispatched as clones")
        self._m_hedge_cancels = m.counter(
            "serving_cluster_hedge_cancels_total",
            "hedge losers cancelled after the race resolved")
        self._m_deaths = m.counter(
            "serving_cluster_replica_deaths_total",
            "replicas declared dead (max_restarts exhausted)")
        self._m_health = per_replica(
            m.gauge, "serving_cluster_replica_health",
            "0 healthy / 1 degraded / 2 draining / 3 dead")
        self._m_free_pages = per_replica(
            m.gauge, "serving_cluster_replica_free_pages",
            "free KV pages, by replica")
        self._m_queue_depth = per_replica(
            m.gauge, "serving_cluster_replica_queue_depth",
            "waiting-queue depth, by replica")

    # ------------------------------------------------------------- chaos
    @staticmethod
    def chaos_injectors(seed: int, n: int) -> List[FaultInjector]:
        """One deterministic `FaultInjector` per replica, all derived
        from a single seed: replica i's injector seed is the first 8
        bytes of sha512(f"{seed}:{i}") — stable across processes (same
        construction as the injector's own per-site streams), so one
        integer reproduces an entire cluster chaos run."""
        return [FaultInjector(seed=int.from_bytes(
            hashlib.sha512(f"{seed}:{i}".encode()).digest()[:8], "big"))
            for i in range(n)]

    def _engine_factory(self, index: int) -> Callable[[], object]:
        def make():
            kw = {}
            if self._factory_kw["replica"]:
                kw["replica"] = index
            if self._factory_kw["fault_injector"] \
                    and self.fault_injectors is not None:
                kw["fault_injector"] = self.fault_injectors[index]
            if self._replica_devices is not None:
                kw["tp_size"] = self.tp_size
                kw["devices"] = self._replica_devices[index]
            return self._factory(**kw)
        return make

    # ------------------------------------------------------------ routing
    def _load_score(self, rep: ReplicaHandle) -> float:
        """Placement load: waiting-queue depth dominates (a queued
        request is a whole prefill + decode the replica still owes),
        remaining in-flight decode budget and KV page pressure break
        ties among equally-deep queues."""
        eng = rep.supervisor.engine
        sch = eng.scheduler
        alloc = eng.cache.allocator
        inflight = sum(r.max_new_tokens - len(r.generated)
                       for r in sch.running)
        used = alloc.num_allocatable - alloc.num_free
        return len(sch.waiting) * 1000.0 + inflight + used

    def _affinity_keys(self, prompt: Sequence[int]
                       ) -> List[Tuple[int, str]]:
        """(prefix_tokens, digest) per full-page prefix, LONGEST first;
        digests are cumulative sha1 over page-sized chunks so every
        prefix of the prompt hashes in one O(len) pass. Capped at
        len(prompt)-1 like `PrefixCache.match`, so the keys cover
        exactly the prefixes admission could reuse."""
        ps = self._page_size
        n_full = (len(prompt) - 1) // ps
        keys: List[Tuple[int, str]] = []
        h = hashlib.sha1()
        for i in range(n_full):
            h.update(np.asarray(prompt[i * ps:(i + 1) * ps],
                                np.int64).tobytes())
            keys.append(((i + 1) * ps, h.hexdigest()))
        keys.reverse()
        return keys

    def _affinity_tokens(self, rep: ReplicaHandle,
                         prompt: Sequence[int],
                         keys: List[Tuple[int, str]]) -> int:
        """Cached-prefix tokens this replica would reuse: a live
        PrefixCache is probed read-only; without one, the affinity
        table's longest hash owned by this replica stands in."""
        eng = rep.supervisor.engine
        if eng is not None and eng.prefix_cache is not None:
            return eng.prefix_cache.peek(prompt)
        for n_tokens, key in keys:
            if self._affinity.get(key) == rep.index:
                return n_tokens
        return 0

    def _note_affinity(self, prompt: Sequence[int], index: int) -> None:
        for _, key in self._affinity_keys(prompt):
            self._affinity[key] = index
            self._affinity.move_to_end(key)
        while len(self._affinity) > self._affinity_cap:
            self._affinity.popitem(last=False)

    def _candidates(self, prompt: Sequence[int]) -> List[ReplicaHandle]:
        """Placement order: healthy replicas before degraded (draining
        and dead never place), each tier by ascending load (or
        round-robin rotation), and — with affinity on — the replica
        holding the longest cached prefix moved to the front."""
        healthy = [r for r in self.replicas if r.health == "healthy"]
        degraded = [r for r in self.replicas if r.health == "degraded"]
        if self.placement == "round_robin":
            if healthy:
                k = self._rr % len(healthy)
                healthy = healthy[k:] + healthy[:k]
            elif degraded:
                k = self._rr % len(degraded)
                degraded = degraded[k:] + degraded[:k]
            self._rr += 1
        else:
            healthy.sort(key=self._load_score)
            degraded.sort(key=self._load_score)
        order = healthy + degraded
        if self.prefix_affinity and order:
            keys = self._affinity_keys(prompt)
            best, best_tokens = None, 0
            for rep in order:
                t = self._affinity_tokens(rep, prompt, keys)
                if t > best_tokens:
                    best, best_tokens = rep, t
            if best is not None:
                order.remove(best)
                order.insert(0, best)
                if self._m_aff_hit is not None:
                    self._m_aff_hit.inc()
            elif self._m_aff_miss is not None:
                self._m_aff_miss.inc()
        return order

    # -------------------------------------------------------- request API
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 1.0, seed: Optional[int] = None,
                    eos_token_id: Optional[int] = None,
                    deadline_s: Optional[float] = None) -> int:
        """Single-engine signature, cluster placement: route to the
        best candidate, spill to the next on `EngineOverloaded`, raise
        it only when every placeable replica is full. The effective
        seed is drawn HERE (not inside the engine) so migration and
        hedging replay the same sampling chain wherever the request
        lands. Returns the consumer-visible request id — stable across
        any number of migrations."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if seed is None:
            # fresh entropy is drawn exactly once, at routing; the seed is
            # journaled with the request, so migration/hedging REPLAY this
            # value rather than redrawing
            seed = int(np.random.randint(0, 2 ** 31 - 1))  # noqa: WALLCLOCK-IN-REPLAY — drawn once, journaled
        candidates = self._candidates(prompt)
        if not candidates:
            raise EngineOverloaded(
                "no placeable replica (all draining or dead)")
        last_exc: Optional[EngineOverloaded] = None
        for tried, rep in enumerate(candidates):
            try:
                rid = rep.supervisor.add_request(
                    prompt, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    seed=seed, eos_token_id=eos_token_id,
                    deadline_s=deadline_s)
            except EngineOverloaded as e:
                last_exc = e
                if self._m_spill is not None:
                    self._m_spill.inc()
                continue
            now = self._clock()
            now_wall = time.time()
            rec = ClusterRequest(
                request_id=rid, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), top_k=int(top_k),
                top_p=float(top_p), seed=int(seed),
                eos_token_id=eos_token_id,
                deadline_wall=(now_wall + deadline_s
                               if deadline_s is not None else None),
                arrival_wall=now_wall,
                replica=rep.index, placed_t=now, last_progress_t=now)
            rec.copies[rid] = _Copy(replica=rep.index, base=0)
            self._records[rid] = rec
            if self._m_routed is not None:
                self._m_routed[rep.index].inc()
            if self.prefix_affinity:
                self._note_affinity(prompt, rep.index)
            # replica tag inside the request's lifecycle lane —
            # trace_summary renders these as [r0->r2]-style headers
            t = time.perf_counter()
            add_host_span(f"serving.request[{rid}].replica[r{rep.index}]",
                          t, t, event_type="Lifecycle")
            return rid
        if self._m_shed is not None:
            self._m_shed.inc()
        raise last_exc

    def cancel(self, request_id: int) -> bool:
        """Cancel the request on every replica currently carrying a
        copy (hedge clones included). True if it was live."""
        rec = self._records.get(request_id)
        if rec is None or rec.status is not None:
            return False
        for erid, copy in list(rec.copies.items()):
            rep = self.replicas[copy.replica]
            try:
                rep.supervisor.cancel(erid)
            except KeyError:
                pass
        rec.status = "cancelled"
        return True

    def status(self, request_id: int) -> Tuple[str, Optional[str]]:
        """(status, error): the cluster record once terminal, else the
        owning replica's live view (waiting/running)."""
        rec = self._records[request_id]
        if rec.status is None:
            self._refresh_status(rec)
        if rec.status is not None:
            return rec.status, rec.error
        for erid, copy in rec.copies.items():
            if copy.replica == rec.replica:
                return self.replicas[copy.replica].supervisor.status(erid)
        for erid, copy in rec.copies.items():
            return self.replicas[copy.replica].supervisor.status(erid)
        return "waiting", rec.error

    def _refresh_status(self, rec: ClusterRequest) -> None:
        """Pull failure-side terminals (failed/expired/shed) up from
        the replicas: a quarantined or expired copy ends the cluster
        request only when NO copy is still making progress."""
        if rec.status is not None or not rec.copies:
            return
        bad: List[Tuple[str, Optional[str]]] = []
        for erid, copy in list(rec.copies.items()):
            sup = self.replicas[copy.replica].supervisor
            try:
                st, err = sup.status(erid)
            except KeyError:
                continue
            if st in TERMINAL_STATUSES and st != "finished":
                bad.append((st, err))
        if bad and len(bad) == len(rec.copies):
            rec.status, rec.error = bad[0]

    def output(self, request_id: int) -> List[int]:
        """prompt + every token delivered to the consumer — the
        cluster-level stream, identical across migrations."""
        rec = self._records[request_id]
        return list(rec.prompt) + list(rec.delivered)

    # --------------------------------------------------------------- steps
    def has_work(self) -> bool:
        return any(rep.health != "dead" and rep.supervisor.has_work()
                   for rep in self.replicas)

    def step(self) -> List[Tuple[int, int]]:
        """One cluster step: health/hedging maintenance, then one
        engine step per replica with work. Token events come back under
        CONSUMER request ids, deduplicated across hedge copies, in
        replica order. A replica dying mid-step (`EngineDead`) triggers
        migration inline; its salvageable events are delivered first."""
        self._maintenance()
        out: List[Tuple[int, int]] = []
        for rep in self.replicas:
            if rep.health == "dead" or not rep.supervisor.has_work():
                continue
            try:
                events = rep.supervisor.step()
            except EngineDead as e:
                # a post-step escalation (watchdog/fault storm) stashes
                # its already-journaled events on the exception: deliver
                # them before migrating, or they would be marked shown
                # in the journal yet never reach the consumer
                out.extend(self._ingest(
                    getattr(e, "undelivered", None) or []))
                self._on_replica_death(rep, e)
                continue
            out.extend(self._ingest(events))
        return out

    def _ingest(self, events: List[Tuple[int, int]]
                ) -> List[Tuple[int, int]]:
        """Translate engine events to consumer events: alias hedge
        clones back to their consumer id, drop tokens from cancelled
        copies, dedup by absolute stream position (copies of one
        request produce bit-identical streams, so any overlap must
        agree — asserted), and resolve hedge races on the first NEW
        token."""
        out: List[Tuple[int, int]] = []
        now = self._clock()
        for erid, tok in events:
            crid = self._alias.get(erid, erid)
            rec = self._records.get(crid)
            if rec is None:
                # not cluster-placed (someone drove a supervisor
                # directly) — pass through untouched
                out.append((erid, tok))
                continue
            copy = rec.copies.get(erid)
            if copy is None:
                continue              # cancelled loser, late drain
            pos = copy.base + copy.emitted
            copy.emitted += 1
            if rec.status is not None:
                continue              # terminal already; suppress
            if pos < len(rec.delivered):
                if rec.delivered[pos] != tok:
                    raise RuntimeError(
                        f"hedge divergence on request {crid}: position "
                        f"{pos} delivered {rec.delivered[pos]} but "
                        f"replica r{copy.replica} produced {tok}")
                continue              # duplicate from the lagging copy
            rec.delivered.append(tok)
            rec.last_progress_t = now
            out.append((crid, tok))
            if len(rec.copies) > 1:
                self._resolve_hedge(rec, erid)
            if len(rec.delivered) >= rec.max_new_tokens or (
                    rec.eos_token_id is not None
                    and tok == rec.eos_token_id):
                rec.status = "finished"
        return out

    def stream(self) -> Iterable[Tuple[int, int, bool]]:
        """(request_id, token, done) across every replica, exactly-once
        per consumer id — migrations and hedges under the hood never
        duplicate or drop a token."""
        while self.has_work():
            events = self.step()
            for i, (rid, tok) in enumerate(events):
                rec = self._records.get(rid)
                done = (rec is not None and rec.status == "finished"
                        and all(r != rid for r, _ in events[i + 1:]))
                yield rid, tok, done

    def run(self) -> Dict[int, List[int]]:
        """Drive everything to completion; {request_id: prompt+tokens}
        for every request ever placed."""
        for _ in self.stream():
            pass
        for rec in self._records.values():
            self._refresh_status(rec)
        return {rid: self.output(rid) for rid in self._records}

    # ------------------------------------------------------------- health
    def drain(self, index: int) -> None:
        """Stop placing NEW work on a replica; in-flight requests keep
        running to completion (planned maintenance)."""
        rep = self.replicas[index]
        if rep.health == "dead":
            raise ValueError(f"replica r{index} is dead")
        self._set_health(rep, "draining")

    def resume(self, index: int) -> None:
        """Re-enable placement on a draining replica."""
        rep = self.replicas[index]
        if rep.health == "dead":
            raise ValueError(f"replica r{index} is dead")
        if rep.health == "draining":
            self._set_health(rep, "healthy")
            rep.clean_steps = 0
            rep.fault_window.clear()

    def health(self) -> List[str]:
        return [rep.health for rep in self.replicas]

    def _set_health(self, rep: ReplicaHandle, state: str) -> None:
        rep.health = state
        if self._m_health is not None:
            self._m_health[rep.index].set(_HEALTH_CODE[state])

    def _maintenance(self) -> None:
        """Per-step health refresh: supervisor restarts and fault
        bursts degrade a replica; `degrade_recovery_steps` clean steps
        heal it. Draining and dead states are sticky (operator /
        death-path owned). Then the hedge scan, if enabled."""
        self._step_count += 1
        for rep in self.replicas:
            if rep.health == "dead":
                continue
            sup = rep.supervisor
            eng = sup.engine
            if eng is None:
                continue
            restarted = len(sup.restarts) > rep.seen_restarts
            if restarted:
                rep.seen_restarts = len(sup.restarts)
            delta = eng.fault_events - rep.last_fault_events
            rep.last_fault_events = eng.fault_events
            rep.fault_window.append(delta)
            if rep.health == "healthy" and (
                    restarted
                    or sum(rep.fault_window) >= self.degrade_after_faults):
                self._set_health(rep, "degraded")
                rep.clean_steps = 0
            elif restarted or delta:
                rep.clean_steps = 0
            else:
                rep.clean_steps += 1
                if rep.health == "degraded" \
                        and rep.clean_steps >= self.degrade_recovery_steps:
                    self._set_health(rep, "healthy")
                    rep.fault_window.clear()
            if self._m_free_pages is not None:
                self._m_free_pages[rep.index].set(
                    eng.cache.allocator.num_free)
                self._m_queue_depth[rep.index].set(
                    len(eng.scheduler.waiting))
        if self.hedge_after_s is not None:
            self._maybe_hedge()

    # ------------------------------------------------------------ hedging
    def _maybe_hedge(self) -> None:
        now = self._clock()
        for rec in self._records.values():
            if rec.status is not None or len(rec.copies) != 1:
                continue
            (erid, copy), = rec.copies.items()
            owner = self.replicas[copy.replica]
            if owner.health != "degraded":
                continue
            if now - max(rec.placed_t, rec.last_progress_t) \
                    < self.hedge_after_s:
                continue
            targets = [r for r in self.replicas
                       if r.index != owner.index
                       and r.health in ("healthy", "degraded")]
            if not targets:
                continue
            healthy = [r for r in targets if r.health == "healthy"]
            target = min(healthy or targets, key=self._load_score)
            self._hedge(rec, owner, target)

    def _hedge(self, rec: ClusterRequest, owner: ReplicaHandle,
               target: ReplicaHandle) -> None:
        """Clone a stuck request onto `target` as a fold of everything
        delivered so far, under a FRESH engine id aliased back to the
        consumer id. Both copies race; `_ingest` dedups the overlap and
        `_resolve_hedge` cancels the loser on its first lost token."""
        t0 = time.perf_counter()
        eng = target.supervisor.engine
        try:
            clone = eng.adopt_request(
                prompt=rec.prompt, delivered=rec.delivered,
                max_new_tokens=rec.max_new_tokens,
                temperature=rec.temperature, top_k=rec.top_k,
                top_p=rec.top_p, seed=rec.seed,
                eos_token_id=rec.eos_token_id,
                deadline_wall=rec.deadline_wall)
        except ValueError:
            return                     # hedging is best-effort
        rec.copies[clone] = _Copy(replica=target.index,
                                  base=len(rec.delivered))
        self._alias[clone] = rec.request_id
        rec.hedges += 1
        if self._m_hedges is not None:
            self._m_hedges.inc()
        t1 = time.perf_counter()
        add_host_span(
            f"serving.cluster.hedge[{rec.request_id}]"
            f".r{owner.index}->r{target.index}",
            t0, t1, event_type="Hedge")
        add_host_span(
            f"serving.request[{rec.request_id}].replica[r{target.index}]",
            t1, t1, event_type="Lifecycle")

    def _resolve_hedge(self, rec: ClusterRequest, winner: int) -> None:
        """First copy to contribute a NEW stream position wins; every
        other copy is cancelled through its replica (journal terminal
        "cancelled"), so exactly one copy keeps generating and the
        consumer keeps seeing one stream."""
        for erid, copy in list(rec.copies.items()):
            if erid == winner:
                continue
            sup = self.replicas[copy.replica].supervisor
            try:
                sup.cancel(erid)
            except KeyError:
                pass
            del rec.copies[erid]
            if self._m_hedge_cancels is not None:
                self._m_hedge_cancels.inc()
        rec.replica = rec.copies[winner].replica

    # ----------------------------------------------------------- failover
    def _on_replica_death(self, rep: ReplicaHandle,
                          exc: EngineDead) -> None:
        """A supervisor exhausted `max_restarts` mid-step: mark the
        replica dead, enforce `max_dead_replicas`, and migrate every
        journal-live request to the survivors — the dead replica's
        journal is the authoritative record of what each consumer was
        shown, so the fold (prompt + delivered) re-prefills on the
        target and the continuation is bit-identical."""
        self._set_health(rep, "dead")
        self.dead_replicas += 1
        if self._m_deaths is not None:
            self._m_deaths.inc()
        if self.dead_replicas > self.max_dead_replicas:
            raise EngineDead(
                f"cluster lost {self.dead_replicas} replicas "
                f"(max_dead_replicas={self.max_dead_replicas}); "
                f"last straw: r{rep.index}: {exc}",
                reason=exc.reason, restarts=exc.restarts)
        t0 = time.perf_counter()
        migrated = 0
        for jrec in rep.journal.live_records():
            self._migrate_one(rep, jrec.request_id, str(exc))
            migrated += 1
        t1 = time.perf_counter()
        if migrated and self._m_migration_s is not None:
            self._m_migration_s.observe(t1 - t0)
        self._dump_death_postmortem(rep, exc, migrated)

    def _dump_death_postmortem(self, rep: ReplicaHandle, exc: EngineDead,
                               migrated: int) -> None:
        """Finish the dead replica's forensics: the supervisor built its
        bundle BEFORE the migration loop ran, so refresh the event list
        from the (still-alive) ring — which now carries the migrate
        events — fold in the cluster's view, and write the bundle when a
        `postmortem_dir` is configured. Guarded end to end: forensics
        must never turn a survived failover into a crash."""
        try:
            sup = rep.supervisor
            bundle = getattr(sup, "postmortem", None)
            if bundle is None:
                return
            recorder = getattr(sup, "_dead_recorder", None)
            if recorder is not None:
                bundle["events"] = recorder.events()
                bundle["events_total"] = recorder.total_recorded
            bundle.setdefault("info", {})["cluster"] = {
                "replica": rep.index,
                "dead_replicas": self.dead_replicas,
                "migrated": migrated,
                "error": str(exc),
            }
            if self.postmortem_dir is not None:
                from ..observability import dump_postmortem

                path = dump_postmortem(
                    bundle, self.postmortem_dir,
                    prefix=f"postmortem-r{rep.index}")
                self.postmortem_paths.append(path)
                sup.postmortem_path = path
        except Exception:  # noqa: BLE001 — forensics must not kill failover
            pass

    def _migrate_one(self, rep: ReplicaHandle, erid: int,
                     reason: str) -> None:
        t0 = time.perf_counter()
        journal = rep.journal
        crid = self._alias.get(erid, erid)
        rec = self._records.get(crid)
        if rec is None:
            # not cluster-placed; nothing to migrate it into
            journal.terminal(erid, "failed",
                             error=f"replica r{rep.index} died: {reason}")
            return
        copy = rec.copies.pop(erid, None)
        if copy is None:
            # a hedge loser already cancelled at cluster level; close
            # the dead journal's record to match
            journal.terminal(erid, "cancelled")
            return
        if rec.status is not None:
            journal.terminal(
                erid,
                rec.status if rec.status in TERMINAL_STATUSES
                else "failed",
                error=rec.error)
            return
        if rec.copies:
            # a live hedge copy survives elsewhere — it owns the
            # stream now; nothing to re-admit
            journal.terminal(erid, "failed",
                             error=f"replica r{rep.index} died; hedge "
                                   f"copy survives on r{rec.replica}")
            rec.replica = next(iter(rec.copies.values())).replica
            return
        if len(rec.delivered) >= rec.max_new_tokens or (
                rec.eos_token_id is not None and rec.delivered
                and rec.delivered[-1] == rec.eos_token_id):
            # everything was delivered; only the finish record died
            # with the replica — reconstruct, never recompute
            rec.status = "finished"
            journal.terminal(erid, "finished")
            return
        targets = [r for r in self.replicas
                   if r.health in ("healthy", "degraded")]
        if not targets:
            rec.status, rec.error = "failed", (
                f"replica r{rep.index} died with no surviving replica "
                "to migrate to")
            journal.terminal(erid, "failed", error=rec.error)
            return
        healthy = [r for r in targets if r.health == "healthy"]
        target = min(healthy or targets, key=self._load_score)
        new_rid = self._adopt_on(target, rec, crid)
        if new_rid is None:
            journal.terminal(erid, "failed", error=rec.error)
            return
        journal.terminal(
            erid, "failed",
            error=f"replica r{rep.index} died ({reason}); migrated to "
                  f"r{target.index} as request {new_rid}")
        rec.migrations += 1
        if self._m_migrations is not None:
            self._m_migrations.inc()
            self._m_migrated_tokens.inc(
                len(rec.prompt) + len(rec.delivered))
        if self.prefix_affinity:
            self._note_affinity(rec.prompt, target.index)
        recorder = getattr(rep.supervisor, "_dead_recorder", None)
        if recorder is not None:
            # append to the DEAD replica's ring: its post-mortem bundle
            # then shows the fatal fault, the death, and where every
            # casualty went — the full story in one timeline
            recorder.record("migrate", rid=crid, src=rep.index,
                            dst=target.index, new_rid=new_rid,
                            delivered=len(rec.delivered))
        t1 = time.perf_counter()
        add_host_span(
            f"serving.cluster.migrate[{crid}]"
            f".r{rep.index}->r{target.index}",
            t0, t1, event_type="Migration")
        add_host_span(
            f"serving.request[{crid}].replica[r{target.index}]",
            t1, t1, event_type="Lifecycle")

    def _adopt_on(self, target: ReplicaHandle, rec: ClusterRequest,
                  crid: int) -> Optional[int]:
        """Re-admit `rec` on `target` under its consumer id (or a fresh
        alias if the target's journal somehow already knows the id),
        registering the FULL history (original prompt + delivered,
        split count 0) in the target's journal first — so if the target
        later dies too, the next migration folds from the same
        authoritative record."""
        from .recovery import RequestRecord

        tsup = target.supervisor
        rid_for_adopt: Optional[int] = crid
        if tsup.journal.known(crid):
            rid_for_adopt = None       # re-mint + alias, never collide
        elif tsup.journal is not None:
            tsup.journal.adopt(RequestRecord(
                request_id=crid, prompt=list(rec.prompt),
                max_new_tokens=rec.max_new_tokens,
                temperature=rec.temperature, top_k=rec.top_k,
                top_p=rec.top_p, seed=rec.seed,
                eos_token_id=rec.eos_token_id,
                deadline_wall=rec.deadline_wall,
                arrival_wall=rec.arrival_wall,
                delivered=list(rec.delivered)))
        try:
            new_rid = tsup.engine.adopt_request(
                prompt=rec.prompt, delivered=rec.delivered,
                max_new_tokens=rec.max_new_tokens,
                temperature=rec.temperature, top_k=rec.top_k,
                top_p=rec.top_p, seed=rec.seed,
                eos_token_id=rec.eos_token_id,
                deadline_wall=rec.deadline_wall,
                request_id=rid_for_adopt)
        except ValueError as e:
            rec.status, rec.error = "failed", (
                f"migration to r{target.index} rejected: {e}")
            rec.copies = {}
            return None
        st, err = tsup.engine.status(new_rid)
        if st in TERMINAL_STATUSES:
            # expired during the outage (deadline_wall in the past):
            # terminal on arrival, never resurrected
            rec.status, rec.error = st, err
            rec.copies = {}
            return new_rid
        rec.copies = {new_rid: _Copy(replica=target.index,
                                     base=len(rec.delivered))}
        rec.replica = target.index
        if new_rid != crid:
            self._alias[new_rid] = crid
        return new_rid

    # -------------------------------------------------------- diagnostics
    def check_consistency(self) -> bool:
        """Cluster invariant audit: every live replica's scheduler (and
        prefix cache / allocator, transitively), every journal, plus
        the cluster's own tables — aliases resolve, every LIVE
        request's copies sit on non-dead replicas that know them (a
        terminal request's copy entries are history: the replica that
        finished a request is allowed to die afterwards), delivered
        streams fit their budgets. Raises RuntimeError on the first
        violation."""
        for rep in self.replicas:
            if rep.health != "dead" and rep.supervisor.engine is not None:
                rep.supervisor.engine.scheduler.check_consistency()
            rep.journal.check_consistency()
        for erid, crid in self._alias.items():
            if crid not in self._records:
                raise RuntimeError(
                    f"cluster corrupt: alias {erid}->{crid} points at "
                    "an unknown request")
        for crid, rec in self._records.items():
            if len(rec.delivered) > rec.max_new_tokens:
                raise RuntimeError(
                    f"cluster corrupt: request {crid} delivered "
                    f"{len(rec.delivered)} tokens over its budget "
                    f"{rec.max_new_tokens}")
            if rec.status is not None:
                continue
            for erid, copy in rec.copies.items():
                rep = self.replicas[copy.replica]
                if rep.health == "dead":
                    raise RuntimeError(
                        f"cluster corrupt: request {crid} holds a copy "
                        f"on dead replica r{copy.replica}")
                if self._alias.get(erid, erid) != crid:
                    raise RuntimeError(
                        f"cluster corrupt: copy {erid} of request "
                        f"{crid} does not alias back to it")
                eng = rep.supervisor.engine
                if eng is not None and erid not in eng.requests \
                        and not rep.journal.known(erid):
                    raise RuntimeError(
                        f"cluster corrupt: copy {erid} of request "
                        f"{crid} unknown to replica r{copy.replica}")
        return True

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, object]:
        """Cluster roll-up: per-replica health + engine/supervisor
        stats, router and failover counters, and a per-request summary
        (status / owner / delivered / migrations / hedges)."""
        terminal: Dict[str, int] = {}
        live = 0
        requests: Dict[int, dict] = {}
        for crid, rec in self._records.items():
            if rec.status is None:
                live += 1
            else:
                terminal[rec.status] = terminal.get(rec.status, 0) + 1
            requests[crid] = {
                "status": rec.status if rec.status is not None else "live",
                "replica": rec.replica,
                "tokens": len(rec.delivered),
                "migrations": rec.migrations,
                "hedges": rec.hedges,
            }

        def counter(c):
            return int(c.value) if c is not None else 0

        return {
            "num_replicas": self.num_replicas,
            "dead_replicas": self.dead_replicas,
            "health": self.health(),
            "placement": self.placement,
            "prefix_affinity": self.prefix_affinity,
            "num_requests": len(self._records),
            "num_finished": terminal.get("finished", 0),
            "num_live": live,
            "terminal": terminal,
            "router": {
                "routed": [counter(c) for c in (self._m_routed or [])],
                "affinity_hits": counter(self._m_aff_hit),
                "affinity_misses": counter(self._m_aff_miss),
                "spillovers": counter(self._m_spill),
                "shed": counter(self._m_shed),
                "affinity_table": len(self._affinity),
            },
            "migrations": counter(self._m_migrations),
            "migrated_tokens": counter(self._m_migrated_tokens),
            "hedges": counter(self._m_hedges),
            "hedge_cancels": counter(self._m_hedge_cancels),
            "replica_deaths": counter(self._m_deaths),
            "replicas": [
                {"index": rep.index, "health": rep.health,
                 "stats": rep.supervisor.stats()}
                for rep in self.replicas],
            "requests": requests,
        }

    def telemetry(self) -> Dict[str, object]:
        """One cluster-wide metric view (ISSUE 13): every live replica's
        engine registry merged with the cluster's own registry into a
        single replica-labelled snapshot plus its Prometheus text
        exposition — the scrape endpoint a deployment exports, instead
        of N per-replica registries.

        Every merged series gains a ``replica`` label: the engine
        registries are tagged with their replica index, the cluster
        registry with ``cluster``. ``setdefault`` (never overwrite)
        keeps the cluster's own per-replica gauges — which already
        carry a ``replica`` label — intact. Engines that share the
        cluster registry (``metrics=cluster.metrics`` factories) are
        skipped so their series never double-count."""
        from ..observability import registry_from_snapshot, to_prometheus

        merged: List[dict] = []

        def fold(registry, tag: str) -> None:
            for d in registry.snapshot()["metrics"]:
                d = dict(d)
                labels = dict(d.get("labels") or {})
                labels.setdefault("replica", tag)
                d["labels"] = labels
                merged.append(d)

        if self.metrics is not None:
            fold(self.metrics, "cluster")
        for rep in self.replicas:
            eng = rep.supervisor.engine
            if eng is None or eng.metrics is None \
                    or eng.metrics is self.metrics:
                continue
            fold(eng.metrics, str(rep.index))
        registry = registry_from_snapshot({"metrics": merged})
        return {
            "replicas": [
                {"index": rep.index, "health": rep.health,
                 "alive": rep.supervisor.engine is not None,
                 "restarts": len(rep.supervisor.restarts),
                 "postmortem": rep.supervisor.postmortem_path}
                for rep in self.replicas],
            "dead_replicas": self.dead_replicas,
            "metrics": registry.snapshot(),
            "prometheus": to_prometheus(registry),
            "postmortems": list(self.postmortem_paths),
        }

    def close(self) -> None:
        for rep in self.replicas:
            rep.journal.close()
