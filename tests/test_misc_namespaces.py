"""Round-3 namespace additions: paddle.signal, paddle.hub, paddle.onnx,
iinfo/finfo, paddle.flops, paddle.autocast alias, incubate.optimizer
(LookAhead / ModelAverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import signal


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestSignal:
    def test_frame_overlap_add_roundtrip_identity_hop(self, rng):
        x = rng.standard_normal(32).astype(np.float32)
        f = signal.frame(_t(x), frame_length=8, hop_length=8)
        assert tuple(f.shape) == (8, 4)
        back = signal.overlap_add(f, hop_length=8)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)

    def test_frame_batched_last_axis(self, rng):
        x = rng.standard_normal((3, 32)).astype(np.float32)
        f = signal.frame(_t(x), 16, 4)
        assert tuple(f.shape) == (3, 16, 5)

    def test_overlap_add_overlapping_sums(self):
        frames = np.ones((4, 3), np.float32)  # frame_length 4, 3 frames
        out = signal.overlap_add(_t(frames), hop_length=2)
        # length = 2*2+4 = 8; middle samples overlap twice
        np.testing.assert_allclose(out.numpy(),
                                   [1, 1, 2, 2, 2, 2, 1, 1])

    def test_stft_matches_scipy(self, rng):
        import scipy.signal as ss
        x = rng.standard_normal(512).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        got = signal.stft(_t(x), n_fft=128, hop_length=32,
                          window=_t(win), center=False).numpy()
        _, _, ref = ss.stft(x, window=win, nperseg=128, noverlap=96,
                            boundary=None, padded=False)
        # scipy normalizes by win.sum(); undo for raw comparison
        ref = ref * win.sum()
        np.testing.assert_allclose(got, ref, atol=1e-3)

    def test_stft_istft_roundtrip(self, rng):
        x = rng.standard_normal((2, 400)).astype(np.float32)
        win = _t(np.hanning(100).astype(np.float32))
        spec = signal.stft(_t(x), n_fft=100, hop_length=25, window=win)
        back = signal.istft(spec, n_fft=100, hop_length=25, window=win,
                            length=400)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-4)

    def test_onesided_complex_input_raises(self):
        x = _t(np.ones(64, np.complex64))
        with pytest.raises(ValueError):
            signal.stft(x, n_fft=16)

    def test_stft_too_short_raises(self):
        with pytest.raises(ValueError, match="n_fft"):
            signal.stft(_t(np.ones(50, np.float32)), n_fft=64, center=False)

    def test_istft_onesided_return_complex_raises(self):
        spec = signal.stft(_t(np.ones(256, np.float32)), n_fft=64)
        with pytest.raises(ValueError, match="return_complex"):
            signal.istft(spec, n_fft=64, return_complex=True)


class TestHub:
    def test_local_hubconf(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_model(scale=1):\n"
            "    '''a tiny model entrypoint'''\n"
            "    return {'scale': scale}\n")
        names = paddle.hub.list(str(tmp_path))
        assert "tiny_model" in names
        assert "tiny" in paddle.hub.help(str(tmp_path), "tiny_model")
        out = paddle.hub.load(str(tmp_path), "tiny_model", scale=3)
        assert out == {"scale": 3}

    def test_remote_source_raises(self):
        with pytest.raises(RuntimeError, match="network"):
            paddle.hub.list("user/repo", source="github")

    def test_missing_entry_raises(self, tmp_path):
        (tmp_path / "hubconf.py").write_text("x = 1\n")
        with pytest.raises(ValueError):
            paddle.hub.load(str(tmp_path), "nope")


class TestOnnx:
    def test_export_requires_input_spec(self):
        # round 4: paddle.onnx.export is a real native exporter (see
        # tests/test_onnx_export.py); the missing-spec error is loud
        with pytest.raises(ValueError, match="input_spec"):
            paddle.onnx.export(None, "model.onnx")


class TestDtypeInfo:
    def test_iinfo(self):
        i = paddle.iinfo("int16")
        assert (i.min, i.max, i.bits) == (-32768, 32767, 16)

    def test_finfo_float32(self):
        f = paddle.finfo(paddle.float32)
        np.testing.assert_allclose(f.eps, np.finfo(np.float32).eps)
        assert f.bits == 32

    def test_finfo_bfloat16(self):
        f = paddle.finfo("bfloat16")
        assert f.bits == 16
        assert f.eps == 0.0078125
        assert f.max > 3e38


class TestFlops:
    def test_linear_flops_exact(self):
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)

            def forward(self, x):
                return self.fc(x)

        n = paddle.flops(M(), input_size=(1, 8))
        assert n == 4 * (2 * 8 - 1 + 1)  # out*(2*in-1+bias)

    def test_conv_transpose_counted(self):
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.up = nn.Conv2DTranspose(4, 2, 3, padding=1)

            def forward(self, x):
                return self.up(x)

        n = paddle.flops(M(), input_size=(1, 4, 8, 8))
        assert n > 0  # regression: transpose convs used to count 0

    def test_autocast_alias(self):
        assert paddle.autocast is paddle.amp.auto_cast


class TestIncubateOptimizers:
    def _setup(self):
        import paddle_tpu.nn as nn
        net = nn.Linear(4, 2)
        x = _t(np.random.RandomState(0).standard_normal((8, 4))
               .astype(np.float32))
        y = _t(np.random.RandomState(1).standard_normal((8, 2))
               .astype(np.float32))

        def loss_fn():
            import paddle_tpu.nn.functional as F
            return F.mse_loss(net(x), y)
        return net, loss_fn

    def test_lookahead_converges_and_syncs(self):
        from paddle_tpu.incubate import LookAhead
        net, loss_fn = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters())
        opt = LookAhead(inner, alpha=0.5, k=3)
        losses = []
        for _ in range(9):
            loss = loss_fn()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]
        # after a sync step the slow copies equal the live weights
        w = net.weight._data
        slow = opt._slow[id(net.weight)]
        np.testing.assert_allclose(np.asarray(w), np.asarray(slow))

    def test_lookahead_validates_args(self):
        net, _ = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters())
        from paddle_tpu.incubate import LookAhead
        with pytest.raises(ValueError):
            LookAhead(inner, alpha=1.5)
        with pytest.raises(ValueError):
            LookAhead(inner, k=0)

    def test_model_average_double_apply_raises(self):
        from paddle_tpu.incubate import ModelAverage
        net, loss_fn = self._setup()
        avg = ModelAverage(0.5, parameters=net.parameters(),
                           min_average_window=100)
        avg.step()
        avg.apply()
        with pytest.raises(RuntimeError, match="restore"):
            avg.apply()
        avg.restore()

    def test_lookahead_state_roundtrip(self):
        from paddle_tpu.incubate import LookAhead
        net, loss_fn = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters())
        opt = LookAhead(inner, k=2)
        for _ in range(2):
            loss = loss_fn()
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        assert sd["step"] == 2 and sd["slow"]
        inner2 = paddle.optimizer.SGD(learning_rate=0.1,
                                      parameters=net.parameters())
        opt2 = LookAhead(inner2, k=2)
        opt2.set_state_dict(sd)
        assert opt2._step_count == 2
        assert len(opt2._slow) == len(sd["slow"])

    def test_model_average_apply_restore(self):
        from paddle_tpu.incubate import ModelAverage
        net, loss_fn = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.5,
                                     parameters=net.parameters())
        # window large enough that no restart happens within the 4 steps
        avg = ModelAverage(0.5, parameters=net.parameters(),
                           min_average_window=100, max_average_window=100)
        seen = []
        for _ in range(4):
            loss = loss_fn()
            loss.backward()
            inner.step()
            inner.clear_grad()
            avg.step()
            seen.append(np.asarray(net.weight._data).copy())
        live = np.asarray(net.weight._data).copy()
        avg.apply()
        applied = np.asarray(net.weight._data)
        np.testing.assert_allclose(applied, np.mean(seen, axis=0),
                                   rtol=1e-5)
        assert not np.allclose(applied, live)
        avg.restore()
        np.testing.assert_allclose(np.asarray(net.weight._data), live)


class TestASP:
    """incubate.asp 2:4 automatic sparsity (round 3)."""

    def _net(self):
        import paddle_tpu.nn as nn
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                             nn.Linear(16, 4))

    def test_prune_gives_2_4_density(self):
        from paddle_tpu.incubate import asp
        net = self._net()
        masks = asp.prune_model(net)
        assert masks  # both Linear weights pruned
        for name in masks:
            p = dict(net.named_parameters())[name]
            assert abs(asp.calculate_density(p) - 0.5) < 1e-6
            # every group of 4 along the input axis keeps exactly 2
            m = masks[name]
            groups = np.moveaxis(m, 0, -1).reshape(-1, 4)
            assert (groups.sum(axis=1) == 2).all()

    def test_masks_held_through_training(self):
        from paddle_tpu.incubate import asp
        import paddle_tpu.nn.functional as F
        net = self._net()
        asp.prune_model(net)
        opt = asp.decorate(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=net.parameters()))
        r = np.random.RandomState(0)
        x = _t(r.standard_normal((16, 8)).astype(np.float32))
        y = _t(r.standard_normal((16, 4)).astype(np.float32))
        losses = []
        for _ in range(6):
            loss = F.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]  # still learns at 50% density
        for _, p in net.named_parameters():
            if p.ndim >= 2:
                assert abs(asp.calculate_density(p) - 0.5) < 1e-6

    def test_excluded_layers(self):
        from paddle_tpu.incubate import asp
        net = self._net()
        name0 = next(n for n, _ in net.named_parameters()
                     if n.endswith("0.weight"))
        asp.set_excluded_layers([name0])
        try:
            masks = asp.prune_model(net)
            assert name0 not in masks
        finally:
            asp.reset_excluded_layers()

    def test_custom_nm_pattern(self):
        from paddle_tpu.incubate import asp
        import paddle_tpu.nn as nn
        net = nn.Sequential(nn.Linear(6, 4))  # 6 % 2 == 0 only for m=2
        masks = asp.prune_model(net, n=1, m=2)
        assert masks
        p = dict(net.named_parameters())["0.weight"]
        assert abs(asp.calculate_density(p) - 0.5) < 1e-6

    def test_biases_untouched(self):
        from paddle_tpu.incubate import asp
        net = self._net()
        masks = asp.prune_model(net)
        assert not any(k.endswith("bias") for k in masks)


class TestIncubateFunctional:
    """incubate.nn.functional fused-op surface (round 3)."""

    def _data(self):
        r = np.random.RandomState(0)
        x = _t(r.standard_normal((2, 6, 16)).astype(np.float32))
        g = _t(np.ones(16, np.float32))
        b = _t(np.zeros(16, np.float32))
        return r, x, g, b

    def test_fused_feedforward_matches_composition(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.nn import functional as IF
        r, x, g, b = self._data()
        w1 = _t(r.standard_normal((16, 32)).astype(np.float32))
        w2 = _t(r.standard_normal((32, 16)).astype(np.float32))
        out = IF.fused_feedforward(x, w1, w2, ln2_scale=g, ln2_bias=b,
                                   dropout1_rate=0.0, dropout2_rate=0.0)
        ref = F.layer_norm(x + F.linear(F.relu(F.linear(x, w1)), w2),
                           [16], g, b)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_fused_mha_runs_and_matches_manual(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.nn import functional as IF
        r, x, g, b = self._data()
        qkvw = _t(r.standard_normal((3, 4, 4, 16)).astype(np.float32))
        lw = _t(r.standard_normal((16, 16)).astype(np.float32))
        out = IF.fused_multi_head_attention(
            x, qkvw, lw, ln_scale=g, ln_bias=b, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        # manual composition
        qkv = x.matmul(_t(qkvw.numpy().reshape(48, 16)), transpose_y=True)
        qkv = qkv.reshape([2, 6, 3, 4, 4])
        ctx = F.scaled_dot_product_attention(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], training=False)
        ref = F.layer_norm(x + F.linear(ctx.reshape([2, 6, 16]), lw),
                           [16], g, b)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_fused_layer_norm_begin_axis(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.nn import functional as IF
        r, x, g, b = self._data()
        out = IF.fused_layer_norm(x, g, b, begin_norm_axis=2)
        ref = F.layer_norm(x, [16], g, b)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_fused_mha_rejects_unsupported(self):
        from paddle_tpu.incubate.nn import functional as IF
        r, x, g, b = self._data()
        qkvw = _t(r.standard_normal((3, 4, 4, 16)).astype(np.float32))
        lw = _t(r.standard_normal((16, 16)).astype(np.float32))
        with pytest.raises(NotImplementedError, match="cache_kv"):
            IF.fused_multi_head_attention(x, qkvw, lw, cache_kv=x)
        with pytest.raises(NotImplementedError, match="ring_id"):
            IF.fused_multi_head_attention(x, qkvw, lw, ring_id=0)

    def test_fused_linear_and_bdrln(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.nn import functional as IF
        r, x, g, b = self._data()
        w = _t(r.standard_normal((16, 8)).astype(np.float32))
        np.testing.assert_allclose(
            IF.fused_linear(x, w).numpy(), F.linear(x, w).numpy(),
            atol=1e-6)
        wt = _t(w.numpy().T)
        np.testing.assert_allclose(
            IF.fused_linear(x, wt, transpose_weight=True).numpy(),
            F.linear(x, w).numpy(), atol=1e-6)
        out = IF.fused_bias_dropout_residual_layer_norm(
            x, x, ln_scale=g, ln_bias=b, dropout_rate=0.0)
        ref = F.layer_norm(x + x, [16], g, b)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)
