"""paddle.io analog: Dataset/DataLoader/samplers.

Ref: python/paddle/io/dataloader/ (upstream layout, unverified — mount
empty). The loader is host-side numpy: workers (threads) prefetch and collate
batches; device transfer happens once per batch. DistributedBatchSampler
keeps paddle's shuffle-seed/epoch contract so per-rank shards are
reproducible (SURVEY.md §7 hard-part 5).
"""
from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        for i, c in enumerate(self.cum):
            if idx < c:
                prev = self.cum[i - 1] if i else 0
                return self.datasets[i][idx - prev]
        raise IndexError(idx)


class ComposeDataset(Dataset):
    """Zip-style composition: sample i is the flattened concatenation of
    each dataset's sample i (paddle.io.ComposeDataset semantics)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets must be non-empty")
        lens = {len(d) for d in self.datasets}
        if len(lens) != 1:
            raise ValueError("all datasets must have the same length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, (list, tuple)):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(math.floor(total * l)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    from ..core.rng import next_key
    import jax

    perm = np.asarray(jax.random.permutation(next_key(), total))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


# ------------------------------------------------------------------ samplers
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class SubsetRandomSampler(Sampler):
    """Random permutation over a fixed index subset."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        order = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            idx = np.random.randint(0, n, size=self.num_samples)
        else:
            idx = np.random.permutation(n)[:self.num_samples]
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharding with paddle's epoch/seed shuffle contract:
    np.random.RandomState(epoch) permutes the global index list; each rank
    takes its contiguous slice after padding to a multiple of world size."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as dist_env

            num_replicas = num_replicas if num_replicas is not None else \
                dist_env.get_world_size()
            rank = rank if rank is not None else dist_env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = indices.tolist()
        # pad to make evenly divisible
        indices += indices[:(self.total_size - n)]
        assert len(indices) == self.total_size
        local = indices[self.local_rank * self.num_samples:
                        (self.local_rank + 1) * self.num_samples]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# ---------------------------------------------------------------- collation
def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch])
                for k in sample}
    raise TypeError(f"cannot collate batch of {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        # thread-based prefetch pipeline (the multi-process worker pool of
        # the reference maps poorly to TPU hosts; threads keep the loader
        # overlap without pickling costs)
        q: "queue.Queue" = queue.Queue(
            maxsize=self.prefetch_factor * max(self.num_workers, 1))
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item


def get_worker_info():
    return None
