"""paddle.hapi — high-level API (Model.fit / callbacks / summary).

Ref: python/paddle/hapi/ (upstream layout, unverified — mount empty).
"""
from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
from .summary import flops, summary  # noqa: F401
