"""ServingEngine: continuous-batching generation over a paged KV cache.

Multiplexes an arbitrary request stream onto a decoder model with a
BOUNDED set of compiled programs (T3's rule: every hot-loop step is one
jitted dispatch):

- one prefill executable per prompt bucket (prompt padded up to the
  bucket; one request per prefill step) — plus, when
  `enable_prefix_caching=True`, ONE offset-aware variant per bucket that
  prefills only the suffix left uncovered by the radix prefix cache
  (shared pages ride in through the page table, see prefix_cache.py).
  Sampling is fused into the prefill executable (per-row PRNG key state
  rides in as device key data);
- ONE fused decode+sample executable per decode horizon: a
  `decode_horizon=N` block runs N decode iterations inside one jitted
  `lax.scan` — model step, sampling (traced per-row temperature/top-k/
  top-p, device PRNG key state), EOS/budget masking, and position
  advance through the page table all on device — and returns an (b, N)
  token block. Rows that finish mid-block emit PAD and park their write
  position at the table-overflow slot (routed to the null page), so the
  host syncs ONCE per N tokens instead of once per token;
- async host/device overlap: the engine dispatches block k+1 (inputs
  taken straight from block k's device-resident carries) BEFORE pulling
  block k's tokens to the host, so Python bookkeeping and scheduling
  run while the device computes. The scheduler reserves each block's
  pages up front (`_ensure_decode_pages` with in-flight upper bounds)
  and drains the pipeline before any preemption, keeping emitted
  streams token-identical to `decode_horizon=1`;
- chunked prefill (`enable_chunked_prefill=True`, Sarathi-Serve style):
  prompts run in page-aligned chunks of `prefill_chunk_tokens` (default
  256), co-scheduled with the step's decode block under a
  `max_num_batched_tokens` budget, so a long prompt never stalls the
  running decoders for a full bucket-padded forward pass. Each chunk is
  a prefill at a TRACED start offset with a TRACED valid length, so the
  whole per-bucket `prefill`/`prefill_offset` executable family
  collapses into ONE `prefill_chunked` executable for every prompt
  length, and padding waste is capped at one chunk (the prompt's final
  one) instead of up-to-2x of a power-of-two bucket. Intermediate
  chunks never sync the host and leave the per-request PRNG state
  untouched (one key split per EMITTED token), so token streams stay
  bit-identical to the unchunked engine.

The engine talks to any decoder model that follows the
`forward(input_ids, caches=..., start_pos=...)` cache protocol of
models/generation.py (LLaMA, GPT); the per-layer cache objects it passes
are `PagedLayerCache` views, which `attend_with_cache` dispatches to the
ragged paged attention op.

Observability (ISSUE 4): every counter lives in ONE
paddle_tpu.observability MetricsRegistry per engine — `stats()` and
`compile_counts()` are thin views over it, `ServingObs` resolves all
handles once at construction so the hot path never looks anything up,
and `enable_metrics=False` removes even that (a None check per site).
On top of the batch-level RecordEvent spans ("serving.prefill" /
"serving.decode_block" / "serving.host_drain"), a LifecycleTracker
emits per-request spans (`serving.request[<rid>].<stage>` for
enqueued/admitted/prefill/first_token/decode_block/preempted/requeued/
finished) into the profiler's chrome-trace host tracer, and TTFT /
inter-token latency histograms back `stats()["latency"]`'s p50/p95/p99.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit.functional import call_functional, extract_state
from ..observability import Histogram, LifecycleTracker, MetricsRegistry
from ..observability.flight_recorder import (
    build_postmortem as _build_bundle, dump_postmortem as _dump_bundle)
from ..observability.slo import SloTracker
from ..profiler import RecordEvent
from .attention import advance_positions
from .kv_cache import (PagedKVCache, PagedLayerCache, overflow_position,
                       pages_for, pools_from_views, views_from_pools)
from .prefix_cache import PrefixCache
from .ragged import build_ragged_inputs
from .ragged import token_buckets as ragged_token_buckets
from .recovery import EngineSnapshot, RequestSnapshot, replay_key_state
from .resilience import (TERMINAL_STATUSES, describe_fault, is_fatal,
                         is_transient)
from .scheduler import (Request, SamplingParams, Scheduler,
                        reserve_request_ids)

__all__ = ["ServingEngine", "ServingObs", "PAD_TOKEN"]

# emitted by dead rows inside a decode block (finished / padding); the
# host drain trims each row at its first PAD
PAD_TOKEN = -1


def _default_buckets(max_seq_len: int) -> Tuple[int, ...]:
    """Power-of-two prompt buckets up to max_seq_len (always included):
    a handful of prefill compilations covers every prompt length."""
    buckets = []
    b = 16
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq_len)
    return tuple(buckets)


def _sample_batch(logits, keys, temps, top_ks, top_ps):
    """Per-row sampling with TRACED knobs (the batch mixes requests with
    different sampling params). Mirrors generation._sample row-wise:
    greedy where temperature == 0, else temperature -> top-k -> top-p ->
    categorical."""
    vocab = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)
    t_safe = jnp.where(temps > 0.0, temps, 1.0)
    scaled = logits / t_safe[:, None]
    # top-k as a rank threshold (top_k <= 0 disables by keeping all V)
    k_eff = jnp.where(top_ks > 0, jnp.minimum(top_ks, vocab), vocab)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    masked = jnp.where(scaled < kth, -jnp.inf, scaled)
    # top-p over the top-k-masked distribution (generation._sample order)
    sorted_m = jnp.sort(masked, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_m, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.minimum(
        jnp.sum(cum < top_ps[:, None], axis=-1, keepdims=True), vocab - 1)
    cutoff = jnp.take_along_axis(sorted_m, cutoff_idx, axis=-1)
    masked = jnp.where(masked < cutoff, -jnp.inf, masked)
    sampled = jax.vmap(jax.random.categorical)(keys, masked)
    return jnp.where(temps == 0.0, greedy, sampled)


def _split_rows(key_data):
    """One split per row, entirely on device: key_data (b, 2) uint32 ->
    (new key_data, sample keys). Bit-identical to the host-side
    `jax.random.split` chain the pre-horizon sampler ran per token."""
    keys = jax.random.wrap_key_data(key_data)
    pair = jax.vmap(jax.random.split)(keys)
    return jax.random.key_data(pair[:, 0]), pair[:, 1]


class ServingObs:
    """Every observability handle the serving hot path touches, resolved
    ONCE against the engine's MetricsRegistry (metric name lookups never
    run per step), plus the per-request LifecycleTracker. The scheduler
    receives this same object and calls the small hooks below at queue
    transitions; with `enable_metrics=False` the engine passes None
    everywhere and the hot path does literally no metrics work
    (tests/test_serving.py pins that with a raise-on-touch guard)."""

    FAMILIES = ("prefill", "prefill_offset", "prefill_chunked", "decode",
                "ragged", "spec", "sample")

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.lifecycle = LifecycleTracker()
        c, g, h = registry.counter, registry.gauge, registry.histogram
        self.prefill_steps = c("serving_prefill_steps_total",
                               "prefill dispatches")
        self.prefill_chunks = c("serving_prefill_chunks_total",
                                "chunked-prefill chunk dispatches")
        self.decode_steps = c("serving_decode_steps_total",
                              "fused decode-block dispatches")
        self.ragged_steps = c("serving_ragged_steps_total",
                              "flat ragged mixed-step dispatches (one "
                              "executable carrying the step's decode "
                              "rows AND prefill chunks)")
        self.tokens = c("serving_tokens_generated_total",
                        "tokens emitted to the host")
        self.host_syncs = c("serving_host_syncs_total",
                            "device->host sync points")
        self.dispatches = c("serving_dispatches_total",
                            "device program launches of any family "
                            "(prefill, chunk, decode block, or ragged "
                            "step) — the per-step launch cost the "
                            "ragged executable collapses to one")
        self.preemptions = c("serving_preemptions_total",
                             "requests preempted and requeued")
        self.prefill_seconds = c("serving_prefill_seconds_total",
                                 "wall time in prefill dispatch+sync")
        self.decode_seconds = c(
            "serving_decode_seconds_total",
            "decode wall time (async-overlap deduplicated)")
        self.compile_miss = {
            fam: c("serving_jit_compile_misses_total",
                   "distinct executables per step family "
                   "(this engine's jit-cache misses)",
                   labels={"family": fam})
            for fam in self.FAMILIES}
        self.ttft = h("serving_ttft_seconds",
                      "request arrival to first token on the host")
        self.inter_token = h(
            "serving_inter_token_seconds",
            "per-token gap between host-visible emissions (a decode "
            "block's gap is spread evenly over its tokens)")
        # the head-of-line metric chunked prefill exists to shrink: the
        # wall gap between consecutive decode-block DISPATCHES while at
        # least one request is running — an unchunked engine shows a
        # full bucket-padded prefill here whenever a prompt arrives
        # mid-decode, a chunked one at most ~one chunk's compute
        self.decode_stall = h(
            "serving_decode_stall_seconds",
            "gap between consecutive decode-block dispatches while "
            "requests are running")
        # resilience counters (ISSUE 6): one labelled series per
        # non-finished terminal status, plus retry/park events
        self.terminated = {
            status: c("serving_requests_terminated_total",
                      "requests reaching a non-finished terminal status",
                      labels={"status": status})
            for status in ("cancelled", "expired", "failed", "shed")}
        self.retries = c("serving_transient_retries_total",
                         "dispatch/drain sites retried after a "
                         "transient fault")
        self.parked_total = c("serving_requests_parked_total",
                              "preemption-storm guard trips (victim "
                              "requeued at the back of the queue)")
        # step-phase breakdown (ISSUE 13): wall time per step split into
        # schedule (policy + page reservation), assemble (host-side batch
        # packing: buckets, tables, padding), dispatch (jitted launch
        # until control returns to the host — async, so this is NOT
        # device time) and drain (the ONE host sync pulling tokens back).
        # device_residency estimates device occupancy as dispatch-time to
        # drain-time of the same block — the denominator ROADMAP 5's
        # overlap fraction needs.
        self.step_phase = {
            phase: h("serving_step_phase_seconds",
                     "per-step wall time by phase (schedule / assemble "
                     "/ dispatch / drain)", labels={"phase": phase})
            for phase in ("schedule", "assemble", "dispatch", "drain")}
        self.device_residency = h(
            "serving_device_residency_seconds",
            "dispatch-to-drain wall per block: how long work was "
            "resident on the device side of the async overlap")
        self.queue_waiting = g("serving_queue_depth",
                               "scheduler queue depth",
                               labels={"state": "waiting"})
        self.queue_running = g("serving_queue_depth",
                               "scheduler queue depth",
                               labels={"state": "running"})
        self.free_pages = g("serving_kv_free_pages",
                            "allocatable KV pages right now")
        self.kv_util = g("serving_kv_page_utilization",
                         "fraction of allocatable KV pages in use")
        # tensor-parallel handles, bound by bind_tp() only when the
        # engine runs with tp_size>1 — None means zero TP metrics work
        self.tp_collective = None
        self.tp_free_pages = None
        # speculative-decoding handles, bound by bind_spec() only when
        # the engine runs with spec_config — None means zero spec
        # metrics work (the enable_metrics=False discipline)
        self.spec_drafted = None
        self.spec_accepted = None
        self.spec_wasted = None
        self.spec_target_steps = None
        self.spec_tokens_per_step = None

    def bind_tp(self, tp_size: int, overlap: bool = False) -> None:
        """TP observability (ISSUE 10): the measured all-reduce latency
        histogram — labelled `overlap="on"/"off"` since ISSUE 18, so
        dashboards can compare the serial wall against the
        ring-overlapped one without mixing samples — one free-page gauge
        per shard (page accounting is shard-replicated, so every shard
        reports the same number; the label keeps per-shard dashboards
        well-formed), and a `tp=N` tag appended to every lifecycle span
        name."""
        r = self.registry
        self.tp_collective = r.histogram(
            "serving_tp_collective_seconds",
            "measured all-reduce wall seconds on the engine's tp "
            "sub-mesh (decode-step payload shape)",
            labels={"overlap": "on" if overlap else "off"})
        self.tp_free_pages = [
            r.gauge("serving_kv_pages_free",
                    "free KV pages per tensor-parallel shard",
                    labels={"shard": str(i)})
            for i in range(tp_size)]
        self.lifecycle.tag = f"tp={tp_size}"

    def bind_kv_pool(self, kv_dtype: str, pool_bytes: int,
                     fp32_pool_bytes: int,
                     rms_error: Optional[float] = None) -> None:
        """KV-pool capacity observability (ISSUE 15): pool bytes (data +
        scale slabs) labelled by storage format for every engine, plus —
        quantized pools only — the capacity ratio against an equal-page
        fp32 pool and the construction-time quantization-error probe
        (the hot path keeps no fp32 originals, so error is characterized
        once, offline)."""
        r = self.registry
        r.gauge("serving_kv_pool_bytes",
                "bytes held by the paged KV pools (data + scale slabs)",
                labels={"kv_dtype": kv_dtype}).set(pool_bytes)
        if rms_error is not None:
            r.gauge("serving_kv_capacity_ratio",
                    "fp32 pool bytes / this pool's bytes at equal page "
                    "count (resident-sequence capacity multiplier)"
                    ).set(fp32_pool_bytes / pool_bytes)
            r.gauge("serving_kv_quant_rms_error",
                    "quantize->dequantize RMS relative error, one-shot "
                    "construction-time probe on gaussian K/V"
                    ).set(rms_error)

    def bind_spec(self) -> None:
        """Speculative-decoding observability (ISSUE 17): drafted /
        accepted / wasted draft-token counters, the target-model pass
        counter their accept-rate divides into, and the per-request
        tokens-per-target-step histogram — the multiplier speculation
        exists to raise (1.0 = non-speculative; the goodput interplay
        shows up through the existing SLO plane, whose TPOT samples
        simply arrive in bigger per-block bursts)."""
        c = self.registry.counter
        self.spec_drafted = c(
            "serving_spec_drafted_tokens_total",
            "draft tokens submitted to fused verification")
        self.spec_accepted = c(
            "serving_spec_accepted_tokens_total",
            "draft tokens accepted by rejection sampling")
        self.spec_wasted = c(
            "serving_spec_wasted_tokens_total",
            "draft tokens rejected (verified but not emitted)")
        self.spec_target_steps = c(
            "serving_spec_target_steps_total",
            "target-model verify passes over speculative rows")
        self.spec_tokens_per_step = self.registry.histogram(
            "serving_spec_tokens_per_target_step",
            "tokens emitted per target-model pass, one sample per "
            "request per drained speculative block")

    # --------------------------------------------------- scheduler hooks
    def enqueued(self, req) -> None:
        self.lifecycle.point(req.request_id, "enqueued", req.arrival_t)

    def admitted(self, req) -> None:
        self.lifecycle.point(req.request_id, "admitted")

    def preempted(self, req) -> None:
        self.preemptions.inc()
        now = time.perf_counter()
        self.lifecycle.point(req.request_id, "preempted", now)
        self.lifecycle.point(req.request_id, "requeued", now)

    def finished(self, req) -> None:
        self.lifecycle.point(req.request_id, "finished", req.finish_t)

    def terminal(self, req, status: str) -> None:
        """A request reached cancelled/expired/failed/shed: count it and
        stamp the lifecycle so chrome traces and `trace_summary
        --requests` show how the request ended."""
        self.terminated[status].inc()
        self.lifecycle.point(req.request_id, status, req.finish_t)

    def parked(self, req) -> None:
        self.parked_total.inc()
        self.lifecycle.point(req.request_id, "parked")

    def sample_queues(self, waiting: int, running: int, allocator) -> None:
        self.queue_waiting.set(waiting)
        self.queue_running.set(running)
        free = allocator.num_free
        total = allocator.num_allocatable        # page 0 never allocates
        self.free_pages.set(free)
        self.kv_util.set(1.0 - free / total if total else 0.0)
        if self.tp_free_pages is not None:
            for shard_gauge in self.tp_free_pages:
                shard_gauge.set(free)


class ServingEngine:
    def __init__(self, model, *, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_batch_size: int = 8,
                 max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 cache_dtype=jnp.float32,
                 kv_dtype: str = "fp32",
                 enable_prefix_caching: bool = False,
                 decode_horizon: int = 8,
                 spec_config=None,
                 enable_chunked_prefill: bool = False,
                 prefill_chunk_tokens: int = 256,
                 max_num_batched_tokens: Optional[int] = None,
                 enable_ragged_step: bool = True,
                 enable_metrics: bool = True,
                 metrics: Optional[MetricsRegistry] = None,
                 max_waiting: Optional[int] = None,
                 max_queue_wait_s: Optional[float] = None,
                 max_preemptions: Optional[int] = 8,
                 fault_injector=None,
                 retry_backoff_s: float = 0.02,
                 journal=None,
                 tp_size: int = 1,
                 devices: Optional[Sequence] = None,
                 tp_quantized_allreduce: bool = False,
                 tp_overlap: bool = False,
                 tp_overlap_chunks: int = 2,
                 slo_classes: Optional[Sequence] = None,
                 slo_refresh_every: int = 64,
                 flight_recorder=None,
                 postmortem_dir: Optional[str] = None):
        from ..models.generation import _config_of

        self.model = model
        model.eval()
        cfg = _config_of(model)
        # quantized serving (ISSUE 15): `kv_dtype` names the KV pool
        # storage format. "fp32"/"bf16" resolve HERE, without importing
        # serving.quant (zero-touch guarantee, raise-on-touch pinned);
        # "int8"/"fp8" are validated lazily by quant.resolve_kv_dtype
        # inside PagedKVCache. `cache_dtype` stays as the legacy spelling
        # of the unquantized formats; a conflict between the two knobs is
        # an error, not a silent preference.
        legacy = {"float32": "fp32", "bfloat16": "bf16"}.get(
            jnp.dtype(cache_dtype).name)
        if legacy is None:
            raise ValueError(
                f"unsupported cache_dtype {cache_dtype!r}: pools take "
                "float32/bfloat16, or use kv_dtype='int8'/'fp8'")
        kv_dtype = str(kv_dtype)
        if kv_dtype == "fp32" and legacy != "fp32":
            kv_dtype = legacy
        elif legacy != "fp32" and kv_dtype != legacy:
            raise ValueError(
                f"conflicting cache_dtype={jnp.dtype(cache_dtype).name} "
                f"and kv_dtype={kv_dtype!r}: pick one knob")
        if kv_dtype not in ("fp32", "bf16", "int8", "fp8"):
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r}: expected one of "
                "'fp32', 'bf16', 'int8', 'fp8'")
        self.kv_dtype = kv_dtype
        self.tp_quantized_allreduce = bool(tp_quantized_allreduce)
        if self.tp_quantized_allreduce and int(tp_size) < 2:
            raise ValueError(
                "tp_quantized_allreduce replaces the row-parallel psum "
                "and needs tp_size >= 2 (tp_size=1 has no collective)")
        # collective/compute overlap (ISSUE 18): split each row-parallel
        # all-reduce into `tp_overlap_chunks` micro-row ring chunks that
        # interleave with the consumer matmuls, tokens bit-identical to
        # the serial psum. chunks=1 degenerates to the serial schedule
        # (TPContext normalizes it off and reuses the serial
        # executables); tp_size=1 has no collective to hide
        self.tp_overlap = bool(tp_overlap)
        self.tp_overlap_chunks = int(tp_overlap_chunks)
        if self.tp_overlap:
            if int(tp_size) < 2:
                raise ValueError(
                    "tp_overlap pipelines the row-parallel all-reduce "
                    "and needs tp_size >= 2 (tp_size=1 has no "
                    "collective to hide)")
            if self.tp_overlap_chunks < 1:
                raise ValueError(
                    f"tp_overlap_chunks must be >= 1, got "
                    f"{tp_overlap_chunks}")
        # tensor parallelism (ISSUE 10): tp_size>1 shards the model
        # weights (Megatron column/row specs) and the KV pools' kv-head
        # axis over a sub-mesh of `devices` (sorted by id; default the
        # first tp_size of jax.devices()) and wraps every jitted step in
        # shard_map. The import stays inside the branch: the tp_size=1
        # path runs ZERO tp code (pinned by a raise-on-touch test)
        self.tp_size = int(tp_size)
        if self.tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {tp_size}")
        if self.tp_size > 1:
            from .tp import TPContext

            self._tp = TPContext(
                model, self.tp_size, devices=devices,
                quantized_allreduce=self.tp_quantized_allreduce,
                overlap=self.tp_overlap,
                overlap_chunks=self.tp_overlap_chunks)
        else:
            self._tp = None
        self.page_size = page_size
        self.max_batch_size = max_batch_size
        self.max_seq_len = max_seq_len or cfg.max_position_embeddings
        self.max_pages_per_seq = pages_for(self.max_seq_len, page_size)
        self.decode_horizon = int(decode_horizon)
        if self.decode_horizon < 1:
            raise ValueError("decode_horizon must be >= 1")
        # speculative decoding (ISSUE 17): model-free drafts (n-gram
        # prompt-lookup / prefix-cache continuation) verified inside the
        # fused decode/ragged executables with on-device rejection
        # sampling. The import stays inside the branch: a spec-off
        # engine runs ZERO spec code (raise-on-touch pinned in
        # tests/test_spec.py), and its non-spec streams are byte
        # identical to pre-spec engines
        if spec_config is not None:
            from . import spec as _spec_module

            self._spec_mod = _spec_module
            self.spec_config = spec_config.validate()
        else:
            self._spec_mod = None
            self.spec_config = None
        self._spec_lookahead = (self.spec_config.lookahead
                                if self.spec_config is not None else 0)
        # chunked prefill (Sarathi-Serve): prompts run in page-aligned
        # chunks co-scheduled with decode under a per-step token budget.
        # Off by default; when on, the chunk width must be a positive
        # multiple of page_size (chunk starts stay page-aligned so every
        # non-final chunk's page charge is exact) and the budget must fit
        # at least one chunk or prefill could never progress
        self.enable_chunked_prefill = bool(enable_chunked_prefill)
        if self.enable_chunked_prefill:
            self.prefill_chunk_tokens = int(prefill_chunk_tokens)
            if self.prefill_chunk_tokens < page_size or \
                    self.prefill_chunk_tokens % page_size:
                raise ValueError(
                    f"prefill_chunk_tokens ({prefill_chunk_tokens}) must "
                    f"be a positive multiple of page_size ({page_size})")
            if max_num_batched_tokens is None:
                # default: one full chunk always fits alongside a full
                # decode batch (decoders charge a block's worst case —
                # under speculation that is horizon × (1+lookahead))
                max_num_batched_tokens = (
                    self.prefill_chunk_tokens
                    + max_batch_size * self.decode_horizon
                    * (1 + self._spec_lookahead))
            self.max_num_batched_tokens = int(max_num_batched_tokens)
            if self.max_num_batched_tokens < self.prefill_chunk_tokens:
                raise ValueError(
                    f"max_num_batched_tokens ({max_num_batched_tokens}) "
                    "must be >= prefill_chunk_tokens "
                    f"({self.prefill_chunk_tokens})")
            # ragged mixed steps (on by default under chunking): a step
            # that carries chunk work dispatches ONE flat executable —
            # decode rows and chunks share it — keyed on a small set of
            # total-token buckets, instead of the decode block plus one
            # dispatch per chunk. `enable_ragged_step=False` keeps the
            # PR 6 chained pipeline (the bench's comparison baseline)
            self.enable_ragged_step = bool(enable_ragged_step)
            self.token_buckets = (
                ragged_token_buckets(max_batch_size,
                                     self.max_num_batched_tokens)
                if self.enable_ragged_step else None)
        else:
            self.prefill_chunk_tokens = None
            self.max_num_batched_tokens = None
            self.enable_ragged_step = False
            self.token_buckets = None
        if num_pages is None:
            # worst case every slot runs a full-length sequence, +1 null
            num_pages = max_batch_size * self.max_pages_per_seq + 1
        self.cache = PagedKVCache.for_model(model, num_pages, page_size,
                                            cache_dtype,
                                            kv_dtype=self.kv_dtype)
        if self._tp is not None:
            self.cache.shard_pools(self._tp.mesh, self._tp.pool_spec)
        # observability: ONE registry per engine is the single source of
        # truth behind stats()/compile_counts() and the exporters. Pass
        # `metrics=` to aggregate several engines into a shared registry,
        # or `enable_metrics=False` to strip every metrics/lifecycle call
        # off the hot path (stats() then returns the same shape zeroed).
        self.metrics = metrics if metrics is not None else (
            MetricsRegistry() if enable_metrics else None)
        self._obs = (ServingObs(self.metrics)
                     if self.metrics is not None else None)
        if self._obs is not None and self._tp is not None:
            self._obs.bind_tp(self.tp_size, overlap=self._tp.overlap)
        if self.metrics is not None:
            self.cache.allocator.bind_metrics(self.metrics)
        if self._obs is not None:
            # equal-page fp32 baseline for the capacity gauge, computed
            # WITHOUT touching serving.quant
            c = self.cache
            fp32_bytes = (c.num_layers * c.num_pages * c.page_size
                          * 2 * c.num_kv_heads * c.head_dim * 4)
            rms = None
            if c.quantized:
                from .quant import measure_roundtrip_error
                rms = measure_roundtrip_error(c.quant_spec, c.head_dim)
            self._obs.bind_kv_pool(c.kv_dtype, c.pool_bytes, fp32_bytes,
                                   rms)
        if self._obs is not None and self.spec_config is not None:
            self._obs.bind_spec()
        # SLO accounting (ISSUE 13): per-request-class TTFT/TPOT targets
        # feeding windowed attainment gauges + a goodput counter. Rides
        # on the metrics registry, so it requires one; with no classes
        # registered the engine holds None and executes zero SLO code
        # (raise-on-touch pinned, like enable_metrics=False).
        if slo_classes:
            if self.metrics is None:
                raise ValueError(
                    "slo_classes requires metrics (SLO accounting lives "
                    "in the registry); drop enable_metrics=False")
            self._slo = SloTracker(self.metrics, slo_classes,
                                   refresh_every=slo_refresh_every)
        else:
            self._slo = None
        # flight recorder (ISSUE 13): bounded ring of control-plane
        # events. None = the engine executes no recorder code at all.
        # Independent of metrics — forensics work even on a metrics-off
        # engine, and vice versa.
        self._recorder = flight_recorder
        # where quarantine/death post-mortem bundles land; None = build
        # bundles only on explicit dump_postmortem(directory=...) calls
        self._postmortem_dir = postmortem_dir
        self.last_postmortem_path: Optional[str] = None
        # automatic prefix caching (full-page granularity, LRU eviction):
        # finished/prefilled prompts leave their full pages in a radix
        # tree; a later prompt sharing a page-aligned prefix reuses them
        # and prefills only its suffix
        self.prefix_cache = (PrefixCache(self.cache.allocator, page_size,
                                         metrics=self.metrics)
                             if enable_prefix_caching else None)
        # resilience (ISSUE 6): bounded queue + queue-wait shedding,
        # per-request deadlines (add_request(deadline_s=...)), transient
        # retry with backoff, preemption-storm parking, and seeded fault
        # injection. Everything strips to a None/empty check when unused
        # — the enable_metrics=False discipline.
        self._max_queue_wait_s = (float(max_queue_wait_s)
                                  if max_queue_wait_s is not None else None)
        self.retry_backoff_s = float(retry_backoff_s)
        self._faults = fault_injector
        # crash recovery (ISSUE 8): the journal is the exactly-once
        # delivery ledger — tokens are appended at the moment a step
        # RETURNS them, never at drain time (recovery.py). None = no
        # journaling, and the only cost is one None check per step.
        self._journal = journal
        # engine-level fault count (every fault _guarded_call or the
        # device_lost gate observes, transient or not): the supervisor's
        # fault-storm window reads deltas of this — a plain int, so it
        # works with metrics off
        self.fault_events = 0
        # live request ids carrying a deadline; the expiry sweep is
        # skipped entirely while this is empty and no queue-wait bound
        # is set, so deadline-free serving runs zero resilience code
        self._deadlined: set = set()
        if fault_injector is not None:
            self.cache.allocator.bind_faults(fault_injector)
            if self.prefix_cache is not None:
                self.prefix_cache.bind_faults(fault_injector)
        self.prefill_buckets = tuple(sorted(
            prefill_buckets or _default_buckets(self.max_seq_len)))
        if self.prefill_buckets[-1] < self.max_seq_len:
            raise ValueError("prefill_buckets must cover max_seq_len "
                             "(preempted requests re-prefill at their "
                             "full current length)")
        self.scheduler = Scheduler(self.cache.allocator, page_size,
                                   max_batch_size, self.max_pages_per_seq,
                                   prefix_cache=self.prefix_cache,
                                   decode_horizon=self.decode_horizon,
                                   drain_hook=self._drain_for_scheduler,
                                   obs=self._obs,
                                   recorder=flight_recorder,
                                   max_waiting=max_waiting,
                                   max_preemptions=max_preemptions,
                                   # chunked prefill handles any folded
                                   # length — no bucket ceiling to guard
                                   max_prefill_tokens=(
                                       None if self.enable_chunked_prefill
                                       else self.prefill_buckets[-1]),
                                   prefill_chunk_tokens=
                                   self.prefill_chunk_tokens,
                                   max_num_batched_tokens=
                                   self.max_num_batched_tokens,
                                   ragged_steps=self.enable_ragged_step,
                                   spec_lookahead=self._spec_lookahead)
        self.params, self.buffers = extract_state(model)
        if self._tp is not None:
            self.params = self._tp.shard_params(self.params)
            self.buffers = self._tp.replicate(self.buffers)
        self.requests: Dict[int, Request] = {}
        # per-request PRNG state as raw (2,) uint32 key data, resident on
        # device — sampling never splits keys on the host
        self._key_state: Dict[int, jax.Array] = {}
        # the dispatched-but-undrained decode block (async overlap depth
        # 1): emitted tokens + the device carries the next chained block
        # consumes without any host round-trip
        self._pending: Optional[dict] = None
        # events produced when the scheduler's drain_hook fires inside
        # schedule(); step() returns them ahead of its own
        self._spill: List[Tuple[int, int]] = []
        self._last_drain_t = 0.0
        # decode-stall observability: perf_counter of the most recent
        # decode-block dispatch, cleared whenever the running set
        # empties, so the serving_decode_stall_seconds histogram only
        # sees gaps while some request was actually being served
        self._last_decode_dispatch_t: Optional[float] = None
        # jitted steps are memoized ON THE MODEL (generation.py's trick):
        # the closures only capture `model`, so engines over the same model
        # — restarts, tests, multiple pools — share compiled executables,
        # and jax retraces per aval set exactly when shapes differ
        self._jit_cache: Dict[object, object] = model.__dict__.setdefault(
            "_serving_jit_cache", {})
        # this engine's distinct per-family input avals == its jit cache
        # misses (the shared caches' _cache_size would count OTHER
        # engines' shapes too); compile_counts() reports these. "sample"
        # stays for compatibility: sampling is fused into prefill/decode,
        # so it counts the (now extinct) standalone sampler dispatches
        self._exec_shapes: Dict[str, set] = {
            "prefill": set(), "prefill_offset": set(),
            "prefill_chunked": set(), "decode": set(), "ragged": set(),
            "spec": set(), "sample": set()}
        # measure this sub-mesh's all-reduce latency ONCE at construction
        # (a few samples of the decode-step payload shape) — blocking on
        # a probe per step would measure device-queue time, not the
        # collective; the bench phase takes denser samples when asked
        if self._tp is not None and self._obs is not None:
            for dt in self._tp.collective_seconds(
                    samples=3, rows=self.max_batch_size):
                self._obs.tp_collective.observe(dt)

    # ----------------------------------------------------------- request API
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 1.0, seed: Optional[int] = None,
                    eos_token_id: Optional[int] = None,
                    deadline_s: Optional[float] = None,
                    slo_class: Optional[str] = None) -> int:
        """Queue one prompt; returns a request id. Non-blocking — the
        request runs as `step()`/`stream()` turn the crank. ALL
        validation happens up front: a rejected request leaves no trace
        (no page allocation, no engine/scheduler registration). Raises
        `EngineOverloaded` when the bounded waiting queue
        (`max_waiting`) is full. `deadline_s` bounds the request's TOTAL
        latency from arrival: past it, a waiting request is expired
        before admission and a running one is cancelled at the next
        block boundary (terminal status "expired" either way).
        `slo_class` opts the request into per-class SLO accounting; it
        must name a class registered via the engine's `slo_classes=`."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0 (got {deadline_s})")
        if slo_class is not None and (
                self._slo is None or not self._slo.has_class(slo_class)):
            raise ValueError(
                f"unknown SLO class {slo_class!r}; register it via "
                "ServingEngine(slo_classes=[SloClass(...)])")
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"{self.max_seq_len}")
        if not self.enable_chunked_prefill \
                and len(prompt) > self.prefill_buckets[-1]:
            # belt over the constructor's buckets-cover-max_seq_len check:
            # admitting this request would allocate pages and then blow up
            # in _bucket_for mid-prefill, leaking them. Chunked prefill
            # has no bucket ceiling — any prompt under max_seq_len runs
            # chunk by chunk
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prefill bucket {self.prefill_buckets[-1]}")
        req = Request(prompt=prompt, max_new_tokens=max_new_tokens,
                      sampling=SamplingParams(temperature, top_k, top_p,
                                              seed),
                      eos_token_id=eos_token_id, slo_class=slo_class)
        if deadline_s is not None:
            req.deadline_t = req.arrival_t + deadline_s
        # scheduler.add validates the page budget and the bounded queue
        # and may raise (ValueError / EngineOverloaded) — only register
        # the request with the engine once it is accepted
        self.scheduler.add(req)
        self.requests[req.request_id] = req
        if deadline_s is not None:
            self._deadlined.add(req.request_id)
        if seed is None:
            seed = int(np.random.randint(0, 2 ** 31 - 1))
        self._key_state[req.request_id] = jax.random.key_data(
            jax.random.key(seed))
        if self._journal is not None:
            # the EFFECTIVE seed (drawn above when the caller passed
            # None) and a wall-clock deadline anchor go in the ledger:
            # both are what a post-crash rebuild continues from
            now_wall = time.time()
            self._journal.submit(
                request_id=req.request_id, prompt=prompt,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                eos_token_id=eos_token_id,
                deadline_wall=(now_wall + deadline_s
                               if deadline_s is not None else None),
                arrival_wall=now_wall)
        return req.request_id

    def output(self, request_id: int) -> List[int]:
        """prompt + generated tokens so far. For a preempted request the
        prompt absorbs already-generated tokens, so this is always the
        full sequence."""
        req = self.requests[request_id]
        return list(req.prompt) + list(req.generated)

    def status(self, request_id: int) -> Tuple[str, Optional[str]]:
        """(status, error) for one request — error is set only for
        status "failed" (the isolated failure, as text)."""
        req = self.requests[request_id]
        return req.status, req.error

    def cancel(self, request_id: int) -> bool:
        """Cancel a request in ANY state: waiting (dequeued before it
        ever runs), running (pages released through the refcounted path,
        so shared prefix pages survive for the other holders), or
        mid-decode-block with tokens in flight — the pending block is
        DRAINED first, so already-sampled tokens surface through the
        next `step()` and no dispatched computation keeps writing into
        released pages. Returns True if the request was live and is now
        "cancelled"; False for unknown/already-terminal ids (including a
        request whose in-flight tokens completed it during the drain)."""
        req = self.requests.get(request_id)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        if self._pending is not None \
                and request_id in self._pending["rids"]:
            # drain first: the in-flight block's tokens reach host state
            # (and the caller, via the spill queue) before teardown
            self._spill.extend(self._drain_pending())
            if req.status in TERMINAL_STATUSES:
                return False      # the drained tokens finished it
        return self._finalize(req, "cancelled")

    # ----------------------------------------------------------- resilience
    def _finalize(self, req: Request, status: str,
                  error: Optional[str] = None) -> bool:
        """Terminal transition through the scheduler (queues + refcounted
        page release) plus engine-side deadline bookkeeping. The journal
        records the terminal status here — the one place every
        failure-side ending (cancelled/expired/failed/shed) funnels
        through — so replay never resurrects a request that already
        ended."""
        done = self.scheduler.finalize(req, status, error=error)
        if done and self._journal is not None \
                and self._journal.known(req.request_id):
            self._journal.terminal(req.request_id, status, error)
        if self._deadlined:
            self._deadlined.discard(req.request_id)
        return done

    def _expire_and_shed(self) -> None:
        """Deadline/queue-wait sweep, run at the top of `step()` — i.e.
        at a block boundary — only while armed (some live request has a
        deadline, or `max_queue_wait_s` is set): waiting requests past
        their deadline expire and ones waiting longer than
        `max_queue_wait_s` are shed, both BEFORE admission can spend
        pages or a prefill on them; running requests past their deadline
        are cancelled here, draining any in-flight block first."""
        now = time.perf_counter()
        for req in list(self.scheduler.waiting):
            if req.deadline_t is not None and now >= req.deadline_t:
                self._finalize(req, "expired")
            elif self._max_queue_wait_s is not None and \
                    now - req.arrival_t >= self._max_queue_wait_s:
                self._finalize(req, "shed")
        expired = [r for r in self.scheduler.running
                   if r.deadline_t is not None and now >= r.deadline_t]
        if expired:
            if self._pending is not None:
                # block boundary discipline: surface in-flight tokens
                # and stop the device writing before releasing pages
                self._spill.extend(self._drain_pending())
            for req in expired:
                if req.status == "running":   # drain may have finished it
                    self._finalize(req, "expired")

    def _guarded_call(self, site: str, fn):
        """Failure-isolation wrapper for one jitted-dispatch or drain
        site: consults the fault injector (when bound), retries a
        TRANSIENT fault exactly once after `retry_backoff_s`, and
        otherwise returns the exception for the caller to quarantine
        with the right drain ordering. A FATAL fault (`is_fatal`) is
        re-raised untouched — the engine is the casualty, and retrying
        or quarantining would hide that from the supervisor. Every
        fault observed here bumps `fault_events` (the supervisor's
        fault-storm signal). Returns (result, None) on success,
        (None, exc) on isolation. The happy path runs no resilience
        code beyond one None check."""
        fi = self._faults
        try:
            if fi is not None:
                fi.check(site)
            return fn(), None
        except Exception as e:  # noqa: BLE001 — isolation boundary
            self.fault_events += 1
            if self._recorder is not None:
                self._recorder.record("fault", site=site,
                                      error=str(e), **describe_fault(e))
            if is_fatal(e):
                raise
            if not is_transient(e):
                return None, e
            if self._obs is not None:
                self._obs.retries.inc()
            if self.retry_backoff_s > 0:
                time.sleep(self.retry_backoff_s)
            try:
                if fi is not None:
                    fi.check(site)
                return fn(), None
            except Exception as e2:  # noqa: BLE001
                self.fault_events += 1
                if self._recorder is not None:
                    self._recorder.record("fault", site=site, retry=True,
                                          error=str(e2),
                                          **describe_fault(e2))
                if is_fatal(e2):
                    raise
                return None, e2

    def _quarantine(self, reqs: Sequence[Request], exc: BaseException,
                    site: str) -> None:
        """Isolate a failed dispatch/drain to exactly the implicated
        requests: status "failed" with the error recorded on each
        Request, pages released via refcounts, and the allocator +
        scheduler invariants re-audited so the survivors keep serving on
        a provably consistent pool. Any pending block belonging to the
        implicated set is discarded (its device carries are suspect and
        its writes target pages being released)."""
        err = f"{site}: {type(exc).__name__}: {exc}"
        rids = {r.request_id for r in reqs}
        if self._recorder is not None:
            self._recorder.record("quarantine", site=site, error=err,
                                  rids=sorted(rids))
        if self._pending is not None \
                and rids & set(self._pending["rids"]):
            rec, self._pending = self._pending, None
            for i, r in enumerate(rec["reqs"]):
                r.inflight = max(r.inflight - rec["incr"][i], 0)
        for req in reqs:
            if req.status not in TERMINAL_STATUSES:
                self._finalize(req, "failed", error=err)
        self.scheduler.check_consistency()
        if self._postmortem_dir is not None:
            # a quarantine is a casualty worth forensics even though the
            # engine survives: dump a bundle, but never let the dump
            # itself take the engine down
            try:
                self.dump_postmortem(f"quarantine-{site}")
            except Exception:  # noqa: BLE001 — forensics must not kill
                pass

    # ---------------------------------------------------------------- steps
    def step(self) -> List[Tuple[int, int]]:
        """One scheduler decision + (at most) one jitted dispatch.
        Returns the (request_id, token) pairs that reached the host this
        step — with a decode horizon and async overlap, a decode block's
        tokens surface one step AFTER its dispatch (the drain overlaps
        the next block's device time). This wrapper is also the crash
        recovery boundary: the injector's `device_lost` site fires here
        (fatal by default — it propagates untouched for the supervisor),
        and the step's returned events are journaled at this exact
        point, the host-visible delivery moment that exactly-once
        replay keys on."""
        fi = self._faults
        if fi is not None:
            try:
                fi.check("device_lost")
            except Exception as e:
                self.fault_events += 1
                if self._recorder is not None:
                    self._recorder.record("fault", site="device_lost",
                                          error=str(e),
                                          **describe_fault(e))
                raise
        events = self._step_impl()
        if self._journal is not None and events:
            self._journal_delivery(events)
        if self._slo is not None:
            self._slo.step_tick()
        return events

    def _step_impl(self) -> List[Tuple[int, int]]:
        if self._deadlined or self._max_queue_wait_s is not None:
            self._expire_and_shed()            # may spill drained tokens
        if not any(r.prefill_done for r in self.scheduler.running):
            # decode-stall gaps are only meaningful while some request
            # continuously WANTED decode steps; a wave boundary — or a
            # stretch where every running request is still mid-prefill
            # with nobody decode-ready — resets the gap clock
            self._last_decode_dispatch_t = None
        if self._pending is not None and self._pending.get("kind") == "spec":
            # A spec block's drain reverts its worst-case page charge
            # (`revert_spec_pages`), so it must run BEFORE schedule()
            # charges the NEXT block's worst case — draining after would
            # free pages the new block's table already needs covered,
            # silently sinking its KV writes into the null page. The
            # early drain costs nothing: spec blocks never chain on
            # device carries, so _spec_decode would sync here anyway.
            self._spill.extend(self._drain_pending())
        t_sched = time.perf_counter()
        decision = self.scheduler.schedule()   # drain_hook may spill here
        if self._obs is not None:
            self._obs.step_phase["schedule"].observe(
                time.perf_counter() - t_sched)
        if self._recorder is not None:
            self._recorder.record(
                "schedule", decision=decision.kind,
                prefill=(decision.prefill.request_id
                         if decision.prefill is not None else None),
                decode=len(decision.decode), chunks=len(decision.chunks))
        spilled, self._spill = self._spill, []
        if decision.kind == "prefill":
            return spilled + self._prefill(decision.prefill)
        if decision.kind == "decode":
            return spilled + self._decode_path(decision.decode)
        if decision.kind == "ragged":
            return spilled + self._ragged_step(decision)
        if decision.kind == "mixed":
            return spilled + self._mixed_step(decision)
        return spilled + self._drain_pending()

    def _mixed_step(self, decision) -> List[Tuple[int, int]]:
        """One chunked-prefill step: the decode block dispatches FIRST
        (async — its drain below overlaps the chunks' device time), then
        every scheduled chunk chains on the block's donated pools, so the
        device serializes decode-block -> chunks while the host runs
        ahead. One shared drain: the block's tokens surface through the
        ordinary pending-drain path; intermediate chunks sync nothing."""
        events: List[Tuple[int, int]] = []
        if decision.decode:
            events.extend(self._decode_path(decision.decode))
        elif self._pending is not None:
            # belt: every pending block's requests are running decoders,
            # so an empty decode batch should imply no pending block
            events.extend(self._drain_pending())
        for task in decision.chunks:
            if task.req.status != "running":
                continue    # finalized mid-step (cancel/expiry/fault)
            if task.start != task.req.num_computed_tokens:
                # stale extent: the request was preempted (and possibly
                # re-admitted with a fresh first chunk) after this task
                # was queued — its pages and cursor no longer match
                continue
            events.extend(self._chunk_prefill(task))
        return events

    def _journal_delivery(self, events: List[Tuple[int, int]]) -> None:
        """Append just-returned events to the journal — called at the
        single point tokens become host-visible to a `step()`/`stream()`
        consumer, never at drain time (a drained-but-unreturned token
        must stay recomputable, not re-deliverable). Consecutive
        same-request runs land as one block record; a request whose
        delivered stream just completed gets its `finished` terminal
        record here, after its tokens."""
        j = self._journal
        t_wall = time.time()
        i = 0
        while i < len(events):
            rid = events[i][0]
            k = i + 1
            while k < len(events) and events[k][0] == rid:
                k += 1
            if j.known(rid):
                j.tokens(rid, [t for _, t in events[i:k]], t_wall=t_wall)
            i = k
        for rid in dict.fromkeys(r for r, _ in events):
            if j.known(rid) and self.requests[rid].status == "finished":
                j.terminal(rid, "finished")

    def drain_all(self) -> List[Tuple[int, int]]:
        """Flush everything already computed out to the caller: spilled
        events (cancel/expiry drained them outside a step) plus the
        pending block — journaled exactly like a step's return."""
        spilled, self._spill = self._spill, []
        events = spilled + self._drain_pending()
        if self._journal is not None and events:
            self._journal_delivery(events)
        return events

    def stream(self):
        """Generator of (request_id, token, done) events until every
        queued request completes."""
        while (self.scheduler.has_work() or self._pending is not None
               or self._spill):
            if self.scheduler.has_work():
                events = self.step()
            else:
                # no schedulable work left: flush the spill plus the
                # pending block
                events = self.drain_all()
            for i, (rid, tok) in enumerate(events):
                done = (self.requests[rid].status == "finished"
                        and all(r != rid for r, _ in events[i + 1:]))
                yield rid, tok, done

    def run(self) -> Dict[int, List[int]]:
        """Drain all queued requests; returns request_id -> full tokens."""
        for _ in self.stream():
            pass
        return {rid: self.output(rid) for rid in self.requests}

    def _note_exec(self, family: str, aval) -> None:
        """Record one step family's input aval; a NEW aval is a jit-cache
        miss, counted into the registry's compile-miss counter (the set
        stays the dedup structure, the registry holds the count)."""
        shapes = self._exec_shapes[family]
        if aval not in shapes:
            shapes.add(aval)
            if self._obs is not None:
                self._obs.compile_miss[family].inc()

    # -------------------------------------------------------------- prefill
    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket")

    def _prefill_jit(self, bucket: int):
        # TP engines key per (tp degree, device subset) — the cache is
        # shared model-wide, and cluster replicas on different sub-meshes
        # must never exchange executables; tp_size=1 keys are UNCHANGED,
        # so this PR compiles the exact same executables as before
        tp = self._tp
        key = ("prefill", bucket) + (tp.jit_key if tp is not None else ())
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model

            def prefill(params, buffers, ids, pools, page_table, last_idx,
                        key_data, temps, top_ks, top_ps):
                views = views_from_pools(pools, page_table)
                (logits, new_views), _ = call_functional(
                    model, params, buffers, (Tensor(ids),),
                    kwargs={"caches": views, "start_pos": 0},
                    training=False)
                last = jax.lax.dynamic_slice_in_dim(
                    logits, last_idx, 1, axis=1)[:, 0]
                key_data, subs = _split_rows(key_data)
                tok = _sample_batch(last, subs, temps, top_ks, top_ps)
                return (tok.astype(jnp.int32), key_data,
                        pools_from_views(new_views))

            if tp is not None:
                prefill = tp.wrap_prefill_exec(prefill)
            self._jit_cache[key] = jax.jit(prefill, donate_argnums=(3,))
        return self._jit_cache[key]

    def _prefill_offset_jit(self, bucket: int):
        """The offset-aware prefill variant (prefix-cache hits): same
        bucket shapes, but start_pos is a TRACED scalar — the suffix
        tokens sit at positions offset..offset+bucket-1 and attend over
        the cached prefix pages through the page table. One extra
        executable per bucket, shared by every hit length."""
        tp = self._tp
        key = (("prefill_offset", bucket)
               + (tp.jit_key if tp is not None else ()))
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model

            def prefill(params, buffers, ids, pools, page_table, last_idx,
                        offset, key_data, temps, top_ks, top_ps):
                views = views_from_pools(pools, page_table)
                (logits, new_views), _ = call_functional(
                    model, params, buffers, (Tensor(ids),),
                    kwargs={"caches": views, "start_pos": offset},
                    training=False)
                last = jax.lax.dynamic_slice_in_dim(
                    logits, last_idx, 1, axis=1)[:, 0]
                key_data, subs = _split_rows(key_data)
                tok = _sample_batch(last, subs, temps, top_ks, top_ps)
                return (tok.astype(jnp.int32), key_data,
                        pools_from_views(new_views))

            if tp is not None:
                prefill = tp.wrap_prefill_exec(prefill)
            self._jit_cache[key] = jax.jit(prefill, donate_argnums=(3,))
        return self._jit_cache[key]

    def _emit(self, req: Request, token: int, now: float
              ) -> Tuple[int, int]:
        req.generated.append(token)
        o = self._obs
        if o is not None:
            o.tokens.inc()
        if req.first_token_t is None:
            req.first_token_t = now
            if o is not None:
                ttft = max(now - req.arrival_t, 0.0)
                o.ttft.observe(ttft)
                o.lifecycle.point(req.request_id, "first_token", now)
                if self._slo is not None:
                    self._slo.first_token(req.slo_class, ttft)
        req.last_token_t = now
        if req.is_done():
            req.finish_t = now
            self.scheduler.finish(req)   # obs.finished fires in there
        return (req.request_id, token)

    def _prefill(self, req: Request) -> List[Tuple[int, int]]:
        # prefix-cache hit: only the uncached suffix runs through the
        # model (bucketed on the SUFFIX length, so a long shared prompt
        # with a short question prefills in the smallest bucket)
        t_in = time.perf_counter()
        n_cached = req.cached_tokens
        suffix = req.prompt[n_cached:]
        bucket = self._bucket_for(len(suffix))
        family = "prefill_offset" if n_cached else "prefill"
        self._note_exec(
            family, (bucket, self.cache.num_pages, self.max_pages_per_seq))
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :len(suffix)] = suffix
        page_table = self.cache.page_table_array([req.pages],
                                                 self.max_pages_per_seq)
        sp = req.sampling
        knobs = (jnp.asarray([sp.temperature], jnp.float32),
                 jnp.asarray([sp.top_k], jnp.int32),
                 jnp.asarray([sp.top_p], jnp.float32))
        key_data = self._key_state[req.request_id][None]

        def dispatch():
            if n_cached:
                tok, new_kd, pools = self._prefill_offset_jit(bucket)(
                    self.params, self.buffers, jnp.asarray(ids),
                    self.cache.pools, page_table,
                    jnp.int32(len(suffix) - 1), jnp.int32(n_cached),
                    key_data, *knobs)
            else:
                tok, new_kd, pools = self._prefill_jit(bucket)(
                    self.params, self.buffers, jnp.asarray(ids),
                    self.cache.pools, page_table,
                    jnp.int32(len(suffix) - 1), key_data, *knobs)
            self.cache.pools = pools
            self._key_state[req.request_id] = new_kd[0]
            return int(np.asarray(tok)[0])

        t0 = time.perf_counter()
        if self._recorder is not None:
            self._recorder.record("dispatch", family=family,
                                  rid=req.request_id, tokens=len(suffix))
        with RecordEvent("serving.prefill"):
            token, err = self._guarded_call("dispatch", dispatch)
        if token is None:
            # isolate THIS request; any pending decode block belongs to
            # other (already-prefilled) requests and keeps flying
            self._quarantine([req], err, "prefill")
            return []
        req.num_computed_tokens = len(req.prompt)
        if self.prefix_cache is not None:
            # register the prompt's full pages for future reuse (the
            # partial last page never enters the tree); in-flight
            # requests can hit them immediately
            self.prefix_cache.insert(req.prompt, req.pages)
        now = time.perf_counter()
        o = self._obs
        prev_t = req.last_token_t            # set => this is a re-prefill
        if o is not None:
            o.prefill_steps.inc()
            o.dispatches.inc()
            o.host_syncs.inc()
            o.prefill_seconds.inc(now - t0)
            o.lifecycle.span(req.request_id, "prefill", t0, now)
            o.step_phase["assemble"].observe(t0 - t_in)
            # prefill's drain is fused into the dispatch (the sampled
            # token syncs inside it), so the whole span lands here
            o.step_phase["dispatch"].observe(now - t0)
        events = [self._emit(req, token, now)]
        if o is not None and prev_t is not None:
            # requeued request: the gap since its last pre-preemption
            # token is honest inter-token latency
            gap = max(now - prev_t, 0.0)
            o.inter_token.observe(gap)
            if self._slo is not None:
                self._slo.decode_tokens(req.slo_class, gap, 1)
        return events

    # ------------------------------------------------------ chunked prefill
    def _chunked_prefill_jit(self):
        """THE chunked-prefill executable — one per engine, not per
        bucket: ids are a fixed (1, prefill_chunk_tokens) window, the
        start offset and the valid length (via `last_idx`) are TRACED
        scalars, and attention reaches the earlier chunks' (and cached
        prefix's) K/V through the page table, exactly the machinery the
        prefix-cache offset prefill proved out. Every chunk of every
        prompt length shares this single compiled program; only its
        final chunk carries padding. The sampled token and split key are
        computed unconditionally (same trace for every chunk) but the
        host ADOPTS them only on the final chunk."""
        tp = self._tp
        key = (("prefill_chunked", self.prefill_chunk_tokens)
               + (tp.jit_key if tp is not None else ()))
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model

            def prefill(params, buffers, ids, pools, page_table, last_idx,
                        offset, key_data, temps, top_ks, top_ps):
                views = views_from_pools(pools, page_table)
                (logits, new_views), _ = call_functional(
                    model, params, buffers, (Tensor(ids),),
                    kwargs={"caches": views, "start_pos": offset},
                    training=False)
                last = jax.lax.dynamic_slice_in_dim(
                    logits, last_idx, 1, axis=1)[:, 0]
                key_data, subs = _split_rows(key_data)
                tok = _sample_batch(last, subs, temps, top_ks, top_ps)
                return (tok.astype(jnp.int32), key_data,
                        pools_from_views(new_views))

            if tp is not None:
                prefill = tp.wrap_prefill_exec(prefill)
            self._jit_cache[key] = jax.jit(prefill, donate_argnums=(3,))
        return self._jit_cache[key]

    def _chunk_prefill(self, task) -> List[Tuple[int, int]]:
        """Dispatch one scheduled prefill chunk. Intermediate chunks
        write K/V and return WITHOUT a host sync (their sampled token is
        discarded and the per-request key state stays untouched — one
        key split per emitted token keeps streams bit-identical to
        unchunked); the final chunk adopts the sampled first token,
        exactly like the tail of `_prefill`. Padding lanes inside the
        chunk are harmless by construction: sub-prompt padding is
        overwritten by the next chunk before anything reads it, tail
        padding past the prompt is overwritten by the first decode
        steps, and positions past the page table's capacity route to
        the null page."""
        t_in = time.perf_counter()
        req, start, n = task.req, task.start, task.length
        rid = req.request_id
        chunk = self.prefill_chunk_tokens
        final = task.is_final
        self._note_exec("prefill_chunked",
                        (chunk, self.cache.num_pages,
                         self.max_pages_per_seq))
        ids = np.zeros((1, chunk), np.int32)
        ids[0, :n] = req.prompt[start:start + n]
        page_table = self.cache.page_table_array([req.pages],
                                                 self.max_pages_per_seq)
        sp = req.sampling
        knobs = (jnp.asarray([sp.temperature], jnp.float32),
                 jnp.asarray([sp.top_k], jnp.int32),
                 jnp.asarray([sp.top_p], jnp.float32))
        key_data = self._key_state[rid][None]

        def dispatch():
            tok, new_kd, pools = self._chunked_prefill_jit()(
                self.params, self.buffers, jnp.asarray(ids),
                self.cache.pools, page_table, jnp.int32(n - 1),
                jnp.int32(start), key_data, *knobs)
            self.cache.pools = pools
            if not final:
                return PAD_TOKEN          # async: no host round-trip
            self._key_state[rid] = new_kd[0]
            return int(np.asarray(tok)[0])

        t0 = time.perf_counter()
        if self._recorder is not None:
            self._recorder.record("dispatch", family="prefill_chunk",
                                  rid=rid, tokens=n, final=final)
        with RecordEvent("serving.prefill_chunk"):
            token, err = self._guarded_call("dispatch", dispatch)
        if token is None:
            # fault mid-chunk: quarantine ONLY this request — the cursor
            # never advanced, so finalize releases exactly its
            # chunk-to-date pages; the decode block and its peers'
            # chunks keep flying (their pools/pages are disjoint)
            self._quarantine([req], err, "prefill_chunk")
            return []
        req.num_computed_tokens = start + n
        now = time.perf_counter()
        o = self._obs
        if o is not None:
            o.prefill_chunks.inc()
            o.dispatches.inc()
            o.prefill_seconds.inc(now - t0)
            # profiler-only spans for intermediate chunks (retained
            # lifecycle lists must not grow per chunk); the final chunk
            # is the retained "prefill" stage
            o.lifecycle.span(rid, "prefill", t0, now, retain=final)
            o.step_phase["assemble"].observe(t0 - t_in)
            o.step_phase["dispatch"].observe(now - t0)
        if not final:
            return []
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, req.pages)
        prev_t = req.last_token_t            # set => this is a re-prefill
        if o is not None:
            o.prefill_steps.inc()
            o.host_syncs.inc()
        events = [self._emit(req, token, now)]
        if o is not None and prev_t is not None:
            gap = max(now - prev_t, 0.0)
            o.inter_token.observe(gap)
            if self._slo is not None:
                self._slo.decode_tokens(req.slo_class, gap, 1)
        return events

    # ---------------------------------------------------------- ragged step
    def _ragged_jit(self, t_bucket: int):
        """ONE executable for a whole mixed step, keyed on the flat
        token bucket: iteration 0 is a single flat (1, T) forward
        carrying every row's input tokens — each decode row's one token
        AND every prefill chunk's extent, routed through their own
        page-table rows by the ragged attention path — followed by the
        decode block's usual (horizon-1)-iteration lax.scan over the
        decode rows. Sampling/EOS/budget masking after the flat forward
        is the decode body's own arithmetic on per-row gathers, so
        decode streams are bit-identical to the chained block; a final
        chunk is a row with an emit budget of 1 (its sampled first
        token, one key split, then it parks), an intermediate chunk a
        row with budget 0 (writes K/V, emits PAD, keeps its key).
        Per-row key-state selection happens IN the executable
        (scan-carried for decode rows, the iteration-0 split for final
        chunks, the untouched input for everything else), so the drain's
        blanket key adoption stays correct for every row class."""
        tp = self._tp
        key = (("ragged", t_bucket, self.decode_horizon,
                self.max_batch_size, self.page_size)
               + (tp.jit_key if tp is not None else ()))
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model
            page_size = self.page_size
            horizon = self.decode_horizon

            def ragged_block(params, buffers, flat_ids, pools,
                             page_tables, flat_pos, row_ids, last_idx,
                             tokens, positions, key_data, temps, top_ks,
                             top_ps, eos_ids, remaining, decode_mask,
                             final_mask):
                max_pages = page_tables.shape[1]
                key_in = key_data
                views = views_from_pools(pools, page_tables, row_ids)
                (logits, new_views), _ = call_functional(
                    model, params, buffers, (Tensor(flat_ids),),
                    kwargs={"caches": views, "start_pos": flat_pos},
                    training=False)
                pools = pools_from_views(new_views)
                # iteration-0 postlude == the decode body's arithmetic,
                # with each row's logits gathered from its last flat
                # token
                key_data, subs = _split_rows(key_data)
                key_split1 = key_data
                nxt = _sample_batch(logits[0, last_idx], subs, temps,
                                    top_ks, top_ps).astype(jnp.int32)
                alive = remaining > 0
                hit_eos = alive & (eos_ids >= 0) & (nxt == eos_ids)
                emit0 = jnp.where(alive, nxt, jnp.int32(PAD_TOKEN))
                remaining = jnp.where(alive, remaining - 1, remaining)
                remaining = jnp.where(hit_eos, jnp.int32(0), remaining)
                tokens = jnp.where(alive, nxt, tokens)
                positions = advance_positions(
                    positions, remaining > 0, max_pages, page_size)

                def body(carry, _):
                    tokens, pools, positions, key_data, remaining = carry
                    views = views_from_pools(pools, page_tables)
                    (logits, new_views), _ = call_functional(
                        model, params, buffers, (Tensor(tokens[:, None]),),
                        kwargs={"caches": views, "start_pos": positions},
                        training=False)
                    pools = pools_from_views(new_views)
                    key_data, subs = _split_rows(key_data)
                    nxt = _sample_batch(logits[:, 0], subs, temps,
                                        top_ks, top_ps).astype(jnp.int32)
                    alive = remaining > 0
                    hit_eos = alive & (eos_ids >= 0) & (nxt == eos_ids)
                    emit = jnp.where(alive, nxt, jnp.int32(PAD_TOKEN))
                    remaining = jnp.where(alive, remaining - 1, remaining)
                    remaining = jnp.where(hit_eos, jnp.int32(0), remaining)
                    tokens = jnp.where(alive, nxt, tokens)
                    positions = advance_positions(
                        positions, remaining > 0, max_pages, page_size)
                    return (tokens, pools, positions, key_data,
                            remaining), emit

                carry = (tokens, pools, positions, key_data, remaining)
                (tokens, pools, positions, key_data, remaining), rest = \
                    jax.lax.scan(body, carry, None, length=horizon - 1)
                emitted = jnp.concatenate(
                    [emit0[:, None], jnp.transpose(rest)], axis=1)
                key_out = jnp.where(
                    decode_mask[:, None], key_data,
                    jnp.where(final_mask[:, None], key_split1, key_in))
                return emitted, pools, key_out

            if tp is not None:
                ragged_block = tp.wrap_ragged_exec(ragged_block)
            self._jit_cache[key] = jax.jit(ragged_block,
                                           donate_argnums=(3,))
        return self._jit_cache[key]

    def _ragged_step(self, decision) -> List[Tuple[int, int]]:
        """One flat ragged step: the whole mixed step — the decode
        rows' horizon block AND every scheduled chunk — is a single
        jitted dispatch (N+1 chained dispatches before). Flat inputs
        are built from host request state, so any pending block drains
        FIRST (a ragged step never chains on device carries); async
        overlap is preserved in the other direction — the record this
        step leaves behind drains under the next step's device time.
        A final chunk's sampled token therefore surfaces at the next
        drain instead of synchronously, one step later than the chained
        path; stream CONTENT is unchanged."""
        events = self._drain_pending()
        t_in = time.perf_counter()      # assemble starts after the drain
        decode = [r for r in decision.decode if r.status == "running"]
        chunks = [t for t in decision.chunks
                  if t.req.status == "running"
                  and t.start == t.req.num_computed_tokens]
        if not chunks:
            # every chunk went stale (finalized/preempted during the
            # drain): fall through to the plain decode pipeline
            return events + (self._decode_path(decode) if decode else [])
        spec_on = self.spec_config is not None
        L = self._spec_lookahead
        # a spec ragged step's decode rows can emit 1 (iteration 0) +
        # (horizon-1) × (1+lookahead) tokens; the in-flight bound (the
        # only thing build_ragged_inputs' horizon feeds) scales with it
        cap_horizon = (1 + (self.decode_horizon - 1) * (1 + L)
                       if spec_on else self.decode_horizon)
        batch = build_ragged_inputs(
            decode, chunks, buckets=self.token_buckets,
            max_batch=self.max_batch_size, horizon=cap_horizon,
            page_size=self.page_size, max_pages=self.max_pages_per_seq)
        if batch is None:
            return events
        self._note_exec("spec" if spec_on else "ragged",
                        (batch.t_bucket, self.max_batch_size,
                         self.decode_horizon, L, self.cache.num_pages,
                         self.max_pages_per_seq))
        page_tables = self.cache.page_table_array(
            batch.page_lists, self.max_pages_per_seq)
        kds = [self._key_state[r.request_id] for r in batch.reqs]
        kds.extend([jnp.zeros((2,), jnp.uint32)]
                   * (self.max_batch_size - len(batch.reqs)))
        key_data = jnp.stack(kds)
        rids = tuple(r.request_id for r in batch.reqs)
        if spec_on:
            # drafts for the decode rows only (rows 0..d-1 of the flat
            # batch); chunk rows stay PAD — a final chunk emits its one
            # iteration-0 token and parks, so drafts could never land
            dbuf = self._spec_mod.build_draft_buffer(
                decode, self.max_batch_size,
                self.decode_horizon * (1 + L), self.spec_config,
                self.prefix_cache)

        def dispatch():
            if spec_on:
                out = self._spec_ragged_jit(batch.t_bucket)(
                    self.params, self.buffers,
                    jnp.asarray(batch.flat_ids), self.cache.pools,
                    page_tables, jnp.asarray(dbuf),
                    jnp.asarray(batch.flat_pos),
                    jnp.asarray(batch.row_ids),
                    jnp.asarray(batch.last_idx),
                    jnp.asarray(batch.tokens),
                    jnp.asarray(batch.positions), key_data,
                    jnp.asarray(batch.temps), jnp.asarray(batch.top_ks),
                    jnp.asarray(batch.top_ps),
                    jnp.asarray(batch.eos_ids),
                    jnp.asarray(batch.remaining),
                    jnp.asarray(batch.decode_mask),
                    jnp.asarray(batch.final_mask))
            else:
                out = self._ragged_jit(batch.t_bucket)(
                    self.params, self.buffers,
                    jnp.asarray(batch.flat_ids), self.cache.pools,
                    page_tables, jnp.asarray(batch.flat_pos),
                    jnp.asarray(batch.row_ids),
                    jnp.asarray(batch.last_idx),
                    jnp.asarray(batch.tokens),
                    jnp.asarray(batch.positions), key_data,
                    jnp.asarray(batch.temps), jnp.asarray(batch.top_ks),
                    jnp.asarray(batch.top_ps),
                    jnp.asarray(batch.eos_ids),
                    jnp.asarray(batch.remaining),
                    jnp.asarray(batch.decode_mask),
                    jnp.asarray(batch.final_mask))
            self.cache.pools = out[1]
            return out

        t0 = time.perf_counter()
        if self._recorder is not None:
            self._recorder.record("dispatch", family="ragged",
                                  rows=len(batch.reqs),
                                  decode=len(decode), chunks=len(chunks),
                                  t_bucket=batch.t_bucket)
        with RecordEvent("serving.ragged_step"):
            out, err = self._guarded_call("dispatch", dispatch)
        if out is None:
            # one dispatch carries every row, so a fault implicates the
            # whole step's requests — coarser than the chained path's
            # per-site isolation, the price of sharing one executable
            self._quarantine(
                [r for r in batch.reqs if r.status == "running"], err,
                "ragged")
            return events
        emitted, pools, key_out = out[0], out[1], out[2]
        for req, n in zip(batch.reqs, batch.incr):
            req.inflight += n
        now = time.perf_counter()
        o = self._obs
        for task in chunks:
            req = task.req
            req.num_computed_tokens = task.start + task.length
            if o is not None:
                o.prefill_chunks.inc()
                o.lifecycle.span(req.request_id, "prefill", t0, now,
                                 retain=task.is_final)
            if task.is_final:
                # pages are complete once this dispatch lands; later
                # dispatches ordering behind it through the donated
                # pools may share them immediately
                if self.prefix_cache is not None:
                    self.prefix_cache.insert(req.prompt, req.pages)
                if o is not None:
                    o.prefill_steps.inc()
        if o is not None:
            o.ragged_steps.inc()
            o.dispatches.inc()
            o.step_phase["assemble"].observe(t0 - t_in)
            o.step_phase["dispatch"].observe(now - t0)
            if decode:
                o.decode_steps.inc()
                if self._last_decode_dispatch_t is not None:
                    o.decode_stall.observe(
                        max(t0 - self._last_decode_dispatch_t, 0.0))
        if decode:
            self._last_decode_dispatch_t = t0
        if decode or any(t.is_final for t in chunks):
            self._pending = {
                "kind": "ragged", "rids": rids, "reqs": list(batch.reqs),
                "incr": list(batch.incr), "emitted": emitted,
                "key_data": key_out, "t0": t0,
            }
            if spec_on:
                self._pending["spec_stats"] = out[3]
                self._pending["windows"] = (
                    (1,) + (L + 1,) * (self.decode_horizon - 1))
        # else: intermediate chunks only — nothing can emit and no key
        # state moved, so dropping the record outright saves a drain
        # (and its host sync) that would deliver zero tokens
        return events

    # --------------------------------------------------------------- decode
    def _decode_block_jit(self, horizon: int):
        """ONE fused decode+sample executable per horizon: N model steps
        + sampling + EOS/budget masking + position advance inside one
        jitted lax.scan. Returns the (b, N) emitted block plus the
        device carries (tokens/positions/keys/budgets) the next chained
        block consumes without a host round-trip."""
        tp = self._tp
        key = ("decode", horizon) + (tp.jit_key if tp is not None else ())
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model
            page_size = self.page_size

            def decode_block(params, buffers, tokens, pools, page_tables,
                             positions, key_data, temps, top_ks, top_ps,
                             eos_ids, remaining):
                max_pages = page_tables.shape[1]

                def body(carry, _):
                    tokens, pools, positions, key_data, remaining = carry
                    views = views_from_pools(pools, page_tables)
                    (logits, new_views), _ = call_functional(
                        model, params, buffers, (Tensor(tokens[:, None]),),
                        kwargs={"caches": views, "start_pos": positions},
                        training=False)
                    pools = pools_from_views(new_views)
                    key_data, subs = _split_rows(key_data)
                    nxt = _sample_batch(logits[:, 0], subs, temps,
                                        top_ks, top_ps).astype(jnp.int32)
                    alive = remaining > 0
                    hit_eos = alive & (eos_ids >= 0) & (nxt == eos_ids)
                    emit = jnp.where(alive, nxt, jnp.int32(PAD_TOKEN))
                    remaining = jnp.where(alive, remaining - 1, remaining)
                    remaining = jnp.where(hit_eos, jnp.int32(0), remaining)
                    tokens = jnp.where(alive, nxt, tokens)
                    positions = advance_positions(
                        positions, remaining > 0, max_pages, page_size)
                    return (tokens, pools, positions, key_data,
                            remaining), emit

                carry = (tokens, pools, positions, key_data, remaining)
                (tokens, pools, positions, key_data, remaining), emitted = \
                    jax.lax.scan(body, carry, None, length=horizon)
                return (jnp.transpose(emitted), pools, tokens, positions,
                        key_data, remaining)

            if tp is not None:
                decode_block = tp.wrap_decode_exec(decode_block)
            self._jit_cache[key] = jax.jit(decode_block,
                                           donate_argnums=(3,))
        return self._jit_cache[key]

    def _decode_rows(self, n: int) -> int:
        """Dispatched decode row count: the next power of two >= n,
        capped at max_batch_size — a 2-request batch stops paying a
        full max_batch-row step. Chained blocks stay consistent for
        free: chaining requires identical rids, hence identical n."""
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_batch_size)

    def _decode(self, reqs: Sequence[Request]) -> List[Tuple[int, int]]:
        t_in = time.perf_counter()
        reqs = [r for r in reqs if r.status == "running"]
        if not reqs:
            return self._drain_pending()
        h = self.decode_horizon
        rids = tuple(r.request_id for r in reqs)
        events_prev: List[Tuple[int, int]] = []
        prev = self._pending
        if prev is not None and (prev.get("kind", "decode") != "decode"
                                 or prev["rids"] != rids):
            # batch composition changed (admission/finish/preemption),
            # or the pending record is a ragged step (its carries are
            # per-ROW-class and must never seed a decode chain): sync
            # and go fresh
            events_prev = self._drain_pending()
            reqs = [r for r in reqs if r.status == "running"]
            if not reqs:
                return events_prev
            rids = tuple(r.request_id for r in reqs)
            prev = None
        b = self._decode_rows(len(reqs))
        self._note_exec(
            "decode", (b, h, self.cache.num_pages, self.max_pages_per_seq))
        page_lists: List[Sequence[int]] = [()] * b
        for i, req in enumerate(reqs):
            page_lists[i] = req.pages
        page_tables = self.cache.page_table_array(page_lists,
                                                  self.max_pages_per_seq)
        if prev is None:
            # fresh block: inputs from (drained, accurate) host state
            park = overflow_position(self.max_pages_per_seq,
                                     self.page_size)
            tokens = np.zeros((b,), np.int32)
            positions = np.full((b,), park, np.int32)
            remaining = np.zeros((b,), np.int32)
            temps = np.zeros((b,), np.float32)
            top_ks = np.zeros((b,), np.int32)
            top_ps = np.ones((b,), np.float32)
            eos_ids = np.full((b,), PAD_TOKEN, np.int32)
            kds = []
            for i, req in enumerate(reqs):
                tokens[i] = (req.generated[-1] if req.generated
                             else req.prompt[-1])
                # the input token's K/V lands at its own position; the
                # step predicts the token after it
                positions[i] = req.num_tokens - 1
                remaining[i] = req.max_new_tokens - len(req.generated)
                sp = req.sampling
                temps[i], top_ks[i], top_ps[i] = (sp.temperature,
                                                  sp.top_k, sp.top_p)
                if req.eos_token_id is not None:
                    eos_ids[i] = req.eos_token_id
                kds.append(self._key_state[req.request_id])
            kds.extend([jnp.zeros((2,), jnp.uint32)] * (b - len(reqs)))
            knobs = (jnp.asarray(temps), jnp.asarray(top_ks),
                     jnp.asarray(top_ps), jnp.asarray(eos_ids))
            tokens = jnp.asarray(tokens)
            positions = jnp.asarray(positions)
            remaining = jnp.asarray(remaining)
            key_data = jnp.stack(kds)
        else:
            # chained block: consume the pending block's device carries —
            # no host sync anywhere on this path
            tokens, positions = prev["tokens"], prev["positions"]
            key_data, remaining = prev["key_data"], prev["remaining"]
            knobs = prev["knobs"]
        # in-flight accounting: the block may add up to min(h, budget)
        # tokens per row before the host sees them; _ensure_decode_pages
        # reserves against this bound before the NEXT block (applied
        # only once the dispatch actually succeeds)
        incr = []
        for req in reqs:
            cap = req.max_new_tokens - len(req.generated) - req.inflight
            incr.append(max(min(h, cap), 0))

        def dispatch():
            out = self._decode_block_jit(h)(
                self.params, self.buffers, tokens, self.cache.pools,
                page_tables, positions, key_data, *knobs, remaining)
            self.cache.pools = out[1]
            return out

        t0 = time.perf_counter()
        if self._recorder is not None:
            self._recorder.record("dispatch", family="decode",
                                  rows=len(reqs), horizon=h)
        with RecordEvent("serving.decode_block"):
            out, err = self._guarded_call("dispatch", dispatch)
        if out is None:
            # a decode dispatch implicates the whole batch. Drain the
            # previous block FIRST (its tokens are sound and its writes
            # must stop before pages are released), then isolate
            # whatever is still running
            ev = self._drain_pending()
            self._quarantine(
                [r for r in reqs if r.status == "running"], err,
                "decode")
            return events_prev + ev
        emitted, pools, tokens, positions, key_data, remaining = out
        for req, n in zip(reqs, incr):
            req.inflight += n
        if self._obs is not None:
            t1 = time.perf_counter()
            self._obs.step_phase["assemble"].observe(t0 - t_in)
            self._obs.step_phase["dispatch"].observe(t1 - t0)
            self._obs.decode_steps.inc()
            self._obs.dispatches.inc()
            if self._last_decode_dispatch_t is not None:
                # dispatch-to-dispatch gap while requests were running:
                # whatever kept the engine away from decode (a prefill,
                # scheduling, host work) shows up here
                self._obs.decode_stall.observe(
                    max(t0 - self._last_decode_dispatch_t, 0.0))
        self._last_decode_dispatch_t = t0
        self._pending = {
            "kind": "decode",
            "rids": rids, "reqs": list(reqs), "incr": incr,
            "emitted": emitted, "tokens": tokens, "positions": positions,
            "key_data": key_data, "remaining": remaining, "knobs": knobs,
            "t0": t0,
        }
        if prev is not None:
            # async overlap: block k+1 is dispatched and running; pulling
            # block k's tokens now costs (at most) the device time block
            # k+1 is already spending
            return events_prev + self._drain_record(prev)
        return events_prev

    # --------------------------------------------------------- speculative
    def _decode_path(self, reqs: Sequence[Request]) -> List[Tuple[int, int]]:
        """Route a decode batch to the speculative block when spec is
        on; the spec-off path is the unchanged `_decode` (byte-identical
        streams, zero spec code executed)."""
        if self.spec_config is not None:
            return self._spec_decode(reqs)
        return self._decode(reqs)

    def _spec_block_jit(self, horizon: int):
        """ONE fused speculative decode-block executable per (horizon,
        lookahead): `horizon` verify windows, each a (b, 1+lookahead)
        target pass + on-device rejection sampling + the decode body's
        EOS/budget masking (spec.make_spec_decode_fn)."""
        tp = self._tp
        L = self.spec_config.lookahead
        key = (("spec", horizon, L, self.page_size)
               + (tp.jit_key if tp is not None else ()))
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model
            fn = self._spec_mod.make_spec_decode_fn(
                model, horizon=horizon, lookahead=L,
                page_size=self.page_size)
            if tp is not None:
                fn = tp.wrap_spec_exec(fn)
            self._jit_cache[key] = jax.jit(fn, donate_argnums=(3,))
        return self._jit_cache[key]

    def _spec_ragged_jit(self, t_bucket: int):
        """The ragged mixed-step executable with speculation fused in:
        iteration 0 is the plain flat forward (chunk rows need it),
        the remaining horizon-1 iterations are verify windows over the
        decode rows (spec.make_spec_ragged_fn)."""
        tp = self._tp
        L = self.spec_config.lookahead
        key = (("spec_ragged", t_bucket, self.decode_horizon, L,
                self.max_batch_size, self.page_size)
               + (tp.jit_key if tp is not None else ()))
        if key not in self._jit_cache:
            model = self.model if tp is None else tp.shard_model
            fn = self._spec_mod.make_spec_ragged_fn(
                model, horizon=self.decode_horizon, lookahead=L,
                page_size=self.page_size)
            if tp is not None:
                fn = tp.wrap_spec_ragged_exec(fn)
            self._jit_cache[key] = jax.jit(fn, donate_argnums=(3,))
        return self._jit_cache[key]

    def _spec_decode(self, reqs: Sequence[Request]) -> List[Tuple[int, int]]:
        """Speculative decode block (ISSUE 17). Structurally `_decode`
        with two differences: drafts are proposed from HOST request
        state, so the pending block always drains FIRST — spec blocks
        never chain on device carries (async overlap is preserved in
        the other direction: this block's record drains under the NEXT
        dispatch) — and the block can emit up to horizon×(1+lookahead)
        tokens per row, whose worst-case page charge the drain reverts
        down to actual acceptance via `revert_spec_pages`."""
        events = self._drain_pending()
        t_in = time.perf_counter()
        reqs = [r for r in reqs if r.status == "running"]
        if not reqs:
            return events
        h = self.decode_horizon
        L = self.spec_config.lookahead
        cap_tokens = h * (1 + L)
        rids = tuple(r.request_id for r in reqs)
        b = self._decode_rows(len(reqs))
        self._note_exec("spec", (b, h, L, self.cache.num_pages,
                                 self.max_pages_per_seq))
        page_lists: List[Sequence[int]] = [()] * b
        for i, req in enumerate(reqs):
            page_lists[i] = req.pages
        page_tables = self.cache.page_table_array(page_lists,
                                                  self.max_pages_per_seq)
        park = overflow_position(self.max_pages_per_seq, self.page_size)
        tokens = np.zeros((b,), np.int32)
        positions = np.full((b,), park, np.int32)
        remaining = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        top_ks = np.zeros((b,), np.int32)
        top_ps = np.ones((b,), np.float32)
        eos_ids = np.full((b,), PAD_TOKEN, np.int32)
        kds = []
        for i, req in enumerate(reqs):
            tokens[i] = (req.generated[-1] if req.generated
                         else req.prompt[-1])
            positions[i] = req.num_tokens - 1
            remaining[i] = req.max_new_tokens - len(req.generated)
            sp = req.sampling
            temps[i], top_ks[i], top_ps[i] = (sp.temperature,
                                              sp.top_k, sp.top_p)
            if req.eos_token_id is not None:
                eos_ids[i] = req.eos_token_id
            kds.append(self._key_state[req.request_id])
        kds.extend([jnp.zeros((2,), jnp.uint32)] * (b - len(reqs)))
        # drafts ride in as one (b, cap) PAD-padded buffer; each verify
        # window slides its per-row cursor by the emitted count
        dbuf = self._spec_mod.build_draft_buffer(
            reqs, b, cap_tokens, self.spec_config, self.prefix_cache)
        incr = []
        for req in reqs:
            cap = req.max_new_tokens - len(req.generated) - req.inflight
            incr.append(max(min(cap_tokens, cap), 0))

        def dispatch():
            out = self._spec_block_jit(h)(
                self.params, self.buffers, jnp.asarray(tokens),
                self.cache.pools, page_tables, jnp.asarray(dbuf),
                jnp.asarray(positions), jnp.stack(kds),
                jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), jnp.asarray(eos_ids),
                jnp.asarray(remaining))
            self.cache.pools = out[1]
            return out

        t0 = time.perf_counter()
        if self._recorder is not None:
            self._recorder.record("dispatch", family="spec",
                                  rows=len(reqs), horizon=h, lookahead=L)
        with RecordEvent("serving.spec_block"):
            out, err = self._guarded_call("dispatch", dispatch)
        if out is None:
            self._quarantine(
                [r for r in reqs if r.status == "running"], err, "spec")
            return events
        emitted, _pools, _tok, _pos, key_data, _rem, sstats = out
        for req, n in zip(reqs, incr):
            req.inflight += n
        if self._obs is not None:
            t1 = time.perf_counter()
            self._obs.step_phase["assemble"].observe(t0 - t_in)
            self._obs.step_phase["dispatch"].observe(t1 - t0)
            self._obs.decode_steps.inc()
            self._obs.dispatches.inc()
            if self._last_decode_dispatch_t is not None:
                self._obs.decode_stall.observe(
                    max(t0 - self._last_decode_dispatch_t, 0.0))
        self._last_decode_dispatch_t = t0
        self._pending = {
            "kind": "spec", "rids": rids, "reqs": list(reqs),
            "incr": incr, "emitted": emitted, "key_data": key_data,
            "spec_stats": sstats, "windows": (L + 1,) * h, "t0": t0,
        }
        return events

    # ---------------------------------------------------------------- drain
    def _drain_for_scheduler(self) -> None:
        """Scheduler drain_hook: the emitted events surface through
        step()'s spill queue so callers still see every token."""
        self._spill.extend(self._drain_pending())

    def _drain_pending(self) -> List[Tuple[int, int]]:
        rec, self._pending = self._pending, None
        if rec is None:
            return []
        return self._drain_record(rec)

    def _drain_record(self, rec: dict) -> List[Tuple[int, int]]:
        """THE host sync: pull one block's (b, N) token buffer, append
        per-request tokens trimmed at EOS/budget (device already masked
        past-the-end steps to PAD), finish requests, refresh per-request
        key state from the block's device carries."""
        o = self._obs
        t_in = time.perf_counter()
        sstats = rec.get("spec_stats")
        windows = rec.get("windows")
        with RecordEvent("serving.host_drain"):
            if sstats is None:
                toks, err = self._guarded_call(
                    "drain", lambda: np.asarray(jax.device_get(rec["emitted"])))  # noqa: HOST-SYNC — THE one sync per decode block (PR 3 contract)
            else:
                pulled, err = self._guarded_call(
                    "drain", lambda: jax.device_get((rec["emitted"], rec["spec_stats"])))  # noqa: HOST-SYNC — still THE one sync per block: a spec block's tokens and accept counters come back in a single transfer (PR 3 contract)
                toks, sstats = (pulled if pulled is not None
                                else (None, None))
        if toks is None:
            # the block's tokens are unrecoverable: give back the
            # in-flight reservation and isolate exactly the block's
            # still-running requests (rec is already detached from
            # self._pending, so teardown releases pages directly)
            for i, req in enumerate(rec["reqs"]):
                req.inflight = max(req.inflight - rec["incr"][i], 0)
            self._quarantine(
                [r for r in rec["reqs"] if r.status == "running"], err,
                "drain")
            return []
        if o is not None:
            o.host_syncs.inc()
        now = time.perf_counter()
        kd = rec["key_data"]
        events: List[Tuple[int, int]] = []
        for i, req in enumerate(rec["reqs"]):
            req.inflight = max(req.inflight - rec["incr"][i], 0)
            self._key_state[req.request_id] = kd[i]
            if req.status != "running":
                continue
            prev_t = req.last_token_t
            k0 = len(events)
            row = toks[i]
            if windows is not None:
                # speculative emit layout: PAD-terminated windows, a
                # row's later windows restarting after each one — the
                # parse flattens them back to one PAD-free stream
                row = self._spec_mod.parse_emitted_row(row, windows)
            for t in row:
                t = int(t)
                if t == PAD_TOKEN:
                    break
                events.append(self._emit(req, t, now))
                if req.status != "running":
                    break
            k = len(events) - k0
            if sstats is not None:
                d_cnt, a_cnt, s_cnt = (int(v) for v in sstats[i])
                req.spec_drafted += d_cnt
                req.spec_accepted += a_cnt
                req.spec_target_steps += s_cnt
                req.spec_emitted += k
                if o is not None and o.spec_drafted is not None:
                    o.spec_drafted.inc(d_cnt)
                    o.spec_accepted.inc(a_cnt)
                    o.spec_wasted.inc(d_cnt - a_cnt)
                    o.spec_target_steps.inc(s_cnt)
                    if s_cnt:
                        o.spec_tokens_per_step.observe(k / s_cnt)
                    if req.status != "running" and req.spec_target_steps:
                        acc = (req.spec_accepted
                               / max(req.spec_drafted, 1))
                        tps = (req.spec_emitted
                               / req.spec_target_steps)
                        o.lifecycle.point(
                            req.request_id,
                            f"spec[a={acc:.2f},t/s={tps:.1f}]", now)
            if o is not None and k:
                # one lifecycle span per request per drained block
                # (profiler-only: per-token volume must not grow the
                # tracker's retained event lists)
                o.lifecycle.span(req.request_id, "decode_block",
                                 rec["t0"], now, retain=False)
                if prev_t is not None:
                    # the block lands as a burst: spread its host-visible
                    # gap evenly over the k tokens it carried
                    per_tok = max(now - prev_t, 0.0) / k
                    for _ in range(k):
                        o.inter_token.observe(per_tok)
                    if self._slo is not None:
                        self._slo.decode_tokens(req.slo_class, per_tok, k)
        if windows is not None:
            # roll the speculative worst-case page charge back to what
            # was actually accepted; the next block's reservation
            # re-tops through the ordinary _ensure_decode_pages path
            for req in rec["reqs"]:
                if req.status == "running":
                    self.scheduler.revert_spec_pages(req)
        # decode wall time without double-counting overlapped block spans
        start = max(rec["t0"], self._last_drain_t)
        if o is not None:
            o.decode_seconds.inc(max(now - start, 0.0))
            o.step_phase["drain"].observe(now - t_in)
            # dispatch-to-drain span of THIS block: how long its work
            # was resident device-side (the async overlap means host
            # wall and device wall differ — this is the device-side
            # estimate ROADMAP 5's overlap fraction divides by)
            o.device_residency.observe(max(now - rec["t0"], 0.0))
        if self._recorder is not None:
            self._recorder.record("drain",
                                  family=rec.get("kind", "decode"),
                                  rows=len(rec["reqs"]),
                                  tokens=len(events))
        self._last_drain_t = now
        return events

    # ------------------------------------------------------------- recovery
    def attach_journal(self, journal) -> None:
        """Attach the RequestJournal this engine appends to (the
        exactly-once delivery ledger; recovery.py). Must happen before
        any request is added — a request unknown to the journal cannot
        be recovered."""
        self._journal = journal

    def salvage(self) -> List[Tuple[int, int]]:
        """Recovery-side best-effort drain (the supervisor's restart
        step 1): surface whatever a still-answering device can deliver —
        spilled events plus the pending block — and journal it, so the
        rebuild folds it into prompts instead of recomputing it. Unlike
        the steady-state drain path this NEVER quarantines: a block the
        device cannot hand back is simply discarded — its tokens were
        never delivered, so the journal never saw them and the rebuilt
        engine recomputes them bit-identically — and its requests stay
        live for re-admission. The injector's `drain` site is consulted
        so chaos schedules can kill the salvage too."""
        events = list(self._spill)
        self._spill = []
        rec, self._pending = self._pending, None
        if rec is not None:
            toks = None
            try:
                fi = self._faults
                if fi is not None:
                    fi.check("drain")
                toks = np.asarray(jax.device_get(rec["emitted"]))
            except Exception:  # noqa: BLE001 — the device may be gone
                self.fault_events += 1
            for i, req in enumerate(rec["reqs"]):
                req.inflight = max(req.inflight - rec["incr"][i], 0)
            if toks is not None:
                now = time.perf_counter()
                kd = rec["key_data"]
                windows = rec.get("windows")
                for i, req in enumerate(rec["reqs"]):
                    self._key_state[req.request_id] = kd[i]
                    if req.status != "running":
                        continue
                    row = toks[i]
                    if windows is not None:
                        row = self._spec_mod.parse_emitted_row(
                            row, windows)
                    for t in row:
                        t = int(t)
                        if t == PAD_TOKEN:
                            break
                        events.append(self._emit(req, t, now))
                        if req.status != "running":
                            break
        if self._journal is not None and events:
            self._journal_delivery(events)
        return events

    def snapshot(self) -> EngineSnapshot:
        """Serializable boundary state of every journal-live request:
        original prompt, delivered tokens, sampling knobs + effective
        seed, wall-clock-anchored deadlines/timestamps, and the PRNG
        key state replayed from the seed by delivered count — never the
        live `_key_state`, which a crash can leave AHEAD of what was
        actually delivered (a lost spill), and delivered is what
        restore continues from. KV pages and the pending block are
        deliberately absent: restore re-prefills the fold instead of
        checkpointing pools. Requires an attached journal."""
        if self._journal is None:
            raise RuntimeError(
                "snapshot() needs an attached journal — the journal is "
                "the source of truth for what each consumer was shown")
        snaps: List[RequestSnapshot] = []
        for rec in self._journal.live_records():
            live = self.requests.get(rec.request_id)
            kd = replay_key_state(rec.seed, len(rec.delivered))
            snaps.append(RequestSnapshot(
                request_id=rec.request_id, prompt=list(rec.prompt),
                delivered=list(rec.delivered),
                max_new_tokens=rec.max_new_tokens,
                temperature=rec.temperature, top_k=rec.top_k,
                top_p=rec.top_p, seed=rec.seed,
                eos_token_id=rec.eos_token_id,
                deadline_wall=rec.deadline_wall,
                arrival_wall=rec.arrival_wall,
                first_token_wall=rec.first_token_wall,
                last_token_wall=rec.last_token_wall,
                preemptions=live.preemptions if live is not None else 0,
                parked=live.parked if live is not None else False,
                key_data=tuple(int(x) for x in np.asarray(kd))))
        config = {
            "page_size": self.page_size,
            "max_batch_size": self.max_batch_size,
            "max_seq_len": self.max_seq_len,
            "decode_horizon": self.decode_horizon,
            "enable_chunked_prefill": self.enable_chunked_prefill,
            "enable_prefix_caching": self.prefix_cache is not None,
            # informational only: the journal's token record is device-
            # independent, so a snapshot taken at one tp degree restores
            # at ANY tp degree (restore() never reads this key)
            "tp_size": self.tp_size,
        }
        return EngineSnapshot(config=config, requests=snaps,
                              taken_wall=time.time())

    def restore(self, snapshot: EngineSnapshot,
                cancelled: Sequence[int] = ()) -> List[int]:
        """Rebuild request state on a FRESH engine from a snapshot.
        Each unfinished request is re-admitted (in submission order,
        with its ORIGINAL request id) as a folded prompt — original
        prompt + delivered tokens, the preemption trick — so its
        re-prefill rides the ordinary chunked-prefill / prefix-cache
        paths and its continuation is bit-identical to never having
        crashed. A request whose delivered stream already satisfies its
        stopping rule is reconstructed as finished (nothing recomputed);
        one named in `cancelled` (a cancel issued while the restore was
        in flight) ends "cancelled"; one whose wall-clock deadline
        passed during the outage ends "expired" — never resurrected.
        Returns the re-admitted request ids."""
        if self.requests:
            raise RuntimeError(
                "restore() needs a fresh engine — this one already "
                f"holds {len(self.requests)} requests")
        if snapshot.config.get("max_seq_len", self.max_seq_len) > \
                self.max_seq_len:
            raise ValueError(
                f"restore target's max_seq_len ({self.max_seq_len}) is "
                "smaller than the snapshot's "
                f"({snapshot.config['max_seq_len']}) — folded prompts "
                "may not fit")
        if snapshot.requests:
            reserve_request_ids(max(r.request_id
                                    for r in snapshot.requests))
        cancelled = set(cancelled)
        now_wall = time.time()
        # translate the snapshot's wall-clock anchors back into this
        # process's perf_counter timeline: deadlines keep counting down
        # across the outage, and TTFT/latency metrics stay honest
        offset = time.perf_counter() - now_wall
        readmitted: List[int] = []
        for rs in snapshot.requests:
            rid = rs.request_id
            done = (len(rs.delivered) >= rs.max_new_tokens
                    or (rs.eos_token_id is not None and rs.delivered
                        and rs.delivered[-1] == rs.eos_token_id))
            sampling = SamplingParams(rs.temperature, rs.top_k,
                                      rs.top_p, rs.seed)
            if done:
                # everything was delivered before the crash and only the
                # `finished` record was lost: reconstruct, never
                # recompute
                req = Request(prompt=list(rs.prompt),
                              max_new_tokens=rs.max_new_tokens,
                              sampling=sampling,
                              eos_token_id=rs.eos_token_id,
                              request_id=rid)
                req.generated = list(rs.delivered)
                req.num_computed_tokens = len(rs.prompt)
                self._restore_times(req, rs, offset)
                req.finish_t = time.perf_counter()
                self.requests[rid] = req
                self._key_state[rid] = jnp.asarray(rs.key_data,
                                                   dtype=jnp.uint32)
                self.scheduler.finish(req)
                if self._journal is not None \
                        and self._journal.known(rid):
                    self._journal.terminal(rid, "finished")
                continue
            req = Request(prompt=list(rs.prompt) + list(rs.delivered),
                          max_new_tokens=(rs.max_new_tokens
                                          - len(rs.delivered)),
                          sampling=sampling,
                          eos_token_id=rs.eos_token_id,
                          request_id=rid)
            req.preemptions = rs.preemptions
            req.parked = rs.parked
            self._restore_times(req, rs, offset)
            self.requests[rid] = req
            self._key_state[rid] = jnp.asarray(rs.key_data,
                                               dtype=jnp.uint32)
            if rid in cancelled:
                # a cancel issued mid-restore wins over re-admission
                self._finalize(req, "cancelled")
                continue
            if rs.deadline_wall is not None:
                req.deadline_t = rs.deadline_wall + offset
                if now_wall >= rs.deadline_wall:
                    # the deadline passed during the outage: expired
                    # requests may NOT be resurrected by replay
                    self._finalize(req, "expired")
                    continue
            self.scheduler.add(req, force=True)
            if req.deadline_t is not None:
                self._deadlined.add(rid)
            if self._obs is not None:
                self._obs.lifecycle.point(rid, "recovered")
            readmitted.append(rid)
        return readmitted

    @staticmethod
    def _restore_times(req: Request, rs: RequestSnapshot,
                       offset: float) -> None:
        req.arrival_t = rs.arrival_wall + offset
        if rs.first_token_wall is not None:
            req.first_token_t = rs.first_token_wall + offset
        if rs.last_token_wall is not None:
            req.last_token_t = rs.last_token_wall + offset

    def adopt_request(self, *, prompt: List[int],
                      delivered: Sequence[int] = (),
                      max_new_tokens: int,
                      temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0, seed: int,
                      eos_token_id: Optional[int] = None,
                      deadline_wall: Optional[float] = None,
                      key_splits: int = 0,
                      request_id: Optional[int] = None,
                      slo_class: Optional[str] = None) -> int:
        """Re-admit another engine's in-flight request into THIS engine
        while it keeps serving — the cluster's migration/hedging
        primitive. `restore()` demands a fresh engine (it rebuilds a
        whole snapshot); this is the single-request equivalent for a
        running survivor: the request enters as a folded prompt
        (`prompt + delivered`) with the REMAINING budget, its PRNG chain
        replayed to `key_splits + len(delivered)` splits past `seed`, so
        the continuation is bit-identical to the stream the dead replica
        would have produced. `request_id=None` mints a fresh id (hedge
        clones); passing one keeps the consumer-visible id across a
        migration (`reserve_request_ids` fences the global counter
        either way). If a journal is attached and does not already know
        the id, the FOLD is journaled as a new submission carrying the
        accumulated split count — a later crash of this engine replays
        correctly however many folds deep the request is. A
        `deadline_wall` already in the past finalizes the request as
        "expired" on arrival (never resurrected), mirroring restore().
        Returns the request id under which the request now runs."""
        prompt = [int(t) for t in prompt]
        delivered = [int(t) for t in delivered]
        if not prompt:
            raise ValueError("empty prompt")
        if slo_class is not None and (
                self._slo is None or not self._slo.has_class(slo_class)):
            # a migrated request's class may not exist on the adopting
            # replica; dropping to class-less beats rejecting the
            # migration, but an explicit unknown class is caller error
            raise ValueError(
                f"unknown SLO class {slo_class!r} on adopting engine")
        remaining = max_new_tokens - len(delivered)
        if remaining < 1:
            raise ValueError(
                f"nothing left to generate: {len(delivered)} of "
                f"{max_new_tokens} tokens already delivered")
        folded = prompt + delivered
        if len(folded) + remaining > self.max_seq_len:
            raise ValueError(
                f"folded prompt ({len(folded)}) + remaining budget "
                f"({remaining}) exceeds max_seq_len {self.max_seq_len}")
        if not self.enable_chunked_prefill \
                and len(folded) > self.prefill_buckets[-1]:
            raise ValueError(
                f"folded prompt length {len(folded)} exceeds the "
                f"largest prefill bucket {self.prefill_buckets[-1]}")
        if request_id is not None:
            if request_id in self.requests:
                raise ValueError(
                    f"request {request_id} already lives on this engine")
            reserve_request_ids(request_id)
        req = Request(prompt=folded, max_new_tokens=remaining,
                      sampling=SamplingParams(temperature, top_k, top_p,
                                              seed),
                      eos_token_id=eos_token_id, slo_class=slo_class,
                      **({"request_id": request_id}
                         if request_id is not None else {}))
        rid = req.request_id
        now_wall = time.time()
        offset = time.perf_counter() - now_wall
        expired = (deadline_wall is not None
                   and now_wall >= deadline_wall)
        if not expired:
            # may raise on the page budget — before any registration,
            # so a rejected adoption leaves no trace (add_request's
            # discipline); force=True because this request was already
            # admitted once, by the engine that died holding it
            self.scheduler.add(req, force=True)
        self.requests[rid] = req
        self._key_state[rid] = jnp.asarray(
            replay_key_state(seed, key_splits + len(delivered)),
            dtype=jnp.uint32)
        if self._journal is not None and not self._journal.known(rid):
            self._journal.submit(
                request_id=rid, prompt=folded,
                max_new_tokens=remaining, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                eos_token_id=eos_token_id, deadline_wall=deadline_wall,
                arrival_wall=now_wall,
                key_splits=key_splits + len(delivered))
        if deadline_wall is not None:
            req.deadline_t = deadline_wall + offset
            if expired:
                self._finalize(req, "expired")
                return rid
            self._deadlined.add(rid)
        if self._obs is not None:
            self._obs.lifecycle.point(rid, "adopted")
        if self._recorder is not None:
            self._recorder.record("adopt", rid=rid,
                                  delivered=len(delivered),
                                  remaining=remaining)
        return rid

    # -------------------------------------------------------------- metrics
    def stats(self) -> Dict[str, object]:
        """Aggregate serving metrics — a THIN VIEW over the metrics
        registry (the single source of truth; the engine keeps no
        parallel hand-maintained counters). All pre-observability keys
        are preserved; the `latency` section adds p50/p95/p99 TTFT and
        inter-token seconds straight from the registry histograms. With
        `enable_metrics=False` the same shape comes back zeroed (only
        request-derived fields are populated)."""
        o = self._obs
        if o is not None:
            s = {
                "prefill_steps": int(o.prefill_steps.value),
                "prefill_chunks": int(o.prefill_chunks.value),
                "decode_steps": int(o.decode_steps.value),
                "ragged_steps": int(o.ragged_steps.value),
                "dispatches": int(o.dispatches.value),
                "tokens_generated": int(o.tokens.value),
                "prefill_time_s": float(o.prefill_seconds.value),
                "decode_time_s": float(o.decode_seconds.value),
                "preemptions": int(o.preemptions.value),
                "host_syncs": int(o.host_syncs.value),
            }
        else:
            s = {
                "prefill_steps": 0, "prefill_chunks": 0,
                "decode_steps": 0, "ragged_steps": 0, "dispatches": 0,
                "tokens_generated": 0, "prefill_time_s": 0.0,
                "decode_time_s": 0.0,
                "preemptions": sum(r.preemptions
                                   for r in self.requests.values()),
                "host_syncs": 0,
            }
        dt = s["decode_time_s"]
        s["decode_tokens_per_s"] = (
            s["tokens_generated"] / dt if dt > 0 else 0.0)
        s["decode_horizon"] = self.decode_horizon
        s["tp_size"] = self.tp_size
        if self._tp is not None:
            s["tp"] = self._tp.describe()
        s["kv_dtype"] = self.kv_dtype
        if self.cache.quantized:
            c = self.cache
            s["quant"] = {
                "kv_dtype": c.kv_dtype,
                "pool_bytes": c.pool_bytes,
                "page_bytes": c.page_bytes,
                "fp32_pool_bytes": (c.num_layers * c.num_pages
                                    * c.page_size * 2 * c.num_kv_heads
                                    * c.head_dim * 4),
                "tp_quantized_allreduce": self.tp_quantized_allreduce,
            }
        s["tokens_per_sync"] = (
            s["tokens_generated"] / s["host_syncs"]
            if s["host_syncs"] else 0.0)
        s["num_requests"] = len(self.requests)
        s["num_finished"] = sum(r.status == "finished"
                                for r in self.requests.values())
        # resilience outcomes, derived from request state so the shape
        # is identical with metrics off (the registry keeps the same
        # counts under serving_requests_terminated_total{status=})
        term = {st: 0 for st in ("cancelled", "expired", "failed", "shed")}
        for r in self.requests.values():
            if r.status in term:
                term[r.status] += 1
        s["terminal"] = term
        s["transient_retries"] = (int(o.retries.value)
                                  if o is not None else 0)
        s["parked"] = sum(r.parked for r in self.requests.values())
        s["free_pages"] = self.cache.allocator.num_free
        s["latency"] = {
            "ttft": (o.ttft.summary() if o is not None
                     else Histogram.empty_summary()),
            "inter_token": (o.inter_token.summary() if o is not None
                            else Histogram.empty_summary()),
            "decode_stall": (o.decode_stall.summary() if o is not None
                             else Histogram.empty_summary()),
        }
        # step-phase breakdown (ISSUE 13): where a step's wall time goes
        # — scheduling, host-side batch assembly, the jitted launch, and
        # THE host sync — plus the dispatch-to-drain device-residency
        # estimate (ROADMAP 5's overlap-fraction denominator)
        if o is not None:
            s["step_breakdown"] = {
                "schedule": o.step_phase["schedule"].summary(),
                "assemble": o.step_phase["assemble"].summary(),
                "dispatch": o.step_phase["dispatch"].summary(),
                "drain": o.step_phase["drain"].summary(),
                "device_residency": o.device_residency.summary(),
            }
        else:
            s["step_breakdown"] = {
                "schedule": Histogram.empty_summary(),
                "assemble": Histogram.empty_summary(),
                "dispatch": Histogram.empty_summary(),
                "drain": Histogram.empty_summary(),
                "device_residency": Histogram.empty_summary(),
            }
        # SLO/goodput (ISSUE 13): per-class targets, windowed TTFT/TPOT
        # percentiles and attainment, plus the all-class goodput counter
        # next to raw tokens_generated
        if self._slo is not None:
            self._slo.refresh(advance=False)
            s["slo"] = self._slo.summary()
            s["goodput_tokens"] = self._slo.goodput_tokens
        else:
            s["slo"] = {}
            s["goodput_tokens"] = 0
        s["prefill_chunk_tokens"] = self.prefill_chunk_tokens
        s["max_num_batched_tokens"] = self.max_num_batched_tokens
        if self.prefix_cache is not None:
            s["prefix_cache"] = self.prefix_cache.stats()
        # speculative decoding (ISSUE 17): derived from request state so
        # the shape is identical with metrics off (the registry keeps
        # the same counts under serving_spec_*_total)
        if self.spec_config is not None:
            drafted = sum(r.spec_drafted for r in self.requests.values())
            accepted = sum(r.spec_accepted
                           for r in self.requests.values())
            steps = sum(r.spec_target_steps
                        for r in self.requests.values())
            emitted = sum(r.spec_emitted for r in self.requests.values())
            s["spec"] = {
                "lookahead": self.spec_config.lookahead,
                "method": self.spec_config.method,
                "drafted_tokens": drafted,
                "accepted_tokens": accepted,
                "wasted_tokens": drafted - accepted,
                "accept_rate": accepted / drafted if drafted else 0.0,
                "target_steps": steps,
                "tokens_per_target_step": (emitted / steps
                                           if steps else 0.0),
                "tokens_per_step": (
                    o.spec_tokens_per_step.summary()
                    if o is not None else Histogram.empty_summary()),
            }
        per_req = {}
        for rid, req in self.requests.items():
            per_req[rid] = {
                "ttft_s": (req.first_token_t - req.arrival_t
                           if req.first_token_t else None),
                "latency_s": (req.finish_t - req.arrival_t
                              if req.finish_t else None),
                "tokens": len(req.generated),
                "preemptions": req.preemptions,
                "status": req.status,
                "slo_class": req.slo_class,
            }
        s["requests"] = per_req
        return s

    # ------------------------------------------------------------ forensics
    def build_postmortem(self, reason: str,
                         info: Optional[Dict[str, object]] = None
                         ) -> Dict[str, object]:
        """Assemble (but do not write) a post-mortem bundle from this
        engine's recorder ring, metrics registry, request table and
        journal tail. Works with any subset of those attached — a
        recorder-less engine still gets metrics + request rows."""
        return _build_bundle(reason, recorder=self._recorder,
                             registry=self.metrics,
                             requests=self.requests.values(),
                             journal=self._journal, info=info)

    def dump_postmortem(self, reason: str,
                        directory: Optional[str] = None,
                        info: Optional[Dict[str, object]] = None) -> str:
        """Build a bundle and write it to ``directory`` (default: the
        engine's ``postmortem_dir``). Returns the path, also stashed on
        ``last_postmortem_path``."""
        directory = directory or self._postmortem_dir
        if directory is None:
            raise ValueError(
                "no directory: pass one or set postmortem_dir= on the "
                "engine")
        path = _dump_bundle(self.build_postmortem(reason, info=info),
                            directory)
        self.last_postmortem_path = path
        return path

    def compile_counts(self) -> Dict[str, int]:
        """Distinct executables THIS engine's step stream needs, i.e. its
        jit-cache miss count per family (prefill buckets, one fused
        decode+sample block per horizon) — the serving tests assert these
        stay bounded. Counted from the engine's own input avals because
        the underlying compiled caches are deliberately shared across
        engines on the same model; with metrics on, the counts are read
        from the registry's `serving_jit_compile_misses_total{family=}`
        counters (kept in lockstep by `_note_exec`)."""
        if self._obs is not None:
            counts = {fam: int(c.value)
                      for fam, c in self._obs.compile_miss.items()}
        else:
            counts = {name: len(shapes)
                      for name, shapes in self._exec_shapes.items()}
        counts["total"] = sum(counts.values())
        return counts
