"""ZeRO-sharded data-parallel training (ISSUE 16 tentpole, layer 2).

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336): instead of every dp replica holding the
full optimizer state and redundantly applying the identical weight
update, shard the update itself —

    reduce-scatter grads -> shard-local optimizer update on the 1/dp
    parameter slice -> all-gather updated params

`ZeroTrainStep` / `zero_train_step` builds that step jit/shard_map-
native on the unified (dp x tp) mesh from `parallel/mesh.py`:

- **stage 0** (the baseline the parity claim is against): fixed-order
  dp all-reduce of every grad, full replicated elementwise update.
- **stage 1** (ZeRO-1, paddle level "os"): same all-reduced grad, but
  the optimizer update runs on this shard's 1/dp flat slice only —
  optimizer-state bytes/chip drop to 1/dp.
- **stage 2** (ZeRO-2, "os_g"): the grad is reduce-SCATTERED (fixed
  shard order), so the full summed gradient never materializes in the
  update path.

**Bit-parity (fp32), by construction**: all stages sum grads with the
same fixed-shard-order `ordered_psum` (and `ordered_psum_scatter`,
whose shard i output is bit-identical to slicing the ordered sum —
the sum is elementwise); the optimizer update is the optimizer's OWN
elementwise `functional_step`, so updating a slice and concatenating
equals slicing the full update. Hence ZeRO-1/2 == replicated dp,
bit-for-bit, at every dp degree (pinned by tests/test_zero.py).
Cross-DEGREE bit-parity is NOT claimed: changing dp changes the batch
summation order, which fp addition does not forgive.

**Optimizer-state layout + degree-blind checkpoints**: each slot is
stored as a (dp, tp, chunk) array placed P("dp", "tp"), where chunk =
ceil(tp_local_flat_size / dp). `save_optimizer_state` reassembles full
logical arrays (host-side, numpy), `load_optimizer_state` re-slices
them for ANY (dp, tp) — save at dp=2, restore at dp=4, keep training:
the same degree-blind contract the serving journal honors for tp.

**tp composition**: params may carry Megatron PartitionSpecs over the
tp axis; the dp machinery slices each shard's TP-LOCAL flat view, so
dp x tp composes on one mesh with no special cases. Loss functions
crossing tp regions must use `mesh.copy_to_tp_region` /
`mesh.reduce_from_tp_region` (differentiating raw collectives under
`shard_map(check_rep=False)` is undefined on jax 0.4.x).

**Limits** (validated loudly at construction): elementwise optimizers
only (Lamb's trust ratio and LBFGS's history are whole-tensor
operations — a 1/dp slice changes them); `grad_clip` is rejected (the
global-norm clip over a slice is wrong — use the GSPMD GroupSharded
surface with `HybridParallelClipGrad` instead).

The paddle-compat GroupSharded/`group_sharded_parallel` surface
(GSPMD sharding-annotation flavor, stages 1-3) lives at the bottom of
this module — `fleet.meta_parallel.sharding` and
`distributed.sharding` are re-export shims onto it — and bridges to
the explicit engine via `_ShardedBase.zero_train_step()`.
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:                                   # newer jax exports it at top level
    from jax import shard_map as _shard_map  # type: ignore
except ImportError:                    # jax 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

from ..nn import Layer
from .mesh import (
    DP_AXIS, TP_AXIS, build_mesh, device_order, local_shape, ordered_psum,
    ordered_psum_scatter, shard_leaf, tp_dim_spec,
)

__all__ = [
    "ZeroTrainStep", "zero_train_step", "model_loss",
    "save_optimizer_state", "load_optimizer_state",
    "GroupShardedStage2", "GroupShardedStage3",
    "GroupShardedOptimizerStage2", "group_sharded_parallel",
    "save_group_sharded_model", "shard_leaf",
]

# whole-tensor update rules: slicing changes the math, so the sharded
# engine refuses them instead of silently diverging from the replica
_NON_ELEMENTWISE = ("Lamb", "LBFGS")


def model_loss(model, criterion=None):
    """Build a `loss_fn(params, x, y) -> scalar` over a Layer via the
    functional forward (`call_functional`), defaulting to mean squared
    error. The mean must be over the LOCAL batch rows — the engine's
    fixed-order dp reduction averages the shard losses."""
    from ..core.tensor import Tensor
    from ..jit.functional import call_functional

    def loss_fn(params, x, y):
        out, _ = call_functional(model, params, {}, (x,), training=True)
        if criterion is None:
            return jnp.mean((out - y) ** 2)
        loss = criterion(Tensor(out), Tensor(y))
        return getattr(loss, "_data", loss)

    return loss_fn


def _pad_flat(x, n: int):
    """Flatten and zero-pad to length n (n >= x.size). Zero padding is
    update-neutral for every elementwise rule: pad params and grads are
    both 0, so the padded slots never feed back into real elements."""
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, n - flat.shape[0]))


# ------------------------------------------------------------- step bodies
# module-level on purpose: these ARE the hot per-step path (traced into
# the one train executable), and graftlint's HOST-SYNC rule audits them
# by name — nested closures would dodge the audit.

def _accumulated_grads(ctx, params, batch):
    """Local (this dp shard's) loss and grads, averaged over
    `ctx.grad_accum` micro-batches split from the local rows (static
    unroll — one executable, no host loop)."""
    vg = jax.value_and_grad(ctx.loss_fn)
    k = ctx.grad_accum
    if k == 1:
        return vg(params, *batch)
    per = batch[0].shape[0] // k
    loss = None
    gsum = None
    for j in range(k):
        micro = tuple(jax.lax.dynamic_slice_in_dim(b, j * per, per, axis=0)
                      for b in batch)
        step_loss, g = vg(params, *micro)
        loss = step_loss if loss is None else loss + step_loss
        gsum = g if gsum is None else jax.tree_util.tree_map(
            lambda a, b: a + b, gsum, g)
    inv = jnp.float32(1.0 / k)
    return loss * inv, jax.tree_util.tree_map(lambda g: g * inv, gsum)


def _replicated_update(ctx, params, grads, state, lr, t):
    """Stage 0: fixed-order dp all-reduce of every grad, full
    elementwise update everywhere — the reference the sharded stages
    are bit-identical to. Returns `(new_params, new_state, grad_aux)`
    where grad_aux is the telemetry (grad_sumsq, nonfinite) pair over
    the MEAN grad (None when telemetry is off — the telemetry-off
    trace is unchanged)."""
    inv = jnp.float32(1.0 / ctx.dp)
    g = {k: ordered_psum(grads[k], DP_AXIS) * inv for k in grads}
    new_p, new_s = ctx.optimizer.functional_step(params, g, state, lr, t)
    aux = None
    if ctx._telemetry is not None:
        # g is replicated across dp (already all-reduced): no dp
        # combine, tp-sharded leaves combined inside grad_leaf_stats
        aux = ctx._trmod.grad_leaf_stats(ctx, g, dp_reduce=False)
    return new_p, new_s, aux


def _sharded_update(ctx, params, grads, state, lr, t):
    """ZeRO-1/2: slice params + grads to this shard's 1/dp flat chunk,
    run the optimizer's own elementwise update on the slice against the
    (dp, tp, chunk)-laid-out state, then all-gather the updated slices
    back into the tp-local param. Stage 1 all-reduces the full grad
    first; stage 2 reduce-scatters so the full summed gradient never
    materializes in the update path.

    Telemetry keeps that property: the grad health stats are taken
    over each shard's SLICE of the mean grad (the slices partition the
    padded flat grad; zero padding contributes 0 to both sumsq and the
    nonfinite count), then dp-combined as per-leaf scalars inside
    `grad_leaf_stats` — the full summed gradient still never
    materializes. Returns `(new_params, new_state, grad_aux)`;
    grad_aux is None when telemetry is off."""
    inv = jnp.float32(1.0 / ctx.dp)
    names = list(params)
    i = jax.lax.axis_index(DP_AXIS)
    sliced_p, sliced_g, local_state = {}, {}, {}
    for k in names:
        chunk = ctx._chunks[k]
        padded = ctx.dp * chunk
        if ctx.stage >= 2:
            gs = ordered_psum_scatter(_pad_flat(grads[k], padded),
                                      DP_AXIS) * inv
        else:
            gfull = ordered_psum(grads[k], DP_AXIS) * inv
            gs = jax.lax.dynamic_slice(_pad_flat(gfull, padded),
                                       (i * chunk,), (chunk,))
        sliced_p[k] = jax.lax.dynamic_slice(_pad_flat(params[k], padded),
                                            (i * chunk,), (chunk,))
        sliced_g[k] = gs
        # state leaves arrive as this shard's (1, 1, chunk) block
        local_state[k] = {slot: v.reshape(-1)
                          for slot, v in state[k].items()}
    new_slices, new_state = ctx.optimizer.functional_step(
        sliced_p, sliced_g, local_state, lr, t)
    new_params = {}
    for k in names:
        full = jax.lax.all_gather(new_slices[k], DP_AXIS).reshape(-1)
        new_params[k] = full[:ctx._loc_sizes[k]].reshape(ctx._loc_shapes[k])
    aux = None
    if ctx._telemetry is not None:
        aux = ctx._trmod.grad_leaf_stats(ctx, sliced_g, dp_reduce=True)
    return new_params, {k: {slot: v.reshape(1, 1, -1)
                            for slot, v in new_state[k].items()}
                        for k in names}, aux


# ------------------------------------------- degree-blind state layout
def _to_zero_layout(full, spec_dim: Optional[int], dp: int, tp: int,
                    chunk: int) -> np.ndarray:
    """Full logical array -> (dp, tp, chunk) sharded layout (host-side
    numpy; the inverse of `_from_zero_layout` at ANY dp)."""
    full = np.asarray(full)
    parts = (np.split(full, tp, axis=spec_dim) if spec_dim is not None
             else [full] * tp)
    blocks = []
    for part in parts:
        flat = np.ravel(part)
        flat = np.pad(flat, (0, dp * chunk - flat.size))
        blocks.append(flat.reshape(dp, chunk))
    return np.stack(blocks, axis=1)


def _from_zero_layout(arr, shape: Tuple[int, ...],
                      spec_dim: Optional[int], tp: int) -> np.ndarray:
    """(dp, tp, chunk) sharded layout -> full logical array. Degree
    blind: only the layout's own leading dim says what dp it was saved
    at; nothing else depends on it."""
    arr = np.asarray(arr)
    if spec_dim is None:
        flat = np.ravel(arr[:, 0])
        return flat[:int(np.prod(shape))].reshape(shape)
    loc_shape = list(shape)
    loc_shape[spec_dim] //= tp
    loc = int(np.prod(loc_shape))
    parts = [np.ravel(arr[:, j])[:loc].reshape(loc_shape)
             for j in range(tp)]
    return np.concatenate(parts, axis=spec_dim)


class ZeroTrainStep:
    """One jitted shard_map train step
    `(params, opt_state, batch, lr, t) -> (loss, params, opt_state)`
    over the unified (dp x tp) mesh, with the optimizer update sharded
    across dp per `stage` (see module docstring). Build once per
    (model, optimizer, degree); `init_state` places params/state, the
    instance is the step callable."""

    def __init__(self, model, optimizer, loss_fn=None, *, criterion=None,
                 dp: Optional[int] = None, tp: int = 1, stage: int = 1,
                 param_specs: Optional[Dict[str, P]] = None,
                 batch_specs: Optional[Sequence[P]] = None,
                 grad_accum: int = 1, devices=None,
                 telemetry=None, enable_telemetry: bool = False):
        if stage not in (0, 1, 2):
            raise ValueError(
                f"stage must be 0 (replicated baseline), 1 (ZeRO-1) or 2 "
                f"(ZeRO-2); got {stage} — stage 3 (param sharding) is the "
                "GSPMD GroupSharded surface (level='p_g_os')")
        opt_name = type(optimizer).__name__
        if opt_name in _NON_ELEMENTWISE:
            raise NotImplementedError(
                f"{opt_name} applies whole-tensor update rules; the "
                "dp-sliced update would change its math. Use an "
                "elementwise optimizer (SGD/Momentum/Adam/AdamW/...)")
        if getattr(optimizer, "_grad_clip", None) is not None:
            raise NotImplementedError(
                "grad_clip inside the sharded update would clip by the "
                "SLICE norm, not the global norm; clip before the step or "
                "use the GSPMD GroupSharded surface with "
                "HybridParallelClipGrad")
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = (loss_fn if loss_fn is not None
                        else model_loss(model, criterion))
        self.tp = int(tp)
        devs = device_order(devices)
        self.dp = int(dp) if dp is not None else max(
            len(devs) // self.tp, 1)
        if self.dp < 1:
            raise ValueError(f"dp must be >= 1, got {self.dp}")
        self.stage = int(stage)
        self.grad_accum = int(grad_accum)
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.param_specs = dict(param_specs or {})
        self.batch_specs = (tuple(batch_specs) if batch_specs is not None
                            else None)
        if self.grad_accum > 1 and self.batch_specs is not None and any(
                tuple(s) != (DP_AXIS,) for s in self.batch_specs):
            raise ValueError(
                "grad_accum > 1 splits every batch leaf along its local "
                "rows, so all batch_specs must be P('dp')")
        self.mesh = build_mesh(((DP_AXIS, self.dp), (TP_AXIS, self.tp)),
                               devices)
        self.devices = tuple(self.mesh.devices.reshape(-1))
        # per-param geometry, discovered at init_state/load time
        # dp=1 "sharding" is an identity: the 1/dp slice IS the whole
        # param, so the engine runs the stage-0 program outright — same
        # math, and literally the same executable, so bit-parity with
        # the replicated baseline is definitional rather than lucky
        # (even boundary reshapes steer XLA's FMA selection enough to
        # drift low bits otherwise)
        self._sharded = self.stage >= 1 and self.dp > 1
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        self._spec: Dict[str, P] = {}
        self._spec_dim: Dict[str, Optional[int]] = {}
        self._loc_shapes: Dict[str, Tuple[int, ...]] = {}
        self._loc_sizes: Dict[str, int] = {}
        self._chunks: Dict[str, int] = {}
        self._state_spec: Dict[str, Dict[str, P]] = {}
        self._step = None
        self._probes: Dict[int, object] = {}
        # ---- training observability (ISSUE 19), opt-in. The import is
        # lazy AND conditional: a telemetry-off trainer never imports
        # observability/training.py at all (poisoned-module pinned in
        # tests/test_training_obs.py — zero cost when off means zero
        # code, not just zero work).
        self._telemetry = None
        self._trmod = None
        if telemetry is not None or enable_telemetry:
            from ..observability import training as _trmod

            self._trmod = _trmod
            self._telemetry = (telemetry if telemetry is not None
                               else _trmod.TrainingTelemetry())
            self._telemetry.bind(
                dp=self.dp, tp=self.tp, stage=self.stage,
                device_ids=[d.id for d in self.devices])

    # ------------------------------------------------------------ geometry
    def _record_geometry(self, params: Dict[str, jnp.ndarray]) -> None:
        sizes = {DP_AXIS: self.dp, TP_AXIS: self.tp}
        for name, arr in params.items():
            shape = tuple(int(d) for d in arr.shape)
            spec = self.param_specs.get(name, P())
            self._shapes[name] = shape
            self._spec[name] = spec
            self._spec_dim[name] = tp_dim_spec(spec)
            loc = local_shape(shape, spec, sizes)
            self._loc_shapes[name] = loc
            self._loc_sizes[name] = int(np.prod(loc)) if loc else 1
            self._chunks[name] = max(
                math.ceil(self._loc_sizes[name] / self.dp), 1)

    def _slot_spec(self, name: str, slot_arr) -> P:
        """Stage-0 placement of one state slot: follow the param's tp
        spec when shaped like the param, else replicate (scalars)."""
        if tuple(slot_arr.shape) == self._shapes[name]:
            return self._spec[name]
        return P()

    # ------------------------------------------------------------ placement
    def init_state(self, params: Optional[Dict[str, jnp.ndarray]] = None):
        """Place full logical params on the mesh and build the sharded
        optimizer state; returns `(params, opt_state)` ready for the
        step callable."""
        if params is None:
            from ..jit.functional import extract_state

            params, _ = extract_state(self.model)
        params = {k: jnp.asarray(v) for k, v in params.items()}
        self._record_geometry(params)
        placed = {k: jax.device_put(
            v, NamedSharding(self.mesh, self._spec[k]))
            for k, v in params.items()}
        host_state = self.optimizer.functional_state(params)
        return placed, self.load_optimizer_state(
            {k: {s: np.asarray(v) for s, v in acc.items()}
             for k, acc in host_state.items()})

    def load_optimizer_state(self, host_state):
        """Full-logical host state -> placed sharded state for THIS
        (dp, tp, stage). Degree-blind restore: the host form carries no
        dp imprint, so state saved at any degree loads at any other."""
        if not self._shapes:
            raise RuntimeError(
                "call init_state() (or pass params to it) before "
                "load_optimizer_state — the engine needs param geometry")
        out = {}
        for name, acc in host_state.items():
            slots = {}
            for slot, arr in acc.items():
                arr = np.asarray(arr)
                if not self._sharded:
                    spec = self._slot_spec(name, arr)
                    slots[slot] = jax.device_put(
                        jnp.asarray(arr), NamedSharding(self.mesh, spec))
                    self._state_spec.setdefault(name, {})[slot] = spec
                else:
                    laid = _to_zero_layout(arr, self._spec_dim[name],
                                           self.dp, self.tp,
                                           self._chunks[name])
                    slots[slot] = jax.device_put(
                        jnp.asarray(laid),
                        NamedSharding(self.mesh, P(DP_AXIS, TP_AXIS)))
                    self._state_spec.setdefault(name, {})[slot] = \
                        P(DP_AXIS, TP_AXIS)
            out[name] = slots
        return out

    def save_optimizer_state(self, opt_state):
        """Placed sharded state -> full-logical host arrays (numpy),
        restorable at ANY dp via `load_optimizer_state`."""
        out = {}
        for name, acc in opt_state.items():
            slots = {}
            for slot, arr in acc.items():
                if not self._sharded:
                    slots[slot] = np.asarray(arr)
                else:
                    slots[slot] = _from_zero_layout(
                        arr, self._shapes[name], self._spec_dim[name],
                        self.tp)
            out[name] = slots
        return out

    # ----------------------------------------------------------- step build
    def _build(self, batch_len: int):
        pspec = {k: self._spec[k] for k in self._shapes}
        sspec = {k: dict(v) for k, v in self._state_spec.items()}
        bspec = (self.batch_specs if self.batch_specs is not None
                 else tuple(P(DP_AXIS) for _ in range(batch_len)))
        if len(bspec) != batch_len:
            raise ValueError(
                f"batch has {batch_len} leaves but batch_specs has "
                f"{len(bspec)}")
        ctx = self
        inv_dp = jnp.float32(1.0 / self.dp)

        def body(params, state, batch, lr, t):
            loss, grads = _accumulated_grads(ctx, params, batch)
            # pin the backward: without the barrier XLA fuses the grad
            # computation with its CONSUMERS, and the stage-0 (full
            # update) vs stage-1/2 (slice/gather) consumers steer it to
            # differently-ordered reductions — observed bit drift at
            # dp=1. The barrier makes the grads a sealed subprogram, so
            # every stage compiles the identical backward.
            loss, grads = jax.lax.optimization_barrier((loss, grads))
            loss = ordered_psum(loss, DP_AXIS) * inv_dp
            if not ctx._sharded:
                new_p, new_s, aux = _replicated_update(ctx, params, grads,
                                                       state, lr, t)
            else:
                new_p, new_s, aux = _sharded_update(ctx, params, grads,
                                                    state, lr, t)
            if ctx._telemetry is None:
                return loss, new_p, new_s
            # seal the update the same way the backward is sealed: the
            # health packing only CONSUMES barriered copies, so it
            # cannot steer how XLA compiles the update itself — the
            # telemetry-on step stays bit-identical to telemetry-off
            # (pinned across the whole (dp, stage) matrix in
            # tests/test_training_obs.py)
            loss, new_p, new_s, params, aux = jax.lax.optimization_barrier(
                (loss, new_p, new_s, params, aux))
            health = ctx._trmod.pack_health(ctx, loss, params, new_p, aux)
            return loss, new_p, new_s, health

        out_specs = ((P(), pspec, sspec) if self._telemetry is None
                     else (P(), pspec, sspec, P()))
        self._step = jax.jit(_shard_map(
            body, mesh=self.mesh,
            in_specs=(pspec, sspec, bspec, P(), P()),
            out_specs=out_specs,
            check_rep=False,  # noqa: COLLECTIVE-MESH — the ordered fixed-shard-order collectives and the (dp,tp,chunk) state outputs are per-shard by design; 0.4.x rep tracking can't see through custom_vjp boundaries
            ))

    def __call__(self, params, opt_state, batch, lr, t):
        """One training step. `batch` is a tuple of GLOBAL arrays
        (row-sharded over dp per batch_specs); `lr` scalar; `t` the
        1-based step count (drives Adam bias correction).

        With telemetry enabled the returned loss is the HOST float the
        telemetry plane drained (same value, already synced) — the one
        per-step host sync covers the caller's loss read too — and the
        call may raise `TrainingDiverged` when the sentinel trips."""
        tele = self._telemetry
        if tele is None:
            batch = tuple(batch)
            if self._step is None:
                self._build(len(batch))
            return self._step(params, opt_state, batch,
                              jnp.asarray(lr, jnp.float32),
                              jnp.asarray(t, jnp.int32))
        t_in = tele.clock()
        batch = tuple(batch)
        if self._step is None:
            self._build(len(batch))
        lr_ = jnp.asarray(lr, jnp.float32)
        t_ = jnp.asarray(t, jnp.int32)
        # tokens from batch SHAPE metadata — never a device read
        rows = batch[0].shape[0]
        tokens = (tele.tokens_per_step if tele.tokens_per_step is not None
                  else int(rows))
        t0 = tele.clock()
        loss, new_p, new_s, health = self._step(params, opt_state, batch,
                                                lr_, t_)
        t1 = tele.clock()
        host_loss = tele.record_step(
            health, step=int(t), tokens=tokens,
            batch_build_s=t0 - t_in, dispatch_s=t1 - t0)
        return host_loss, new_p, new_s

    # -------------------------------------------------------- observability
    @staticmethod
    def bytes_per_chip(tree) -> int:
        """Max-over-devices resident bytes of a placed pytree — THE
        1/dp measurement for the optimizer-state claim."""
        total = 0
        for arr in jax.tree_util.tree_leaves(tree):
            total += max(s.data.size * s.data.dtype.itemsize
                         for s in arr.addressable_shards)
        return total

    def optimizer_state_bytes_per_chip(self, opt_state) -> int:
        return self.bytes_per_chip(opt_state)

    def collective_seconds(self, samples: int = 3, rows: int = 1,
                           width: int = 1024) -> List[float]:
        """Measured wall seconds per fixed-order dp all-reduce of a
        replicated (rows, width) f32 buffer — the training twin of
        `TPContext.collective_seconds`. Feeds the
        `parallel_dp_collective_seconds` bench probe. On CPU meshes one
        dispatch's host overhead dominates — which is the honest
        number."""
        fn = self._probes.get((rows, width))
        if fn is None:
            mesh = self.mesh

            def reduce_one(y):
                return ordered_psum(y, DP_AXIS)

            def allreduce(x):
                return _shard_map(
                    reduce_one, mesh=mesh, in_specs=P(), out_specs=P(),
                    check_rep=False,  # noqa: COLLECTIVE-MESH — probe psum of a replicated buffer; rep tracking adds latency to the very overhead being measured
                    )(x)
            fn = jax.jit(allreduce)
            self._probes[(rows, width)] = fn
        x = jax.device_put(jnp.zeros((rows, width), jnp.float32),
                           NamedSharding(self.mesh, P()))
        fn(x).block_until_ready()              # compile + warm
        out = []
        for _ in range(max(int(samples), 1)):
            t0 = time.perf_counter()
            fn(x).block_until_ready()
            out.append(time.perf_counter() - t0)
        # the training twin of serving_tp_collective_seconds: same
        # registry, same construction-time-probe discipline (per-step
        # timing would measure dispatch queueing, not the collective)
        from ..observability import global_registry

        hist = global_registry().histogram(
            "parallel_dp_collective_seconds",
            "fixed-order dp all-reduce probe (ZeroTrainStep)")
        for s in out:
            hist.observe(s)
        return out

    def shard_step_seconds(self, samples: int = 3, rows: int = 128,
                           width: int = 128,
                           best_of: int = 3) -> Dict[str, float]:
        """Per-dp-shard straggler probe: a warmed best-of-N single-
        device micro-step (matmul-shaped) timed on EACH dp row's lead
        device, published as `training_shard_step_seconds{shard=}`.
        Same discipline as `collective_seconds`/`TPContext.
        collective_seconds`: two warm-up dispatches, then best-of-N per
        sample (`observability.training.probe_best_of` = min, monotone
        as trials are added) — so a shard whose BEST case is slow is a
        real straggler, not scheduler noise, and it shows up before it
        stalls the whole mesh at the next collective."""
        from ..observability import training as trmod

        fn = self._probes.get(("shard", rows, width))
        if fn is None:
            fn = jax.jit(lambda a: (a @ a.T).sum())
            self._probes[("shard", rows, width)] = fn
        out: Dict[str, float] = {}
        # enumerate over the mesh's (dp, tp) device grid rows — the
        # shard label cardinality is the dp degree, bounded by the mesh
        for shard, dev_row in enumerate(self.mesh.devices):
            dev = dev_row.reshape(-1)[0]
            x = jax.device_put(jnp.ones((rows, width), jnp.float32), dev)
            fn(x).block_until_ready()          # compile + warm
            fn(x).block_until_ready()
            best = []
            for _ in range(max(int(samples), 1)):
                trials = []
                for _ in range(max(int(best_of), 1)):
                    t0 = time.perf_counter()
                    fn(x).block_until_ready()
                    trials.append(time.perf_counter() - t0)
                best.append(trmod.probe_best_of(trials))
            if self._telemetry is not None:
                for s in best:
                    self._telemetry.observe_shard_step(str(shard), s)
            else:
                from ..observability import global_registry

                hist = global_registry().histogram(
                    "training_shard_step_seconds",
                    "warmed best-of-N per-dp-shard step-time probe",
                    labels={"shard": str(shard)})
                for s in best:
                    hist.observe(s)
            out[str(shard)] = trmod.probe_best_of(best)
        return out

    def describe(self) -> Dict[str, object]:
        return {
            "dp": self.dp,
            "tp": self.tp,
            "stage": self.stage,
            "grad_accum": self.grad_accum,
            "devices": [d.id for d in self.devices],
            "params": len(self._shapes),
            "chunk_elems": sum(self._chunks.values()),
            "telemetry": (self._telemetry.summary()
                          if self._telemetry is not None else None),
        }


def zero_train_step(model, optimizer, loss_fn=None, *, stage: int = 1,
                    **kwargs) -> ZeroTrainStep:
    """Builder form of `ZeroTrainStep` (the API named in ROADMAP item
    4): `step = zero_train_step(model, opt, stage=1); params, st =
    step.init_state(); loss, params, st = step(params, st, (x, y), lr,
    t)`."""
    return ZeroTrainStep(model, optimizer, loss_fn, stage=stage, **kwargs)


def save_optimizer_state(step: ZeroTrainStep, opt_state):
    """Module-level alias of the degree-blind save (mirrors the serving
    journal's snapshot helpers)."""
    return step.save_optimizer_state(opt_state)


def load_optimizer_state(step: ZeroTrainStep, host_state):
    return step.load_optimizer_state(host_state)


# ===================================================================
# paddle-compat GroupSharded surface (GSPMD sharding-annotation flavor)
# -------------------------------------------------------------------
# Ref: fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py,
# group_sharded_optimizer_stage2.py + python/paddle/distributed/
# sharding/group_sharded.py (upstream layout, unverified — mount empty).
#
# Paddle implements ZeRO with explicit param slicing, pre-forward
# allgathers, grad reduce-scatter hooks and rank-local optimizer
# updates. This surface keeps the TPU-native GSPMD equivalent —
# sharding ANNOTATIONS consumed by a jitted train step (stage 1:
# opt-state dim-0 sharded; stage 2: + grads constrained to the
# scattered layout; stage 3: + params sharded with gather-on-use
# scheduled by XLA) — and now shares the repo's one mesh substrate and
# bridges to the explicit shard_map engine above via
# `zero_train_step()`.
# ===================================================================

def _default_mesh(axis: str = "sharding"):
    devs = device_order()
    return build_mesh(((axis, len(devs)),))


class _ShardedBase(Layer):
    stage = None
    _shard_params = False

    def __init__(self, layer: Layer, optimizer=None, group=None,
                 sync_buffers: bool = False, device: str = "tpu",
                 segment_size: int = 2 ** 20, offload: bool = False,
                 hcg=None, **kwargs):
        super().__init__()
        self._layers = layer
        self._optimizer = optimizer
        self.offload = offload
        if offload:
            try:  # fail LOUDLY at construction, not mid-training
                jax.devices()[0].memory("pinned_host")
            except Exception as e:
                raise NotImplementedError(
                    "offload=True needs a backend with pinned_host memory "
                    f"support; {jax.devices()[0].platform} reports none"
                ) from e
        if hcg is not None and hcg.mesh is not None and \
                hcg.get_sharding_parallel_world_size() > 1:
            self.mesh = hcg.mesh
            self.axis = "sharding"
        elif group is not None and getattr(group, "mesh", None) is not None:
            self.mesh = group.mesh
            self.axis = group.axis_name
        else:
            self.mesh = _default_mesh()
            self.axis = "sharding"
        if self._shard_params:
            self._place_params()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # ------------------------------------------------ sharding hint trees
    def data_sharding(self):
        axes = tuple(a for a in self.mesh.axis_names
                     if a in ("dp", "sharding") and self.mesh.shape[a] > 1)
        return NamedSharding(self.mesh, P(axes if axes else None))

    def param_sharding(self):
        """Prefix sharding for params: stage 1/2 replicate params."""
        return NamedSharding(self.mesh, P())

    def param_shardings(self, params: dict):
        if not self._shard_params:
            sh = self.param_sharding()
            return {k: sh for k in params}
        return {k: shard_leaf(v, self.mesh, self.axis)
                for k, v in params.items()}

    def opt_state_shardings(self, opt_state: dict):
        """Moment slots shaped like the param shard dim-0; scalars repl.
        With offload=True the slots additionally live in pinned host memory
        (ZeRO-offload: HBM holds only params/grads/activations; XLA streams
        the moments in for the update)."""
        out = {}
        for pname, acc in opt_state.items():
            shardings = {}
            for slot, v in acc.items():
                sh = shard_leaf(v, self.mesh, self.axis)
                if self.offload:
                    sh = sh.with_memory_kind("pinned_host")
                shardings[slot] = sh
            out[pname] = shardings
        return out

    def grad_shardings(self, params: dict):
        if self.stage >= 2:
            return {k: shard_leaf(v, self.mesh, self.axis)
                    for k, v in params.items()}
        return {k: NamedSharding(self.mesh, P()) for k in params}

    def _place_params(self):
        for _, p in self._layers.named_parameters():
            p._data = jax.device_put(
                p._data, shard_leaf(p._data, self.mesh, self.axis))

    # ------------------------------------------ explicit-engine bridge
    def zero_train_step(self, loss_fn=None, criterion=None,
                        **kwargs) -> ZeroTrainStep:
        """The one-implementation bridge (ISSUE 16 satellite): build
        the explicit shard_map ZeRO step for THIS wrapper's model +
        optimizer at dp = the sharding axis size. Stage 3 has no
        shard_map twin — its gather-on-use param sharding is the GSPMD
        placement-tree contract — so it refuses."""
        if self.stage >= 3:
            raise NotImplementedError(
                "stage 3 (p_g_os) shards params via the GSPMD placement "
                "trees (param_shardings); the explicit shard_map engine "
                "covers stages 1/2")
        return ZeroTrainStep(self._layers, self._optimizer,
                             loss_fn, criterion=criterion,
                             dp=int(self.mesh.shape[self.axis]),
                             stage=self.stage, **kwargs)

    # ------------------------------------------------------- delegation
    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        out = self._layers.set_state_dict(sd, *a, **k)
        if self._shard_params:
            self._place_params()
        return out

    def get_all_parameters(self, convert2cpu: bool = False):
        """stage3 API: gather full params (device_put to replicated)."""
        repl = NamedSharding(self.mesh, P())
        for _, p in self._layers.named_parameters():
            p._data = jax.device_put(p._data, repl)
        return self._layers.parameters()


class GroupShardedStage2(_ShardedBase):
    stage = 2
    _shard_params = False


class GroupShardedStage3(_ShardedBase):
    stage = 3
    _shard_params = True


class GroupShardedOptimizerStage2:
    """Optimizer wrapper partitioning state over the sharding axis (ZeRO-1/2
    optimizer side). Delegates the whole surface; the sharded placement is
    applied by the jitted step through opt_state_shardings."""

    def __init__(self, params, optim, group=None, offload: bool = False,
                 device: str = "tpu", **kwargs):
        self._optim = optim
        self._params = params
        self.offload = offload
        self.group = group

    def __getattr__(self, name):
        return getattr(self._optim, name)

    def step(self):
        return self._optim.step()

    def minimize(self, *a, **k):
        return self._optim.minimize(*a, **k)


def group_sharded_parallel(model: Layer, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm: bool = False,
                           dp_group=None, exclude_layer=None):
    """paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(
            f"group_sharded_parallel level must be 'os' (ZeRO-1), 'os_g' "
            f"(ZeRO-2) or 'p_g_os' (ZeRO-3); got {level!r}")
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer=optimizer, group=group,
                                     offload=offload)
    else:
        wrapped = GroupShardedStage2(model, optimizer=optimizer, group=group,
                                     offload=offload)
        wrapped.stage = 1 if level == "os" else 2
    opt = GroupShardedOptimizerStage2(model.parameters(), optimizer,
                                      group=group, offload=offload)
    if scaler is not None:
        return wrapped, opt, scaler
    return wrapped, opt


def save_group_sharded_model(model, output, optimizer=None):
    """Gather-on-rank0 save (ref: group_sharded.py save util)."""
    from ..framework.io import save as _save

    if hasattr(model, "get_all_parameters"):
        model.get_all_parameters()
    _save(model.state_dict(), str(output) + ".pdparams")
    if optimizer is not None:
        inner = getattr(optimizer, "_optim", optimizer)
        _save(inner.state_dict(), str(output) + ".pdopt")
