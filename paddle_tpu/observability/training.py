"""Training observability plane (ISSUE 19).

The serving stack got its telemetry in ISSUEs 4/13 — a metrics
registry, SLO windows, a flight recorder and postmortem bundles. The
ZeRO trainer (ISSUE 16) was flying blind: one construction-time
collective probe, no per-step signal at all. This module brings the
same discipline to training, under the same two hard rules:

- **zero cost when off**: `ZeroTrainStep` imports this module lazily
  and only when a telemetry knob is set, so a telemetry-off trainer
  executes (and imports) zero training-observability code
  (poisoned-module pinned in tests/test_training_obs.py);
- **one host sync per step when on**: every health scalar — loss,
  global grad norm, param norm, update norm, NaN/Inf counts — is
  computed INSIDE the existing jitted step body and returned as one
  packed f32 vector alongside the loss, so the whole set rides a
  single device->host drain (`TrainingTelemetry._host_read`, the one
  noqa'd sync below). No extra executable is built (compile-count
  pinned) and the telemetry-on step is bit-identical in
  params/opt-state to telemetry-off: the health computation only
  *consumes* values the update already produced, behind the step's
  optimization barriers.

Pieces:

- traced helpers (`sumsq` / `nonfinite_count` / `combine_leaf_stats` /
  `pack_health`) — called from the step body at trace time; the
  cross-shard combines use the same fixed-shard-order `ordered_psum`
  as the update itself, so the packed vector is replicated and
  deterministic;
- `TrainingTelemetry` — resolve-once handles for the
  `training_step_phase_seconds{phase=}` histograms (batch_build /
  dispatch / host_drain), tokens/sec and tokens/sec/chip gauges,
  health gauges and step/token/host-sync counters, all labelled with
  the bounded {dp, tp, stage} geometry; a host-side ring of recent
  step scalars; flight-recorder events per step;
- `DivergenceSentinel` — sliding-window monitor (reusing
  `HistogramWindow` bucket-delta means as the reference) over
  loss/grad-norm flagging nan / loss_spike / grad_spike / plateau;
  a tripped condition dumps a `paddle_tpu.postmortem/v1` *training*
  bundle through the existing `build_postmortem` machinery and raises
  the typed `TrainingDiverged`;
- `probe_best_of` — the straggler probe's min-estimator, shared with
  `ZeroTrainStep.shard_step_seconds` (same warmed best-of-N
  discipline as `TPContext.collective_seconds`).

What a training bundle deliberately does NOT capture: parameter,
gradient or optimizer-state VALUES, and batch contents. It carries
scalars only — the recent step ring, the metrics snapshot, the
sentinel verdict and the mesh/stage geometry — so a bundle is always
small and never leaks weights.
"""
from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .flight_recorder import FlightRecorder, build_postmortem, \
    dump_postmortem
from .metrics import MetricsRegistry
from .slo import HistogramWindow

__all__ = [
    "HEALTH_FIELDS", "SCALER_FIELDS", "TRAINING_SNAPSHOT_SCHEMA",
    "SentinelConfig", "DivergenceSentinel", "TrainingDiverged",
    "TrainingTelemetry", "probe_best_of",
    "sumsq", "nonfinite_count", "combine_leaf_stats", "pack_health",
]

# the packed in-executable health vector, index-aligned with
# `pack_health` below (tests and the report CLI index by this tuple)
HEALTH_FIELDS = ("loss", "grad_norm", "param_norm", "update_norm",
                 "nonfinite_grads", "nonfinite_params")

# bf16 mixed-precision extras appended AFTER the six health scalars
# when the trainer runs with dynamic loss scaling (param_dtype="bf16"):
# the post-transition scale and a 0/1 skipped-step flag. HEALTH_FIELDS
# stays a 6-tuple — existing indexers are untouched; `record_step`
# keys off the drained vector's length.
SCALER_FIELDS = ("loss_scale", "skipped_step")

TRAINING_SNAPSHOT_SCHEMA = "paddle_tpu.training_telemetry/v1"

# host wall split of one __call__: build the batch tuple + lazy build,
# dispatch the one executable, drain the packed health vector
PHASES = ("batch_build", "dispatch", "host_drain")


def probe_best_of(trials):
    """Best-of-N estimator for the straggler probe: the MINIMUM of the
    timed trials. min is monotone non-increasing as trials are added —
    more trials can only tighten the estimate toward the true cost
    (pinned by tests) — which is what makes per-shard numbers
    comparable: every shard reports its best case, so a consistently
    slower best IS a straggler, not scheduler noise."""
    return min(trials)


# --------------------------------------------------------------- traced
# in-executable health scalars. These run INSIDE the jitted step body
# (zero.py calls them at trace time); they must never touch the host.
# graftlint's HOST-SYNC rule audits `pack_health` by name via
# DEFAULT_HOT_MODULES.

def sumsq(x):
    """f32 sum of squares of one leaf (flattened)."""
    import jax.numpy as jnp

    return jnp.sum(jnp.square(x.astype(jnp.float32).reshape(-1)))


def nonfinite_count(x):
    """f32 count of NaN/Inf elements in one leaf."""
    import jax.numpy as jnp

    return jnp.sum((~jnp.isfinite(x)).astype(jnp.float32)).astype(
        jnp.float32)


def combine_leaf_stats(vec, tp_mask, dp_reduce: bool):
    """Cross-shard combine of per-leaf stat rows (nleaves, k).

    `dp_reduce=True` sums rows over the dp axis first (stage-2 slices
    partition each leaf across dp shards; replicated rows must NOT be
    dp-reduced or they multiply by dp). tp-sharded leaves additionally
    need their tp parts summed: `tp_mask` is a (nleaves, 1) 0/1 trace
    constant — masked rows go through a tp psum (replicated rows
    contribute exact zeros there), unmasked rows pass through. Both
    combines are the same fixed-shard-order `ordered_psum` the update
    uses, so the result is deterministic and replicated."""
    from ..parallel.mesh import DP_AXIS, TP_AXIS, ordered_psum

    if dp_reduce:
        vec = ordered_psum(vec, DP_AXIS)
    if tp_mask is not None:
        vec = vec * (1.0 - tp_mask) + ordered_psum(vec * tp_mask, TP_AXIS)
    return vec


def tp_leaf_mask(ctx, names):
    """(nleaves, 1) 0/1 mask of tp-sharded leaves for `ctx` (a
    ZeroTrainStep), or None when no leaf is tp-sharded (skips the tp
    combine entirely — the common tp=1 case adds no collective)."""
    import jax.numpy as jnp

    flags = [1.0 if ctx._spec_dim.get(k) is not None else 0.0
             for k in names]
    if not any(flags):
        return None
    return jnp.asarray(flags, jnp.float32)[:, None]


def grad_leaf_stats(ctx, per_leaf, dp_reduce: bool):
    """Reduce per-leaf local (sumsq, nonfinite) gradient pairs to the
    global `(grad_sumsq, nonfinite_grads)` aux scalars the step body
    threads to `pack_health`. `per_leaf` is an ordered {name: leaf}
    dict of the leaves the stats were taken over (full mean grads in
    the replicated path, this shard's scatter slices in the sharded
    path — the slices partition the padded flat grad, and the zero
    padding contributes exactly 0 to both stats)."""
    import jax.numpy as jnp

    names = list(per_leaf)
    rows = jnp.stack([jnp.stack([sumsq(per_leaf[k]),
                                 nonfinite_count(per_leaf[k])])
                      for k in names])
    vec = combine_leaf_stats(rows, tp_leaf_mask(ctx, names), dp_reduce)
    return jnp.sum(vec[:, 0]), jnp.sum(vec[:, 1])


def pack_health(ctx, loss, old_params, new_params, grad_aux,
                extras=None):
    """Pack the six HEALTH_FIELDS scalars into ONE replicated f32
    vector — the single extra output of the telemetry-on step body,
    drained by `TrainingTelemetry._host_read` in one transfer.
    Param/update stats are computed from the (replicated-across-dp,
    tp-local) old/new params, with tp-sharded leaves combined over the
    tp axis; `grad_aux` arrives pre-reduced from `grad_leaf_stats`.
    `extras` (bf16 mode) appends the SCALER_FIELDS scalars — same
    vector, same single drain: mixed precision adds zero host
    syncs."""
    import jax.numpy as jnp

    names = list(new_params)
    rows = jnp.stack([jnp.stack([
        sumsq(new_params[k]),
        sumsq(new_params[k] - old_params[k]),
        nonfinite_count(new_params[k]),
    ]) for k in names])
    vec = combine_leaf_stats(rows, tp_leaf_mask(ctx, names),
                             dp_reduce=False)
    gsq, nfg = grad_aux
    fields = [
        loss.astype(jnp.float32),
        jnp.sqrt(gsq),
        jnp.sqrt(jnp.sum(vec[:, 0])),
        jnp.sqrt(jnp.sum(vec[:, 1])),
        nfg,
        jnp.sum(vec[:, 2]),
    ]
    if extras is not None:
        fields.extend(e.astype(jnp.float32) for e in extras)
    return jnp.stack(fields)


# ------------------------------------------------------------- sentinel
@dataclass(frozen=True)
class SentinelConfig:
    """Divergence sentinel thresholds. The window references are
    HistogramWindow bucket-delta means re-anchored every `window`
    steps; spike verdicts compare the current value against the LAST
    COMPLETED window's mean, so a single noisy step inside a window
    never moves its own reference."""

    window: int = 32            # steps per reference window
    warmup_steps: int = 8       # no spike/plateau verdicts before this
    loss_spike_factor: float = 3.0
    grad_spike_factor: float = 10.0
    plateau_steps: int = 200    # steps without best-loss improvement
    plateau_rtol: float = 1e-3  # relative improvement that resets it
    # conditions that RAISE TrainingDiverged (others only flag + count;
    # plateau defaults to flag-only — a stalled run is a tuning
    # problem, not a crash)
    trip_on: Tuple[str, ...] = ("nan", "loss_spike", "grad_spike")
    max_bundles: int = 1        # postmortem bundles per sentinel life

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"window must be >= 1 (got {self.window})")
        if self.loss_spike_factor <= 1.0 or self.grad_spike_factor <= 1.0:
            raise ValueError("spike factors must be > 1")
        unknown = set(self.trip_on) - {"nan", "loss_spike",
                                       "grad_spike", "plateau"}
        if unknown:
            raise ValueError(f"unknown trip conditions: {sorted(unknown)}")


class TrainingDiverged(RuntimeError):
    """Typed divergence signal: the sentinel tripped. Carries the
    verdict dict, the dumped bundle path (None if no postmortem_dir)
    and the bundle itself for in-process handling."""

    def __init__(self, message: str, *, verdict: Dict[str, Any],
                 bundle_path: Optional[str] = None,
                 bundle: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.verdict = verdict
        self.bundle_path = bundle_path
        self.bundle = bundle


class DivergenceSentinel:
    """Sliding-window loss/grad-norm monitor.

    Observations land in two wide log-bucket histograms
    (`training_loss_observations` / `training_grad_norm_observations`);
    `HistogramWindow`s over them supply the per-window mean that
    becomes the spike reference — no second accumulator, the windows
    are pure bucket-delta views (slo.py discipline). `check` is the
    hot path: a handful of float compares per step.
    """

    CONDITIONS = ("nan", "loss_spike", "grad_spike", "plateau")

    def __init__(self, registry: MetricsRegistry,
                 config: Optional[SentinelConfig] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.config = config or SentinelConfig()
        lab = dict(labels or {})
        # wide range: losses/grad norms are not latency-shaped
        self._loss_hist = registry.histogram(
            "training_loss_observations",
            "per-step training loss (sentinel window source)",
            labels=lab or None, lo=1e-9, hi=1e9, growth=2.0 ** 0.5)
        self._grad_hist = registry.histogram(
            "training_grad_norm_observations",
            "per-step global grad norm (sentinel window source)",
            labels=lab or None, lo=1e-9, hi=1e9, growth=2.0 ** 0.5)
        self._loss_win = HistogramWindow(self._loss_hist)
        self._grad_win = HistogramWindow(self._grad_hist)
        self._flag_counters = {
            c: registry.counter(
                "training_sentinel_flags_total",
                "sentinel conditions flagged (tripped or not)",
                labels={**lab, "condition": c})
            for c in self.CONDITIONS
        }
        self._loss_ref: Optional[float] = None
        self._grad_ref: Optional[float] = None
        self._in_window = 0
        self._seen = 0
        self._best_loss = math.inf
        self._best_step = 0
        self._last_verdict: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------ hot path
    def check(self, *, step: int, loss: float, grad_norm: float,
              nonfinite: float) -> Optional[Dict[str, Any]]:
        """Feed one step's scalars; returns a verdict dict when a
        condition fires (caller decides whether `tripped` escalates),
        else None."""
        self._seen += 1
        cfg = self.config
        if nonfinite > 0 or loss != loss or grad_norm != grad_norm \
                or math.isinf(loss) or math.isinf(grad_norm):
            return self._verdict(
                "nan", step, loss, grad_norm,
                detail=f"nonfinite={nonfinite:g}")
        self._loss_hist.observe(loss)
        self._grad_hist.observe(grad_norm)
        warm = self._seen > cfg.warmup_steps
        if warm and self._loss_ref is not None and \
                loss > cfg.loss_spike_factor * max(self._loss_ref, 1e-12):
            return self._verdict(
                "loss_spike", step, loss, grad_norm,
                detail=f"ref={self._loss_ref:g} "
                       f"factor={cfg.loss_spike_factor:g}")
        if warm and self._grad_ref is not None and \
                grad_norm > cfg.grad_spike_factor * max(self._grad_ref,
                                                        1e-12):
            return self._verdict(
                "grad_spike", step, loss, grad_norm,
                detail=f"ref={self._grad_ref:g} "
                       f"factor={cfg.grad_spike_factor:g}")
        if loss < self._best_loss * (1.0 - cfg.plateau_rtol):
            self._best_loss = loss
            self._best_step = step
        elif warm and step - self._best_step >= cfg.plateau_steps:
            self._best_step = step  # re-arm: one flag per plateau span
            return self._verdict(
                "plateau", step, loss, grad_norm,
                detail=f"best={self._best_loss:g} over last "
                       f"{cfg.plateau_steps} steps")
        self._in_window += 1
        if self._in_window >= cfg.window:
            self._roll_window()
        return None

    def _roll_window(self) -> None:
        """Close the current window: its mean becomes the next spike
        reference, and both windows re-anchor."""
        if self._loss_win.count:
            self._loss_ref = self._loss_win.sum / self._loss_win.count
        if self._grad_win.count:
            self._grad_ref = self._grad_win.sum / self._grad_win.count
        self._loss_win.anchor()
        self._grad_win.anchor()
        self._in_window = 0

    def _verdict(self, condition: str, step: int, loss: float,
                 grad_norm: float, detail: str) -> Dict[str, Any]:
        self._flag_counters[condition].inc()
        v = {
            "condition": condition,
            "step": step,
            "loss": loss,
            "grad_norm": grad_norm,
            "detail": detail,
            "tripped": condition in self.config.trip_on,
            "message": f"sentinel: {condition} at step {step} "
                       f"(loss={loss:g} grad_norm={grad_norm:g}; "
                       f"{detail})",
        }
        self._last_verdict = v
        return v

    # ----------------------------------------------------------- cold path
    def state(self) -> Dict[str, Any]:
        """JSON-able sentinel state for snapshots and bundles."""
        return {
            "seen": self._seen,
            "loss_ref": self._loss_ref,
            "grad_ref": self._grad_ref,
            "best_loss": (None if math.isinf(self._best_loss)
                          else self._best_loss),
            "best_step": self._best_step,
            "flags": {c: self._flag_counters[c].value
                      for c in self.CONDITIONS},
            "last_verdict": self._last_verdict,
            "config": asdict(self.config),
        }


# ------------------------------------------------------------ telemetry
class TrainingTelemetry:
    """The per-trainer telemetry plane. Construct (optionally with your
    own registry/recorder/sentinel config), pass as
    `ZeroTrainStep(..., telemetry=...)` — or let
    `enable_telemetry=True` build this default. The trainer calls
    `bind()` once with its geometry (resolve-handles-once, metrics.py
    discipline) and `record_step()` once per step."""

    PHASES = PHASES

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 sentinel: Optional[SentinelConfig] = None,
                 enable_sentinel: bool = True,
                 recorder: Optional[FlightRecorder] = None,
                 enable_recorder: bool = True,
                 postmortem_dir: Optional[str] = None,
                 tokens_per_step: Optional[int] = None,
                 history: int = 128,
                 clock=time.perf_counter):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.recorder = recorder if recorder is not None else (
            FlightRecorder(256, clock=clock) if enable_recorder else None)
        self.postmortem_dir = postmortem_dir
        self.tokens_per_step = tokens_per_step
        self.clock = clock
        self._sentinel_cfg = sentinel if enable_sentinel else None
        if enable_sentinel and sentinel is None:
            self._sentinel_cfg = SentinelConfig()
        self.sentinel: Optional[DivergenceSentinel] = None
        self._ring: deque = deque(maxlen=max(int(history), 1))
        self._bundles_dumped = 0
        self.geometry: Dict[str, Any] = {}
        self._bound = False

    # ---------------------------------------------------------------- bind
    def bind(self, *, dp: int, tp: int, stage: int,
             device_ids: List[int]) -> None:
        """Resolve every metric handle once for this trainer's bounded
        {dp, tp, stage} label set. Idempotent for identical geometry;
        a second bind with different geometry is a bug (one telemetry
        plane per trainer)."""
        geometry = {"dp": int(dp), "tp": int(tp), "stage": int(stage),
                    "devices": [int(d) for d in device_ids]}
        if self._bound:
            if geometry != self.geometry:
                raise ValueError(
                    f"telemetry already bound to {self.geometry}; "
                    f"rebinding to {geometry} would mix series — build "
                    "one TrainingTelemetry per trainer")
            return
        self.geometry = geometry
        lab = {"dp": str(geometry["dp"]), "tp": str(geometry["tp"]),
               "stage": str(geometry["stage"])}
        self._labels = lab
        self._n_chips = max(len(geometry["devices"]), 1)
        reg = self.registry
        self._phase = {
            ph: reg.histogram(
                "training_step_phase_seconds",
                "host wall split of one train step by phase",
                labels={**lab, "phase": ph})
            for ph in PHASES
        }
        self._step_wall = reg.histogram(
            "training_step_seconds",
            "end-to-end host wall of one train step", labels=lab)
        self._steps = reg.counter(
            "training_steps_total", "train steps completed", labels=lab)
        self._tokens = reg.counter(
            "training_tokens_total", "tokens consumed", labels=lab)
        self._host_syncs = reg.counter(
            "training_host_syncs_total",
            "device->host drains (exactly one per step)", labels=lab)
        self._nonfinite_total = reg.counter(
            "training_nonfinite_total",
            "nonfinite grad/param elements seen", labels=lab)
        self._tps = reg.gauge(
            "training_tokens_per_sec",
            "tokens/sec over the last step's wall", labels=lab)
        self._tps_chip = reg.gauge(
            "training_tokens_per_sec_per_chip",
            "tokens/sec/chip over the last step's wall", labels=lab)
        self._health_gauges = {
            name: reg.gauge(f"training_{name}",
                            f"last step's {name}", labels=lab)
            for name in ("loss", "grad_norm", "param_norm", "update_norm")
        }
        # ---- ISSUE 20: comms visibility + mixed-precision scaler.
        # All label sets bounded (2 collectives, 2 scale events), so
        # resolve-once at bind keeps the hot path allocation-free.
        self._comm = {
            c: reg.histogram(
                "training_comm_seconds",
                "warmed best-of-N ZeRO collective probe "
                "(reduce-scatter / all-gather wall seconds)",
                labels={**lab, "collective": c})
            for c in ("reduce_scatter", "all_gather")
        }
        self._overlap_gauge = reg.gauge(
            "training_overlap_fraction",
            "measured fraction of bucket-collective wall hidden by the "
            "ring pipeline", labels=lab)
        self._loss_scale_gauge = reg.gauge(
            "training_loss_scale",
            "current dynamic loss scale (bf16 mixed precision)",
            labels=lab)
        self._scale_events = {
            ev: reg.counter(
                "training_loss_scale_events_total",
                "dynamic loss-scale transitions",
                labels={**lab, "event": ev})
            for ev in ("backoff", "growth")
        }
        self._skipped_steps = reg.counter(
            "training_skipped_steps_total",
            "optimizer steps skipped on nonfinite grads "
            "(dynamic loss scaling)", labels=lab)
        self._last_scale: Optional[float] = None
        self._overlap_fraction: Optional[float] = None
        if self._sentinel_cfg is not None:
            self.sentinel = DivergenceSentinel(
                reg, self._sentinel_cfg, labels=lab)
        self._bound = True

    # ------------------------------------------------------------ hot path
    def _host_read(self, health) -> List[float]:
        """THE one device->host sync of a telemetry-on step: drain the
        packed health vector. Everything record_step consumes is a
        plain host float after this."""
        host = np.asarray(health)  # noqa: HOST-SYNC — the ONE intentional per-step drain: all six health scalars ride this single transfer (zero-extra-sync pin in tests/test_training_obs.py)
        return [float(v) for v in host]  # noqa: HOST-SYNC — host-side unpack of the already-drained numpy vector, not a second device sync

    def record_step(self, health, *, step: int, tokens: int,
                    batch_build_s: float, dispatch_s: float) -> float:
        """Record one completed step: drains `health` (the step body's
        packed vector) in the one host sync, observes the three phase
        histograms, refreshes throughput + health gauges, appends to
        the step ring, records a flight-recorder event and runs the
        sentinel. Returns the host loss (the trainer hands it back to
        the caller so the caller's own loss read is NOT a second
        sync). Raises TrainingDiverged when the sentinel trips."""
        t0 = self.clock()
        vals = self._host_read(health)
        drain_s = self.clock() - t0
        loss, grad_norm, param_norm, update_norm, nfg, nfp = vals[:6]
        # bf16 mode appends the SCALER_FIELDS pair (same drain)
        loss_scale: Optional[float] = None
        skipped = False
        if len(vals) > 6:
            loss_scale = vals[6]
            skipped = vals[7] > 0.0
            prev = self._last_scale
            self._loss_scale_gauge.set(loss_scale)
            if prev is not None and loss_scale != prev:
                ev = "backoff" if loss_scale < prev else "growth"
                self._scale_events[ev].inc()
                if self.recorder is not None:
                    self.recorder.record(
                        "loss_scale", step=int(step), event=ev,
                        scale=loss_scale)
            self._last_scale = loss_scale
            if skipped:
                self._skipped_steps.inc()
        self._host_syncs.inc()
        self._steps.inc()
        self._tokens.inc(int(tokens))
        self._phase["batch_build"].observe(batch_build_s)
        self._phase["dispatch"].observe(dispatch_s)
        self._phase["host_drain"].observe(drain_s)
        wall = batch_build_s + dispatch_s + drain_s
        self._step_wall.observe(wall)
        tps = tokens / wall if wall > 0 else 0.0
        self._tps.set(tps)
        self._tps_chip.set(tps / self._n_chips)
        self._health_gauges["loss"].set(loss)
        self._health_gauges["grad_norm"].set(grad_norm)
        self._health_gauges["param_norm"].set(param_norm)
        self._health_gauges["update_norm"].set(update_norm)
        nonfinite = nfg + nfp
        if nonfinite > 0:
            self._nonfinite_total.inc(int(nonfinite))
        entry = {
            "step": int(step), "loss": loss, "grad_norm": grad_norm,
            "param_norm": param_norm, "update_norm": update_norm,
            "nonfinite": nonfinite, "tokens": int(tokens),
            "wall_s": wall,
        }
        if loss_scale is not None:
            entry["loss_scale"] = loss_scale
            entry["skipped"] = bool(skipped)
        self._ring.append(entry)
        if self.recorder is not None:
            self.recorder.record(
                "train_step", step=int(step), loss=loss,
                grad_norm=grad_norm, tokens=int(tokens), wall_s=wall)
        # a skipped step bypasses the sentinel entirely: its loss/grads
        # MAY be nonfinite, but the scaler already handled it (params
        # reverted, scale backed off) — a divergence trip would turn
        # the designed recovery path into a crash
        if self.sentinel is not None and not skipped:
            verdict = self.sentinel.check(
                step=int(step), loss=loss, grad_norm=grad_norm,
                nonfinite=nonfinite)
            if verdict is not None:
                if self.recorder is not None:
                    self.recorder.record(
                        "diverged", step=int(step),
                        condition=verdict["condition"],
                        tripped=verdict["tripped"])
                if verdict["tripped"]:
                    self._trip(verdict)
        return loss

    # ----------------------------------------------------------- cold path
    def _trip(self, verdict: Dict[str, Any]) -> None:
        """Tripped-sentinel path: build + (maybe) dump the training
        postmortem bundle, then raise. Deliberately NOT on the happy
        path — only a tripped verdict reaches here."""
        bundle = self.build_bundle(
            reason=f"diverged-{verdict['condition']}", verdict=verdict)
        path = None
        cfg = self.sentinel.config if self.sentinel is not None else None
        max_bundles = cfg.max_bundles if cfg is not None else 1
        if self.postmortem_dir and self._bundles_dumped < max_bundles:
            path = dump_postmortem(bundle, self.postmortem_dir,
                                   prefix="training-postmortem")
            self._bundles_dumped += 1
        raise TrainingDiverged(verdict["message"], verdict=verdict,
                               bundle_path=path, bundle=bundle)

    def build_bundle(self, reason: str,
                     verdict: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
        """A `paddle_tpu.postmortem/v1` bundle with the training
        section (`bundle["training"]`): recent step ring, sentinel
        state + verdict, geometry. Scalars only — never parameter,
        gradient or optimizer-state values (module docstring
        contract)."""
        bundle = build_postmortem(
            reason, recorder=self.recorder, registry=self.registry,
            info={"variant": "training", **self.geometry})
        bundle["training"] = {
            "schema": TRAINING_SNAPSHOT_SCHEMA,
            "geometry": dict(self.geometry),
            "steps": list(self._ring),
            "sentinel": (self.sentinel.state()
                         if self.sentinel is not None else None),
            "verdict": verdict,
        }
        return bundle

    def observe_comm(self, collective: str, seconds: float) -> None:
        """Publish one collective-probe measurement
        (`training_comm_seconds{collective=reduce_scatter|all_gather}`
        — resolve-once handles from bind)."""
        self._comm[collective].observe(seconds)

    def set_overlap_fraction(self, fraction: float) -> None:
        """Record the measured overlap fraction (see
        `ZeroTrainStep.measure_overlap_fraction`) — gauge + summary."""
        self._overlap_fraction = float(fraction)
        self._overlap_gauge.set(float(fraction))

    def observe_shard_step(self, shard: str, seconds: float) -> None:
        """Publish one straggler-probe measurement for a dp shard
        (bounded label: one series per dp row)."""
        self.registry.histogram(
            "training_shard_step_seconds",
            "warmed best-of-N per-dp-shard step-time probe",
            labels={**self._labels, "shard": str(shard)}).observe(seconds)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able telemetry snapshot (`tools/training_report.py`
        renders it): geometry, full metrics snapshot, step ring,
        sentinel state and the compact summary."""
        return {
            "schema": TRAINING_SNAPSHOT_SCHEMA,
            "geometry": dict(self.geometry),
            "metrics": self.registry.snapshot(),
            "steps": list(self._ring),
            "sentinel": (self.sentinel.state()
                         if self.sentinel is not None else None),
            "summary": self.summary(),
        }

    def summary(self) -> Dict[str, Any]:
        """Compact `describe()["telemetry"]` view."""
        if not self._bound:
            return {"bound": False}
        return {
            "bound": True,
            "geometry": dict(self.geometry),
            "steps": self._steps.value,
            "tokens": self._tokens.value,
            "host_syncs": self._host_syncs.value,
            "tokens_per_sec": self._tps.value,
            "tokens_per_sec_per_chip": self._tps_chip.value,
            "last": (dict(self._ring[-1]) if self._ring else None),
            "phases": {ph: h.summary() for ph, h in self._phase.items()},
            "comm": {c: h.summary() for c, h in self._comm.items()},
            "overlap_fraction": self._overlap_fraction,
            "loss_scale": self._last_scale,
            "skipped_steps": self._skipped_steps.value,
            "loss_scale_events": {
                ev: c.value for ev, c in self._scale_events.items()},
            "sentinel": (self.sentinel.state()
                         if self.sentinel is not None else None),
        }
