"""TPU-native autoregressive generation with a static-shape KV cache.

Ref surface: PaddleNLP `model.generate` (greedy/sampling; ecosystem atop
the reference fork — mount empty, layout unverified). TPU-first design:

- the KV cache is a pair of fixed-size arrays per layer, updated in place
  with `lax.dynamic_update_slice` (XLA keeps the buffer donated/aliased
  across steps — no reallocation, no dynamic shapes);
- prefill is ONE jitted call over the whole padded prompt; decode is ONE
  jitted single-token step reused for every position (two compilations
  total, both MXU-shaped);
- sampling (greedy / temperature / top-k / top-p) runs inside the jitted
  step with threefry keys, so the logits never leave the device.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit.functional import call_functional, extract_state
from ..nn import functional as F

__all__ = ["generate", "attend_with_cache", "init_caches"]


def attend_with_cache(q, k, v, cache, start_pos, rep, bias=None):
    """Write this block's K/V into the cache at `start_pos`, then attend q
    over the full (masked) cache.

    q: Tensor (b, s, heads, hd); k/v: Tensor (b, s, kv_heads, hd);
    cache: (k_cache, v_cache) raw jnp arrays (b, max_len, kv_heads, hd),
    OR a serving.PagedLayerCache — then the write/attend runs on the paged
    pool (ragged per-row positions, `start_pos` may be a (b,) vector) and
    every attention module here serves the continuous-batching engine
    unmodified; bias: optional additive (1, heads, s, max_len) attention
    bias (T5's relative position bias), folded into the visibility mask.
    Returns (ctx Tensor (b, s, heads, hd), new_cache).
    """
    if hasattr(cache, "page_table"):
        from ..serving.attention import paged_attend

        return paged_attend(q, k, v, cache, start_pos, rep, bias=bias)
    kc, vc = cache
    kd = k._data.astype(kc.dtype)
    vd = v._data.astype(vc.dtype)
    start = jnp.asarray(start_pos, jnp.int32)
    kc = jax.lax.dynamic_update_slice(kc, kd, (0, start, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, vd, (0, start, 0, 0))
    max_len = kc.shape[1]
    s = q.shape[1]
    kf, vf = kc, vc
    if rep > 1:  # GQA: expand kv heads to match q heads
        kf = jnp.repeat(kf, rep, axis=2)
        vf = jnp.repeat(vf, rep, axis=2)
    # position j visible to query i iff j <= start_pos + i
    pos_q = start + jnp.arange(s, dtype=jnp.int32)
    allowed = jnp.arange(max_len, dtype=jnp.int32)[None, :] <= pos_q[:, None]
    mask = jnp.where(allowed, 0.0, -1e9).astype(jnp.float32)[None, None]
    if bias is not None:
        bias_d = bias._data if hasattr(bias, "_data") else bias
        mask = mask + bias_d.astype(jnp.float32)
    ctx = F.scaled_dot_product_attention(
        q, Tensor(kf), Tensor(vf), attn_mask=Tensor(mask), is_causal=False)
    return ctx, (kc, vc)


def init_caches(model, batch, max_len, dtype=jnp.float32):
    """Zeroed (k, v) cache pair per decoder layer, sized from the config."""
    cfg = _config_of(model)
    kv_heads = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    shape = (batch, max_len, kv_heads, head_dim)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.num_hidden_layers)]


def _config_of(model):
    for attr in ("llama", "gpt"):
        if hasattr(model, attr):
            return getattr(model, attr).config
    if hasattr(model, "config"):
        return model.config
    raise ValueError("model exposes no config for cache sizing")


def _sample(logits, key, temperature, top_k, top_p):
    """Sample the next token from (b, vocab) logits inside jit."""
    if temperature == 0.0:  # greedy
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    vocab = logits.shape[-1]
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, min(top_k, vocab))[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p (first element always in)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(model, input_ids, max_new_tokens=32, temperature=1.0,
             top_k=0, top_p=1.0, eos_token_id: Optional[int] = None,
             seed: Optional[int] = None, cache_dtype=jnp.float32,
             num_beams: int = 1, length_penalty: float = 0.0):
    """Autoregressive generation. input_ids: Tensor/array (b, prompt_len).
    Returns a Tensor (b, prompt_len + max_new_tokens) of token ids; rows
    that hit `eos_token_id` are padded with eos afterwards.

    num_beams > 1 selects beam search (greedy within beams; temperature/
    top_k/top_p are sampling knobs and must stay at their defaults)."""
    if num_beams > 1:
        # temperature 0.0 (the library's greedy spelling) and 1.0 are both
        # fine — beam search is greedy within beams either way
        if temperature not in (0.0, 1.0) or top_k or top_p != 1.0:
            raise ValueError(
                "beam search (num_beams>1) does not combine with "
                "temperature/top_k/top_p sampling")
        return _beam_generate(model, input_ids, max_new_tokens, num_beams,
                              eos_token_id, cache_dtype, length_penalty)
    was_training = model.training
    model.eval()
    try:
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        b, prompt_len = ids.shape
        total = prompt_len + max_new_tokens
        params, buffers = extract_state(model)
        caches = init_caches(model, b, total, cache_dtype)
        if seed is None:
            # fresh entropy per call: unseeded sampling must differ between
            # calls (PaddleNLP generate semantics)
            seed = int(np.random.randint(0, 2 ** 31 - 1))
        key = jax.random.key(seed)

        # jitted steps are memoized on the model: jax's jit cache is keyed
        # by function identity, so fresh closures per call would recompile
        # every generate() invocation
        # key omissions are deliberate: `model` scopes the cache dict
        # itself (model.__dict__), `seed` enters as the traced key arg,
        # and num_beams>1 dispatched to _beam_generate above
        cache_key = (b, prompt_len, total, float(temperature), int(top_k),  # noqa: JIT-CACHE-KEY — omitted params scoped/traced, see above
                     float(top_p), jnp.dtype(cache_dtype).name,
                     eos_token_id)
        jit_cache = model.__dict__.setdefault("_generate_jit_cache", {})
        if cache_key not in jit_cache:
            def prefill(params, buffers, ids, caches):
                (logits, new_caches), _ = call_functional(
                    model, params, buffers, (Tensor(ids),),
                    kwargs={"caches": caches, "start_pos": 0},
                    training=False)
                return logits[:, -1], new_caches

            def decode(params, buffers, token, caches, pos, key, finished):
                (logits, new_caches), _ = call_functional(
                    model, params, buffers, (Tensor(token[:, None]),),
                    kwargs={"caches": caches, "start_pos": pos},
                    training=False)
                nxt = _sample(logits[:, 0], key, temperature, top_k, top_p)
                if eos_token_id is not None:
                    # already-finished rows keep emitting eos; the finished
                    # mask lives on device so steady-state decode never
                    # forces a per-token host round-trip (the host polls it
                    # only every _EOS_POLL steps)
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                return nxt, new_caches, finished

            jit_cache[cache_key] = (jax.jit(prefill),
                                    jax.jit(decode, donate_argnums=(3,)))
        prefill_j, decode_j = jit_cache[cache_key]

        last_logits, caches = prefill_j(params, buffers, ids, caches)
        key, sub = jax.random.split(key)
        token = _sample(last_logits, sub, temperature, top_k, top_p)

        finished = jnp.zeros((b,), bool)
        if eos_token_id is not None:
            finished = token == eos_token_id
        out = [ids, token[:, None]]
        _EOS_POLL = 16  # host-side early-exit check cadence
        for step in range(1, max_new_tokens):
            key, sub = jax.random.split(key)
            token, caches, finished = decode_j(
                params, buffers, token, caches,
                jnp.int32(prompt_len + step - 1), sub, finished)
            out.append(token[:, None])
            if (eos_token_id is not None and step % _EOS_POLL == 0
                    and bool(np.asarray(finished).all())):
                # all rows hit eos; pad the rest with eos and stop early
                remaining = max_new_tokens - 1 - step
                if remaining:
                    out.append(jnp.full((b, remaining), eos_token_id,
                                        ids.dtype))
                break
        return Tensor(jnp.concatenate(
            [o.astype(ids.dtype) for o in out], axis=1))
    finally:
        if was_training:
            model.train()


# ------------------------------------------------------------- beam search

def _beam_generate(model, input_ids, max_new_tokens, num_beams,
                   eos_token_id, cache_dtype, length_penalty):
    """Beam search over the same static-shape KV cache: beams ride the
    batch axis (b*k rows), each decode step is ONE jitted call — sample,
    score, and beam-reorder (a cache gather over the batch axis) all
    happen on device; the host loop only counts steps.

    Scores are summed token log-probs; finished beams (eos) are frozen
    and keep emitting eos with no score change. Final ranking divides by
    length**length_penalty (0.0 = raw sum, paddle's default shape)."""
    was_training = model.training
    model.eval()
    try:
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        b, prompt_len = ids.shape
        k = int(num_beams)
        total = prompt_len + max_new_tokens
        params, buffers = extract_state(model)
        caches = init_caches(model, b * k, total, cache_dtype)
        eos = -1 if eos_token_id is None else int(eos_token_id)

        # `model` scopes the cache dict itself; `length_penalty` is only
        # used in the eager post-loop ranking, never inside the traced fns
        cache_key = ("beam", b, k, prompt_len, total,  # noqa: JIT-CACHE-KEY — omitted params scoped/eager, see above
                     jnp.dtype(cache_dtype).name, eos)
        jit_cache = model.__dict__.setdefault("_generate_jit_cache", {})
        if cache_key not in jit_cache:
            def prefill(params, buffers, ids_rep, caches):
                (logits, new_caches), _ = call_functional(
                    model, params, buffers, (Tensor(ids_rep),),
                    kwargs={"caches": caches, "start_pos": 0},
                    training=False)
                logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
                # row-major beams: batch i occupies rows [i*k, (i+1)*k).
                # All k beams are identical after prefill, so beam 0 keeps
                # its top-k candidates and the rest start at -inf (else the
                # first step would pick k copies of the same argmax)
                lp = logp.reshape(b, k, -1)
                mask = jnp.where(jnp.arange(k)[None, :, None] == 0,
                                 0.0, -jnp.inf)
                tok, scores, beam_idx = _beam_select(lp + mask)
                return tok, scores, beam_idx, new_caches

            def decode(params, buffers, token, caches, pos, scores,
                       finished):
                (logits, new_caches), _ = call_functional(
                    model, params, buffers, (Tensor(token[:, None]),),
                    kwargs={"caches": caches, "start_pos": pos},
                    training=False)
                logp = jax.nn.log_softmax(
                    logits[:, 0].astype(jnp.float32)).reshape(b, k, -1)
                if eos >= 0:
                    # a finished beam contributes exactly one continuation:
                    # eos at zero cost (keeps its score; others -inf)
                    vocab = logp.shape[-1]
                    frozen = jnp.where(
                        jnp.arange(vocab)[None, None, :] == eos, 0.0,
                        -jnp.inf)
                    logp = jnp.where(finished.reshape(b, k)[..., None],
                                     frozen, logp)
                tok, new_scores, beam_idx = _beam_select(
                    logp + scores.reshape(b, k)[..., None])
                flat_src = (jnp.arange(b)[:, None] * k
                            + beam_idx).reshape(-1)
                new_caches = [(kc[flat_src], vc[flat_src])
                              for kc, vc in new_caches]
                new_finished = finished
                if eos >= 0:
                    new_finished = (finished.reshape(b, k)[
                        jnp.arange(b)[:, None], beam_idx].reshape(-1)
                        | (tok.reshape(-1) == eos))
                return (tok.reshape(-1), new_scores.reshape(-1),
                        flat_src, new_caches, new_finished)

            jit_cache[cache_key] = (jax.jit(prefill),
                                    jax.jit(decode, donate_argnums=(3,)))
        prefill_j, decode_j = jit_cache[cache_key]

        ids_rep = jnp.repeat(ids, k, axis=0)           # (b*k, prompt)
        tok, scores, beam_idx, caches = prefill_j(params, buffers, ids_rep,
                                                  caches)
        prev_tok = tok.reshape(-1)
        scores = scores.reshape(-1)
        finished = (prev_tok == eos) if eos >= 0 else \
            jnp.zeros((b * k,), bool)
        histories = [prev_tok[:, None]]                # per-step columns
        reorders = []                                  # per-step beam srcs

        _EOS_POLL = 16
        for step in range(1, max_new_tokens):
            prev_tok, scores, flat_src, caches, finished = decode_j(
                params, buffers, prev_tok, caches,
                jnp.int32(prompt_len + step - 1), scores, finished)
            reorders.append(flat_src)
            histories.append(prev_tok[:, None])
            if (eos >= 0 and step % _EOS_POLL == 0
                    and bool(np.asarray(finished).all())):
                break   # history length tracks the early exit

        # reconstruct each surviving beam's token history by walking the
        # reorder chain backwards (beams swap parents every step)
        cols = [histories[-1]]
        src = jnp.arange(b * k)
        for step in range(len(reorders) - 1, -1, -1):
            src = reorders[step][src]
            cols.append(histories[step][src])
        cols.reverse()
        gen = jnp.concatenate(cols, axis=1)            # (b*k, steps_run)
        if gen.shape[1] < max_new_tokens and eos >= 0:
            gen = jnp.concatenate(
                [gen, jnp.full((b * k, max_new_tokens - gen.shape[1]),
                               eos, gen.dtype)], axis=1)

        lengths = (jnp.argmax(gen == eos, axis=1) + 1
                   if eos >= 0 else jnp.full((b * k,), gen.shape[1]))
        lengths = jnp.where((gen == eos).any(axis=1) if eos >= 0
                            else jnp.zeros((b * k,), bool),
                            lengths, gen.shape[1])
        ranked = scores / jnp.maximum(
            lengths.astype(jnp.float32), 1.0) ** length_penalty
        best = jnp.argmax(ranked.reshape(b, k), axis=1)
        gen_best = gen[jnp.arange(b) * k + best]
        if eos >= 0:
            # pad everything after the first eos with eos
            hit = jnp.cumsum(gen_best == eos, axis=1) > 0
            after = jnp.concatenate(
                [jnp.zeros((b, 1), bool), hit[:, :-1]], axis=1)
            gen_best = jnp.where(after, eos, gen_best)
        return Tensor(jnp.concatenate(
            [ids, gen_best.astype(ids.dtype)], axis=1))
    finally:
        if was_training:
            model.train()


def _beam_select(scored):
    """(b, k, V) cumulative scores -> top-k over the flattened k*V
    continuations: returns tokens (b, k), scores (b, k), parent beam
    indices (b, k)."""
    b, k, v = scored.shape
    flat = scored.reshape(b, k * v)
    top_s, top_i = jax.lax.top_k(flat, k)
    return top_i % v, top_s, top_i // v
