"""Functionalize a stateful nn.Layer for jit/pjit.

Paddle's dy2static converts imperative models into static Programs (ref:
python/paddle/jit/dy2static/program_translator.py, upstream layout, unverified
— mount empty). The TPU-native equivalent is simpler and stronger: temporarily
re-bind every Parameter/buffer `_data` to traced jax values, run the Layer's
ordinary Python forward under `jax.jit` tracing, and collect mutated buffers
(e.g. BatchNorm running stats) as explicit outputs. One Layer definition thus
serves eager, jit, and pjit without a separate static graph mode.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Tuple

from ..core import tape as tape_mod
from ..core.rng import default_generator
from ..core.tensor import Tensor


def extract_state(layer) -> Tuple[Dict[str, object], Dict[str, object]]:
    """Pull (params, buffers) pytrees of raw jax arrays, keyed by qualified
    name. Param names follow named_parameters (structured names)."""
    params = {}
    for name, p in layer.named_parameters():
        params[name] = p._data
    buffers = {}
    for name, b in layer.named_buffers():
        if b is not None:
            buffers[name] = b._data
    return params, buffers


@contextlib.contextmanager
def bind_state(layer, params: Dict, buffers: Dict):
    """Re-bind layer state to the given arrays (typically tracers) for the
    duration of the context. On exit, yields mutated buffer values through the
    `out` dict and restores the original arrays."""
    param_objs = dict(layer.named_parameters())
    buffer_objs = {n: b for n, b in layer.named_buffers() if b is not None}
    saved = {}
    for name, p in param_objs.items():
        saved[id(p)] = p._data
        if name in params:
            p._data = params[name]
    for name, b in buffer_objs.items():
        saved[id(b)] = b._data
        if name in buffers:
            b._data = buffers[name]
    out = {"buffers": None}
    try:
        yield out
        # collect possibly-rebound buffer arrays (BN running stats etc.)
        out["buffers"] = {n: b._data for n, b in buffer_objs.items()}
    finally:
        for p in list(param_objs.values()) + list(buffer_objs.values()):
            p._data = saved[id(p)]


def call_functional(layer, params, buffers, args, kwargs=None, rng_key=None,
                    training=None):
    """Run `layer(*args)` as a pure function of (params, buffers, args).

    Returns (outputs_pytree_of_arrays, new_buffers). The autograd tape is
    disabled inside — differentiation happens at the jax level (jax.grad over
    this function), not via the eager tape.
    """
    kwargs = kwargs or {}
    wrapped_args = [a if a is None or isinstance(a, Tensor) else Tensor(a)
                    for a in args]
    old_training = layer.training
    if training is not None:
        layer.train() if training else layer.eval()
    try:
        with bind_state(layer, params, buffers) as out:
            ctx = (default_generator().trace_mode(rng_key)
                   if rng_key is not None else contextlib.nullcontext())
            with ctx, tape_mod.no_grad():
                result = layer(*wrapped_args, **kwargs)
        new_buffers = out["buffers"]
    finally:
        if training is not None:
            layer.train() if old_training else layer.eval()

    def unwrap(x):
        return x._data if isinstance(x, Tensor) else x

    import jax

    out_arrays = jax.tree_util.tree_map(
        unwrap, result, is_leaf=lambda x: isinstance(x, Tensor))
    return out_arrays, new_buffers
