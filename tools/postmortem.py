#!/usr/bin/env python
"""Render a flight-recorder post-mortem bundle (ISSUE 13).

A `paddle_tpu.postmortem/v1` bundle is the JSON file the serving stack
dumps when an engine is quarantined or a replica dies: the flight
recorder's last-N control-plane events, a metrics snapshot, the
per-request status table and the journal tail (counts only — the bundle
never carries token values; the RequestJournal owns exactly-once token
state). This tool turns one into the story a human reads first:

- the event timeline, relative to the first retained event, with the
  trace_summary conventions — `!!` for faults/quarantines/death, `>>`
  for migrations, `~` for restarts/adoptions;
- a casualty summary: how every request ended, failures flagged;
- the final metrics that matter at 3am (tokens, goodput, SLO
  attainment, restarts, step-phase p95s).

Usage:
    python tools/postmortem.py BUNDLE.json [--events N] [--metrics]

Standalone on purpose (json/argparse only): point it at a bundle from
any machine without installing the framework.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional

# trace_summary's convention: these terminal statuses are casualties
BAD_TERMINALS = ("failed", "expired", "shed")

# event kinds worth shouting about in the timeline
_ALARM_KINDS = {"fault", "quarantine", "dead", "diverged"}
_MOVE_KINDS = {"migrate"}
_RECOVER_KINDS = {"restart", "adopt"}


def load_bundle(path: str) -> dict:
    with open(path) as f:
        bundle = json.load(f)
    schema = bundle.get("schema", "")
    if not schema.startswith("paddle_tpu.postmortem/"):
        raise SystemExit(
            f"{path}: not a paddle_tpu post-mortem bundle "
            f"(schema={schema!r})")
    return bundle


def _event_detail(ev: dict) -> str:
    """One-line payload rendering, keyed on the event kind."""
    kind = ev.get("kind")
    if kind == "schedule":
        parts = [str(ev.get("decision", "?"))]
        for k in ("prefill", "decode", "chunks"):
            if ev.get(k) not in (None, 0):
                parts.append(f"{k}={ev[k]}")
        return " ".join(parts)
    if kind == "dispatch":
        bits = [str(ev.get("family", "?"))]
        for k in ("rid", "rows", "tokens", "horizon", "t_bucket",
                  "decode", "chunks"):
            if k in ev and ev[k] is not None:
                bits.append(f"{k}={ev[k]}")
        return " ".join(bits)
    if kind == "drain":
        return (f"{ev.get('family', '?')} rows={ev.get('rows', '?')} "
                f"tokens={ev.get('tokens', '?')}")
    if kind == "fault":
        tag = ("FATAL" if ev.get("fatal")
               else "transient" if ev.get("transient") else "persistent")
        retry = " (retry)" if ev.get("retry") else ""
        return (f"{tag} at {ev.get('site', '?')}{retry}: "
                f"{ev.get('error', '?')}")
    if kind == "quarantine":
        rids = ",".join(str(r) for r in ev.get("rids", ()))
        return f"site={ev.get('site', '?')} requests [{rids}]"
    if kind == "preempt":
        parked = " PARKED" if ev.get("parked") else ""
        return (f"request {ev.get('rid', '?')} "
                f"(#{ev.get('preemptions', '?')}){parked}")
    if kind == "terminal":
        err = f": {ev['error']}" if ev.get("error") else ""
        return f"request {ev.get('rid', '?')} -> {ev.get('status')}{err}"
    if kind == "restart":
        return (f"epoch {ev.get('epoch', '?')} ({ev.get('reason', '?')}) "
                f"readmitted={ev.get('readmitted', '?')}")
    if kind == "dead":
        return (f"reason={ev.get('reason', '?')} after "
                f"{ev.get('restarts', '?')} restart(s): "
                f"{ev.get('error')}")
    if kind == "migrate":
        return (f"request {ev.get('rid', '?')} "
                f"r{ev.get('src', '?')}->r{ev.get('dst', '?')} "
                f"as {ev.get('new_rid', '?')} "
                f"({ev.get('delivered', '?')} tokens delivered)")
    if kind == "adopt":
        return (f"request {ev.get('rid', '?')} "
                f"delivered={ev.get('delivered', '?')} "
                f"remaining={ev.get('remaining', '?')}")
    if kind == "train_step":
        return (f"step {ev.get('step', '?')} "
                f"loss={ev.get('loss', '?')} "
                f"grad_norm={ev.get('grad_norm', '?')} "
                f"tokens={ev.get('tokens', '?')}")
    if kind == "diverged":
        trip = "TRIPPED" if ev.get("tripped") else "flagged"
        return (f"{trip} {ev.get('condition', '?')} at step "
                f"{ev.get('step', '?')}")
    skip = {"seq", "t", "kind"}
    return " ".join(f"{k}={v}" for k, v in ev.items() if k not in skip)


def format_events(events: List[dict], events_total: int,
                  capacity: int, last: Optional[int] = None) -> str:
    if not events:
        return "  (empty ring — the recorder saw no events)"
    shown = events[-last:] if last else events
    t0 = shown[0].get("t", 0.0)
    lines = []
    dropped = events_total - len(events)
    if dropped > 0:
        lines.append(f"  ... {dropped} earlier event(s) evicted "
                     f"(ring capacity {capacity})")
    if len(shown) < len(events):
        lines.append(f"  ... {len(events) - len(shown)} retained "
                     "event(s) elided (--events)")
    for ev in shown:
        mark = ("!!" if ev.get("kind") in _ALARM_KINDS
                else ">>" if ev.get("kind") in _MOVE_KINDS
                else " ~" if ev.get("kind") in _RECOVER_KINDS
                else "  ")
        dt = (ev.get("t", t0) - t0) * 1e3
        lines.append(f"  {mark} +{dt:10.3f} ms  #{ev.get('seq', '?'):<6}"
                     f"{ev.get('kind', '?'):<11}{_event_detail(ev)}")
    return "\n".join(lines)


def format_requests(rows: List[dict]) -> str:
    if not rows:
        return "  (no requests registered on the engine)"
    lines = []
    counts: Dict[str, int] = {}
    for r in sorted(rows, key=lambda r: r.get("request_id", 0)):
        status = r.get("status", "?")
        counts[status] = counts.get(status, 0) + 1
        mark = " !!" if status in BAD_TERMINALS else ""
        slo = (f" slo={r['slo_class']}" if r.get("slo_class") else "")
        err = f"  ({r['error']})" if r.get("error") else ""
        lines.append(
            f"  request {r.get('request_id', '?'):<6}{status:<11}"
            f"{r.get('generated', 0):>5} tok  "
            f"{r.get('preemptions', 0)} preempt{slo}{err}{mark}")
    summary = ", ".join(f"{n} {st}" for st, n in sorted(counts.items()))
    bad = sum(counts.get(s, 0) for s in BAD_TERMINALS)
    lines.append("")
    lines.append(f"  {len(rows)} request(s): {summary}")
    if bad:
        lines.append(f"  !! {bad} of {len(rows)} did not finish")
    return "\n".join(lines)


def _metric_rows(snapshot: Optional[dict]) -> List[dict]:
    if not snapshot:
        return []
    return list(snapshot.get("metrics", ()))


def format_key_metrics(snapshot: Optional[dict]) -> str:
    """The final registry values worth reading first; `--metrics` dumps
    the full snapshot instead."""
    rows = _metric_rows(snapshot)
    if not rows:
        return "  (no metrics snapshot in this bundle)"
    lines = []

    def label_str(d):
        labels = d.get("labels") or {}
        return ("{" + ",".join(f"{k}={v}" for k, v in
                               sorted(labels.items())) + "}"
                if labels else "")

    wanted_values = (
        "serving_tokens_generated_total",
        "serving_slo_goodput_tokens_total",
        "serving_slo_attainment",
        "serving_requests_terminated_total",
        "serving_engine_restarts_total",
        "serving_preemptions_total",
        "serving_transient_retries_total",
        "serving_cluster_replica_deaths_total",
        "serving_cluster_migrations_total",
        "training_steps_total",
        "training_tokens_total",
        "training_host_syncs_total",
        "training_nonfinite_total",
        "training_tokens_per_sec_per_chip",
        "training_loss",
        "training_grad_norm",
    )
    for d in rows:
        if d.get("name") in wanted_values and "value" in d:
            v = d["value"]
            v = f"{v:g}" if isinstance(v, float) else str(v)
            lines.append(
                f"  {d['name'] + label_str(d):<58}{v:>10}")
    # step-phase p95s from the raw histogram rows, if present
    for d in rows:
        if d.get("name") in ("serving_step_phase_seconds",
                             "serving_device_residency_seconds",
                             "training_step_phase_seconds") \
                and d.get("count"):
            mean = d["sum"] / d["count"] if d["count"] else 0.0
            lines.append(
                f"  {d['name'] + label_str(d):<58}"
                f"{d['count']:>6} obs  mean {mean * 1e3:8.3f} ms")
    return "\n".join(lines) if lines else "  (no serving metrics found)"


def format_journal_tail(tail: List[dict]) -> str:
    if not tail:
        return "  (no journal attached)"
    lines = []
    for r in tail:
        status = r.get("status") or "live"
        mark = " !!" if status in BAD_TERMINALS else ""
        err = f"  ({r['error']})" if r.get("error") else ""
        lines.append(f"  request {r.get('request_id', '?'):<6}"
                     f"{status:<11}"
                     f"{r.get('delivered_tokens') or 0:>5} delivered"
                     f"{err}{mark}")
    return "\n".join(lines)


def format_training(training: dict) -> str:
    """Compact digest of a training bundle's section: verdict, recent
    step tail, sentinel flags. tools/training_report.py renders the
    full report (sparklines, phase breakdown, straggler table)."""
    lines = []
    geo = training.get("geometry") or {}
    lines.append(
        f"training run: dp={geo.get('dp', '?')} tp={geo.get('tp', '?')} "
        f"stage={geo.get('stage', '?')} "
        f"devices={len(geo.get('devices') or [])}")
    verdict = training.get("verdict")
    if verdict:
        mark = "!!" if verdict.get("tripped") else " ~"
        lines.append(f"  {mark} {verdict.get('message', verdict)}")
    sentinel = training.get("sentinel") or {}
    flags = {c: n for c, n in (sentinel.get("flags") or {}).items() if n}
    if flags:
        lines.append("  sentinel flags: " + ", ".join(
            f"{c}={n}" for c, n in sorted(flags.items())))
    steps = training.get("steps") or []
    lines.append(f"  step ring ({len(steps)} retained), last 8:")
    for s in steps[-8:]:
        nf = s.get("nonfinite", 0)
        mark = " !!" if (nf and nf > 0) else ""
        loss = s.get("loss")
        gnorm = s.get("grad_norm")
        lines.append(
            f"    step {s.get('step', '?'):<6}"
            f"loss={(f'{loss:g}' if isinstance(loss, float) else loss):<14}"
            f"grad_norm="
            f"{f'{gnorm:g}' if isinstance(gnorm, float) else gnorm}"
            f"{mark}")
    lines.append("  (full report: python tools/training_report.py "
                 "BUNDLE.json)")
    return "\n".join(lines)


def render(bundle: dict, last_events: Optional[int] = None,
           full_metrics: bool = False) -> str:
    out = []
    when = bundle.get("unix_time")
    stamp = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(when))
             if when else "?")
    out.append(f"post-mortem: {bundle.get('reason', '?')}")
    out.append(f"schema {bundle.get('schema')}   dumped {stamp}")
    info = bundle.get("info") or {}
    if info:
        out.append("info: " + json.dumps(info, sort_keys=True))
    out.append("")
    events = bundle.get("events") or []
    out.append(f"event timeline ({len(events)} retained of "
               f"{bundle.get('events_total', len(events))} recorded, "
               f"ring capacity {bundle.get('ring_capacity', '?')}):")
    out.append(format_events(events,
                             bundle.get("events_total", len(events)),
                             bundle.get("ring_capacity", 0),
                             last=last_events))
    out.append("")
    if bundle.get("training"):
        # training bundle variant (ISSUE 19): dumped by the ZeRO
        # trainer's divergence sentinel — there are no requests and no
        # journal, so render the training digest instead of an empty
        # serving casualty table
        out.append(format_training(bundle["training"]))
        out.append("")
    else:
        out.append("requests:")
        out.append(format_requests(bundle.get("requests") or []))
        out.append("")
        out.append("journal tail (token COUNTS only — the journal owns "
                   "token state):")
        out.append(format_journal_tail(bundle.get("journal_tail") or []))
        out.append("")
    if full_metrics:
        out.append("metrics snapshot:")
        out.append(json.dumps(bundle.get("metrics"), indent=1,
                              sort_keys=True))
    else:
        out.append("final metrics (--metrics for the full snapshot):")
        out.append(format_key_metrics(bundle.get("metrics")))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a paddle_tpu flight-recorder post-mortem "
                    "bundle (event timeline, casualty summary, final "
                    "metrics)")
    ap.add_argument("bundle", help="postmortem-*.json path")
    ap.add_argument("--events", type=int, default=None,
                    help="show only the last N timeline events")
    ap.add_argument("--metrics", action="store_true",
                    help="dump the full metrics snapshot instead of "
                         "the key-metrics digest")
    args = ap.parse_args(argv)
    print(render(load_bundle(args.bundle), last_events=args.events,
                 full_metrics=args.metrics))
    return 0


if __name__ == "__main__":
    sys.exit(main())
