"""Normalization layers. Ref: python/paddle/nn/layer/norm.py (upstream
layout, unverified). Running stats are non-trainable buffers updated in
forward; the jit functionalizer threads them as explicit state."""
from __future__ import annotations

from ...core.tensor import Tensor
from ...tensor.creation import ones, zeros
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", zeros([num_features]))
        self.register_buffer("_variance", ones([num_features]))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """On TPU under pjit, batch stats are computed over the *global* batch
    automatically when the batch axis is sharded (XLA inserts the cross-chip
    reduction) — so SyncBatchNorm is BatchNorm with the right sharding; the
    class exists for API parity and conversion."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.num_features, layer.momentum,
                                layer.epsilon,
                                data_format=layer.data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers["_mean"] = layer._mean
            new._buffers["_variance"] = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight,
                            self.bias, epsilon=self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else \
            self.create_parameter(shape=[num_channels], attr=weight_attr,
                                  default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            epsilon=self.epsilon,
                            data_format=self.data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else \
            self.create_parameter(shape=[num_features], attr=weight_attr,
                                  default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        raise NotImplementedError(
            "SpectralNorm is not implemented yet in paddle_tpu")
