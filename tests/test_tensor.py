"""Tensor wrapper behavior: creation, properties, methods, indexing,
in-place semantics. Pattern follows the reference's OpTest idea (SURVEY.md §4):
compare against NumPy reference results."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == np.float32
        np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])

    def test_default_float32_from_float64(self):
        t = paddle.to_tensor(np.zeros((2, 2), dtype=np.float64))
        assert t.dtype == np.float32

    def test_int_dtype(self):
        t = paddle.to_tensor([1, 2, 3])
        assert t.dtype in (np.int32, np.int64)

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])

    def test_arange_linspace_eye(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5),
            rtol=1e-6)
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3,
                                      dtype=np.float32))

    def test_random_shapes(self):
        assert paddle.rand([4, 5]).shape == [4, 5]
        assert paddle.randn([4, 5]).shape == [4, 5]
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))

    def test_seed_reproducible(self):
        paddle.seed(42)
        a = paddle.randn([8]).numpy()
        paddle.seed(42)
        b = paddle.randn([8]).numpy()
        np.testing.assert_array_equal(a, b)


class TestProperties:
    def test_shape_ndim_size(self):
        t = paddle.zeros([2, 3, 4])
        assert t.shape == [2, 3, 4]
        assert t.ndim == 3
        assert t.size == 24
        assert t.numel() == 24

    def test_T(self):
        t = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"))
        np.testing.assert_array_equal(t.T.numpy(), t.numpy().T)

    def test_item(self):
        assert paddle.to_tensor(3.5).item() == pytest.approx(3.5)

    def test_astype(self):
        t = paddle.to_tensor([1.7, 2.3]).astype("int32")
        assert t.dtype == np.int32


class TestMath:
    def test_binary_ops(self):
        a = paddle.to_tensor([4.0, 9.0])
        b = paddle.to_tensor([2.0, 3.0])
        np.testing.assert_allclose((a + b).numpy(), [6, 12])
        np.testing.assert_allclose((a - b).numpy(), [2, 6])
        np.testing.assert_allclose((a * b).numpy(), [8, 27])
        np.testing.assert_allclose((a / b).numpy(), [2, 3])
        np.testing.assert_allclose((a ** 2).numpy(), [16, 81])
        np.testing.assert_allclose((a % b).numpy(), [0, 0])
        np.testing.assert_allclose((2 + a).numpy(), [6, 11])
        np.testing.assert_allclose((1 - a).numpy(), [-3, -8])

    def test_unary_ops(self):
        a = paddle.to_tensor([1.0, 4.0])
        np.testing.assert_allclose(a.sqrt().numpy(), [1, 2])
        np.testing.assert_allclose(a.log().numpy(), np.log([1, 4]),
                                   rtol=1e-6)
        np.testing.assert_allclose((-a).numpy(), [-1, -4])
        np.testing.assert_allclose(abs(paddle.to_tensor([-2.0])).numpy(), [2])

    def test_matmul(self):
        a = np.random.rand(3, 4).astype("float32")
        b = np.random.rand(4, 5).astype("float32")
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)

    def test_matmul_transpose_flags(self):
        a = np.random.rand(4, 3).astype("float32")
        b = np.random.rand(5, 4).astype("float32")
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=True, transpose_y=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b.T, rtol=1e-5)

    def test_clip(self):
        a = paddle.to_tensor([-1.0, 0.5, 2.0])
        np.testing.assert_allclose(a.clip(0.0, 1.0).numpy(), [0, 0.5, 1])


class TestReduction:
    def test_sum_mean(self):
        x = np.random.rand(3, 4).astype("float32")
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t.sum().numpy(), x.sum(), rtol=1e-5)
        np.testing.assert_allclose(t.sum(axis=1).numpy(), x.sum(1),
                                   rtol=1e-5)
        np.testing.assert_allclose(t.mean(axis=0, keepdim=True).numpy(),
                                   x.mean(0, keepdims=True), rtol=1e-5)

    def test_max_min_argmax(self):
        x = np.array([[1.0, 5.0], [3.0, 2.0]], dtype="float32")
        t = paddle.to_tensor(x)
        assert t.max().item() == 5.0
        assert t.min().item() == 1.0
        np.testing.assert_array_equal(t.argmax(axis=1).numpy(), [1, 0])

    def test_cumsum(self):
        x = np.arange(6).reshape(2, 3).astype("float32")
        np.testing.assert_allclose(
            paddle.to_tensor(x).cumsum(axis=1).numpy(), x.cumsum(1))

    def test_std_var_unbiased(self):
        x = np.random.rand(10).astype("float32")
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t.std().numpy(), x.std(ddof=1), rtol=1e-4)
        np.testing.assert_allclose(t.var(unbiased=False).numpy(),
                                   x.var(), rtol=1e-4)


class TestManipulation:
    def test_reshape_transpose_flatten(self):
        x = np.arange(24).reshape(2, 3, 4).astype("float32")
        t = paddle.to_tensor(x)
        assert t.reshape([4, 6]).shape == [4, 6]
        assert t.reshape([-1, 6]).shape == [4, 6]
        np.testing.assert_array_equal(
            t.transpose([2, 0, 1]).numpy(), x.transpose(2, 0, 1))
        assert t.flatten().shape == [24]
        assert t.flatten(1, 2).shape == [2, 12]

    def test_squeeze_unsqueeze(self):
        t = paddle.zeros([2, 1, 3])
        assert t.squeeze(1).shape == [2, 3]
        assert t.unsqueeze(0).shape == [1, 2, 1, 3]
        assert t.unsqueeze([0, 2]).shape == [1, 2, 1, 1, 3]

    def test_concat_stack_split(self):
        a = paddle.ones([2, 3])
        b = paddle.zeros([2, 3])
        assert paddle.concat([a, b], axis=0).shape == [4, 3]
        assert paddle.stack([a, b], axis=0).shape == [2, 2, 3]
        parts = paddle.split(paddle.arange(10), 2)
        assert [p.shape for p in parts] == [[5], [5]]
        parts = paddle.split(paddle.arange(10), [3, -1])
        assert [p.shape for p in parts] == [[3], [7]]

    def test_gather_index_select(self):
        x = paddle.to_tensor(np.arange(12).reshape(3, 4).astype("float32"))
        idx = paddle.to_tensor([0, 2])
        np.testing.assert_array_equal(
            x.gather(idx).numpy(), x.numpy()[[0, 2]])
        np.testing.assert_array_equal(
            x.index_select(idx, axis=1).numpy(), x.numpy()[:, [0, 2]])

    def test_where(self):
        c = paddle.to_tensor([True, False])
        a = paddle.to_tensor([1.0, 1.0])
        b = paddle.to_tensor([2.0, 2.0])
        np.testing.assert_allclose(paddle.where(c, a, b).numpy(), [1, 2])

    def test_topk(self):
        x = paddle.to_tensor([[1.0, 9.0, 3.0], [7.0, 2.0, 5.0]])
        vals, idx = paddle.topk(x, k=2)
        np.testing.assert_allclose(vals.numpy(), [[9, 3], [7, 5]])
        np.testing.assert_array_equal(idx.numpy(), [[1, 2], [0, 2]])

    def test_sort_argsort(self):
        x = paddle.to_tensor([3.0, 1.0, 2.0])
        np.testing.assert_allclose(x.sort().numpy(), [1, 2, 3])
        np.testing.assert_array_equal(x.argsort().numpy(), [1, 2, 0])
        np.testing.assert_allclose(
            x.sort(descending=True).numpy(), [3, 2, 1])

    def test_tril_triu(self):
        x = paddle.ones([3, 3])
        assert x.tril().numpy().sum() == 6
        assert x.triu(1).numpy().sum() == 3

    def test_unique_nonzero_eager(self):
        x = paddle.to_tensor([3, 1, 3, 0])
        np.testing.assert_array_equal(x.unique().numpy(), [0, 1, 3])
        nz = paddle.nonzero(x)
        assert nz.shape == [3, 1]


class TestIndexing:
    def test_basic(self):
        x = paddle.to_tensor(np.arange(12).reshape(3, 4).astype("float32"))
        np.testing.assert_array_equal(x[0].numpy(), [0, 1, 2, 3])
        np.testing.assert_array_equal(x[:, 1].numpy(), [1, 5, 9])
        np.testing.assert_array_equal(x[1:, ::2].numpy(),
                                      x.numpy()[1:, ::2])

    def test_tensor_index(self):
        x = paddle.to_tensor(np.arange(12).reshape(3, 4).astype("float32"))
        idx = paddle.to_tensor([2, 0])
        np.testing.assert_array_equal(x[idx].numpy(), x.numpy()[[2, 0]])

    def test_bool_mask_getitem(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        m = x > 1.5
        np.testing.assert_allclose(x.masked_select(m).numpy(), [2, 3])

    def test_setitem(self):
        x = paddle.zeros([3, 3])
        x[1] = 5.0
        assert x.numpy()[1].sum() == 15
        x[0, 0] = paddle.to_tensor(2.0)
        assert x.numpy()[0, 0] == 2

    def test_setitem_grad_flows(self):
        x = paddle.ones([3], dtype="float32")
        x.stop_gradient = False
        y = x * 2
        y[0] = 0.0
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0, 2, 2])


class TestInplace:
    def test_add_(self):
        x = paddle.ones([2])
        x.add_(paddle.ones([2]))
        np.testing.assert_allclose(x.numpy(), [2, 2])

    def test_fill_zero(self):
        x = paddle.ones([2, 2])
        x.fill_(3.0)
        assert x.numpy().sum() == 12
        x.zero_()
        assert x.numpy().sum() == 0

    def test_set_value(self):
        x = paddle.zeros([2, 2])
        x.set_value(np.ones((2, 2), dtype="float32"))
        assert x.numpy().sum() == 4


class TestComparison:
    def test_compare_ops(self):
        a = paddle.to_tensor([1.0, 2.0, 3.0])
        b = paddle.to_tensor([2.0, 2.0, 2.0])
        np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
        np.testing.assert_array_equal((a == b).numpy(),
                                      [False, True, False])
        assert bool(paddle.allclose(a, a))

    def test_logical(self):
        a = paddle.to_tensor([True, False])
        b = paddle.to_tensor([True, True])
        np.testing.assert_array_equal((a & b).numpy(), [True, False])
        np.testing.assert_array_equal((~a).numpy(), [False, True])


class TestDtypePromotion:
    def test_float_int(self):
        a = paddle.to_tensor([1, 2])
        b = paddle.to_tensor([0.5, 0.5])
        assert (a + b).dtype == np.float32

    def test_cast_roundtrip(self):
        a = paddle.to_tensor([1.9])
        assert a.astype("int64").astype("float32").item() == 1.0

    def test_bfloat16(self):
        a = paddle.to_tensor([1.0, 2.0], dtype="bfloat16")
        assert a.dtype == paddle.bfloat16
        assert (a + a).dtype == paddle.bfloat16


class TestRound3Shims:
    """version / rank / shape / crop / index_put / broadcast_shape /
    LazyGuard parity shims."""

    def test_version(self):
        assert paddle.version.full_version
        paddle.version.show()
        assert paddle.version.cuda() is False

    def test_rank_and_shape(self):
        x = paddle.to_tensor(np.zeros((2, 3, 4), np.float32))
        assert int(paddle.rank(x).numpy()) == 3
        assert paddle.shape(x).numpy().tolist() == [2, 3, 4]

    def test_dtype_predicates(self):
        f = paddle.to_tensor(np.zeros(2, np.float32))
        c = paddle.to_tensor(np.zeros(2, np.complex64))
        assert paddle.is_floating_point(f) and not paddle.is_complex(f)
        assert paddle.is_complex(c) and not paddle.is_floating_point(c)

    def test_broadcast_shape(self):
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]

    def test_crop(self):
        x = paddle.to_tensor(np.arange(12).reshape(3, 4))
        out = paddle.crop(x, shape=[2, 2], offsets=[1, 1])
        assert out.numpy().tolist() == [[5, 6], [9, 10]]
        tail = paddle.crop(x, shape=[-1, 2], offsets=[1, 0])
        assert tail.numpy().tolist() == [[4, 5], [8, 9]]

    def test_index_put_set_and_accumulate(self):
        x = paddle.to_tensor(np.zeros(5, np.float32))
        idx = (paddle.to_tensor(np.array([1, 3, 1])),)
        v = paddle.to_tensor(np.array([7.0, 8.0, 2.0], np.float32))
        out = paddle.index_put(x, idx, v)
        assert out.numpy()[3] == 8.0
        acc = paddle.index_put(x, idx, v, accumulate=True)
        assert acc.numpy()[1] == 9.0  # 7 + 2

    def test_misc_shims(self):
        with paddle.LazyGuard():
            lin = paddle.nn.Linear(2, 2)
        assert lin.weight is not None
        paddle.disable_signal_handler()
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)
        paddle.set_printoptions(precision=4)


class TestRound3TensorMethods:
    def test_inplace_variants(self):
        t = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
        t.tril_()
        assert t.numpy()[0, 2] == 0 and t.numpy()[2, 0] == 6
        u = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
        u.triu_()
        assert u.numpy()[2, 0] == 0
        f = paddle.to_tensor(np.array([1.7, -2.3], np.float32))
        f.floor_()
        np.testing.assert_array_equal(f.numpy(), [1.0, -3.0])
        c = paddle.to_tensor(np.array([1.2], np.float32))
        c.ceil_()
        assert c.numpy()[0] == 2.0
        r = paddle.to_tensor(np.array([7.0, 9.0], np.float32))
        r.remainder_(paddle.to_tensor(np.array([4.0, 5.0], np.float32)))
        np.testing.assert_array_equal(r.numpy(), [3.0, 4.0])

    def test_apply_and_nbytes(self):
        t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = t.apply(lambda a: a * 3)
        np.testing.assert_array_equal(out.numpy(), [3.0, 6.0])
        np.testing.assert_array_equal(t.numpy(), [1.0, 2.0])  # not mutated
        t.apply_(lambda a: a + 1)
        np.testing.assert_array_equal(t.numpy(), [2.0, 3.0])
        assert t.nbytes == 8
        g = paddle.to_tensor(np.array([1.0], np.float32))
        g.stop_gradient = False
        with pytest.raises(RuntimeError, match="grad"):
            g.apply_(lambda a: a)
