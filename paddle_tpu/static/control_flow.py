"""Data-dependent control flow for dy2static (ref: python/paddle/static/nn/
control_flow.py, upstream layout, unverified — mount empty).

TPU-first design: a traced branch cannot be a Python `if` — everything under
jit is traced once (XLA semantics). So `cond`/`while_loop`/`switch_case` have
two executions:

- **dygraph** (concrete values): plain Python control flow on the tape —
  exactly one branch runs, loops unroll, gradients flow through the eager
  autograd.
- **traced** (inputs are jax tracers, i.e. inside to_static/jit/pjit): lower
  to `lax.cond` / `lax.while_loop` / `lax.switch`, the compiler-friendly
  control flow XLA compiles natively. Branch callables close over outer
  tracers, so no operand plumbing is required of the user.

`while_loop` in traced mode is forward-only (jax cannot reverse-differentiate
`lax.while_loop`); training loops that need gradients through a traced loop
should use a bounded `lax.scan`-style construct or keep the loop in dygraph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "switch_case", "case"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap_tree(out):
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, out,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_tree(out):
    return jax.tree_util.tree_map(
        lambda d: Tensor(d) if isinstance(d, (jax.Array, jnp.ndarray)) else d,
        out)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Run `true_fn()` if `pred` else `false_fn()` (paddle.static.nn.cond).

    Dygraph: executes exactly one branch eagerly. Traced: lowers to
    `lax.cond`; both branches are traced (XLA requirement) and must return
    the same structure/shapes/dtypes.
    """
    pd = _data(pred)
    if not _is_tracer(pd):
        return true_fn() if bool(pd) else false_fn()

    def lower(fn):
        def branch(_):
            with tape_mod.no_grad():
                return _unwrap_tree(fn())
        return branch

    scalar = jnp.reshape(pd, ()).astype(bool)
    out = jax.lax.cond(scalar, lower(true_fn), lower(false_fn), 0)
    return _wrap_tree(out)


def while_loop(cond_fn, body_fn, loop_vars, name=None):
    """paddle.static.nn.while_loop over a list of loop variables.

    Dygraph: a Python while loop (unrolled, differentiable on the tape).
    Traced: `lax.while_loop` — body output must match loop_vars'
    shapes/dtypes; forward-only under autodiff.
    """
    is_seq = isinstance(loop_vars, (list, tuple))
    vals = list(loop_vars) if is_seq else [loop_vars]
    datas = [_data(v) for v in vals]

    if not any(_is_tracer(d) for d in datas):
        # probe the condition too: concrete loop vars with a condition that
        # closes over a TRACED outer value still need the lax path
        c0 = cond_fn(*vals)
        if not _is_tracer(_data(c0)):
            while bool(_data(c0)):
                out = body_fn(*vals)
                vals = list(out) if isinstance(out, (list, tuple)) else [out]
                c0 = cond_fn(*vals)
            return vals if is_seq else vals[0]

    def c(state):
        with tape_mod.no_grad():
            r = cond_fn(*[Tensor(d) for d in state])
        return jnp.reshape(_data(r), ()).astype(bool)

    def b(state):
        with tape_mod.no_grad():
            out = body_fn(*[Tensor(d) for d in state])
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(_data(o) for o in out)

    res = jax.lax.while_loop(c, b, tuple(datas))
    wrapped = [Tensor(d) for d in res]
    return wrapped if is_seq else wrapped[0]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case → `lax.switch` when traced."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]
    idx_d = _data(branch_index)

    if not _is_tracer(idx_d):
        i = int(idx_d)
        return fns[keys.index(i)]() if i in keys else default()

    # map sparse keys onto a dense lax.switch table; any index outside the
    # key set (including negatives) must hit the default slot, matching the
    # dygraph branch above
    table = {k: j for j, k in enumerate(keys)}
    lookup = jnp.full(max(keys) + 1, len(fns), jnp.int32)
    for k, j in table.items():
        lookup = lookup.at[k].set(j)
    idx0 = jnp.reshape(idx_d, ()).astype(jnp.int32)
    in_range = (idx0 >= 0) & (idx0 <= max(keys))
    dense_idx = jnp.where(in_range,
                          lookup[jnp.clip(idx0, 0, max(keys))],
                          len(fns))

    def lower(fn):
        def branch(_):
            with tape_mod.no_grad():
                return _unwrap_tree(fn())
        return branch

    out = jax.lax.switch(dense_idx, [lower(f) for f in fns] +
                         [lower(default)], 0)
    return _wrap_tree(out)


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case: first true predicate wins (nested cond)."""
    if not pred_fn_pairs:
        raise ValueError("case() needs at least one (pred, fn) pair")
    if default is None:
        default = pred_fn_pairs[-1][1]
        pred_fn_pairs = pred_fn_pairs[:-1]

    def build(i):
        if i == len(pred_fn_pairs):
            return default
        pred, fn = pred_fn_pairs[i]
        return lambda: cond(pred, fn, build(i + 1))

    return build(0)()
