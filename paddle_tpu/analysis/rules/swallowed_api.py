"""SWALLOWED-API — broad excepts that silently eat errors and fall through.

The PR 5 postmortem: ring/ulysses attention wrapped ``jax.lax.axis_size``
in ``except Exception`` with an ``n = 1`` fall-through; when a jax bump
removed the attribute, every rank silently attended only its local shard
("100% elements wrong" — no crash, no log, no test failure until a
stress matrix diffed numerics). The hazard is the *shape*, not the one
API: a broad/bare except whose handler neither re-raises, nor logs, nor
even looks at the exception, sitting over real work and falling through
to a default.

Fires on a broad handler (bare / ``Exception`` / ``BaseException``,
alone or in a tuple) when the handler body

  * contains no ``raise``,
  * makes no logging-ish call (``warnings.warn``, ``logging``/logger
    methods, ``print``, ``_log``), and
  * never reads the bound exception name (recording ``e`` somewhere is
    surfacing it),

and the try body contains at least one call. When the try body contains
a jax-derived call (alias-tracked: ``import jax.profiler as jp`` counts)
the message names the PR 5 class explicitly.

Suppress with ``# noqa: BLE001 — <reason>`` (the repo's existing
discipline) or ``# noqa: SWALLOWED-API — <reason>`` on the except line.
"""
import ast
from typing import Iterator, List, Tuple

from ..core import Finding, ParsedModule, Rule, is_jax_call, walk_stmts

_BROAD = {"Exception", "BaseException"}
_LOG_CALL_TAILS = {
    "warn", "warning", "error", "exception", "critical", "info", "debug",
    "log", "print",
}
_LOG_ROOTS = {"print", "_log", "log", "logger", "logging", "warnings"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in _BROAD
                   for el in t.elts)
    return False


def _is_logging_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _LOG_ROOTS
    if isinstance(f, ast.Attribute):
        if f.attr in _LOG_CALL_TAILS:
            return True
        root = f.value
        while isinstance(root, ast.Attribute):
            root = root.value
        return isinstance(root, ast.Name) and root.id in _LOG_ROOTS
    return False


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in walk_stmts(handler.body):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call) and _is_logging_call(node):
            return False
        if bound and isinstance(node, ast.Name) \
                and node.id == bound and isinstance(node.ctx, ast.Load):
            return False  # the exception is recorded/used somewhere
    return True


class SwallowedApiRule(Rule):
    name = "SWALLOWED-API"
    aliases = ("BLE001",)
    description = ("broad except that silently swallows errors from the "
                   "try body and falls through to a default (the PR 5 "
                   "silent-wrong-result class when jax APIs are involved)")

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        hits: List[Tuple[int, str]] = []
        aliases = module.jax_aliases
        for node in module.nodes():
            if not isinstance(node, ast.Try):
                continue
            body_calls = [n for n in walk_stmts(node.body)
                          if isinstance(n, ast.Call)]
            if not body_calls:
                continue
            jax_calls = [c for c in body_calls if is_jax_call(c, aliases)]
            for handler in node.handlers:
                if not _is_broad(handler) or not _handler_is_silent(handler):
                    continue
                if jax_calls:
                    api = ".".join(
                        _chain_str(jax_calls[0]))
                    msg = (f"broad except silently swallows errors from "
                           f"jax API call `{api}` and falls through to a "
                           f"default — the PR 5 silent-wrong-result class; "
                           f"re-raise, log, or annotate "
                           f"`# noqa: BLE001 — <reason>`")
                else:
                    msg = (f"broad except silently swallows all errors "
                           f"from {len(body_calls)} call site(s) with no "
                           f"re-raise, log, or use of the exception; "
                           f"narrow it, log the fall-through, or annotate "
                           f"`# noqa: BLE001 — <reason>`")
                hits.append((handler.lineno, msg))
        yield from self.findings(module, hits)


def _chain_str(call: ast.Call) -> List[str]:
    from ..core import call_chain

    return call_chain(call) or ["<call>"]
