"""Serving resilience layer (ISSUE 6): deterministic FaultInjector,
allocator/scheduler invariant audits, cancellation in every request
state (waiting / running / mid-decode-block, and across prefix-cache
page sharing), per-request deadlines + bounded-queue load shedding,
failure isolation with one transient retry (persistent faults
quarantine exactly the implicated requests), preemption-storm parking,
the `_preempt` fold-length bucket guard, and the chaos-parity
acceptance test: under a seeded schedule of alloc faults + transient
dispatch faults + mid-block cancellations, every non-quarantined
request's token stream is identical to a fault-free run while the pool
invariants hold after every step. The zero-overhead guard pins that a
resilience-free engine executes no resilience code on the hot path
(the enable_metrics=False raise-on-touch discipline).

Single tiny LLaMA reused module-wide (tests/test_serving.py's pattern)
so the fast lane compiles one prefill-bucket + decode set.
"""
import functools
import importlib.util
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    BlockAllocator, EngineOverloaded, FaultInjector, InjectedFault,
    Request, SamplingParams, Scheduler, ServingEngine, TERMINAL_STATUSES,
    is_transient,
)


@functools.lru_cache(maxsize=None)
def _llama():
    paddle.seed(1234)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _engine(**kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("decode_horizon", 4)
    kw.setdefault("retry_backoff_s", 0.0)
    return ServingEngine(_llama(), **kw)


_PROMPTS = [[7, 3, 9, 1, 4], [2, 8, 6, 5, 1, 9, 3, 7, 2],
            [4, 4, 1, 8, 8, 2, 6, 3, 9, 5, 1, 7, 3]]


def _reference(prompts=_PROMPTS, max_new_tokens=6, **kw):
    eng = _engine(**kw)
    rids = [eng.add_request(p, max_new_tokens=max_new_tokens)
            for p in prompts]
    return eng.run(), rids


# ------------------------------------------------------------ FaultInjector

class TestFaultInjector:
    def test_fail_at_fires_exactly_once(self):
        fi = FaultInjector().fail_at("alloc", 2)
        fi.check("alloc")
        fi.check("alloc")
        with pytest.raises(InjectedFault) as ei:
            fi.check("alloc")
        assert ei.value.site == "alloc" and ei.value.index == 2
        assert ei.value.transient
        fi.check("alloc")                      # index 3: past the arm
        assert fi.counts["alloc"] == 4
        assert fi.fired == {"alloc": 1}
        assert fi.log == [("alloc", 2, True)]

    def test_fail_every_period(self):
        fi = FaultInjector().fail_every("dispatch", 3)
        hits = []
        for i in range(9):
            try:
                fi.check("dispatch")
            except InjectedFault:
                hits.append(i)
        assert hits == [2, 5, 8]
        assert fi.total_fired() == 3

    def test_fail_rate_deterministic_per_seed_and_site(self):
        def trace(seed):
            fi = (FaultInjector(seed=seed).fail_rate("drain", 0.5)
                  .fail_rate("alloc", 0.5))
            out = []
            for site in ("drain", "alloc") * 50:
                try:
                    fi.check(site)
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        a, b = trace(3), trace(3)
        assert a == b                          # same seed: same schedule
        assert trace(4) != a                   # different seed: different
        assert 10 < sum(a) < 90                # sanity: rate is ~0.5

    def test_persistent_flag_and_is_transient(self):
        fi = FaultInjector().fail_at("drain", 0, transient=False)
        with pytest.raises(InjectedFault) as ei:
            fi.check("drain")
        assert not ei.value.transient
        assert not is_transient(ei.value)
        assert is_transient(InjectedFault("drain", 1))
        assert not is_transient(RuntimeError("boom"))

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            FaultInjector().fail_at("nonsense", 0)


# ------------------------------------------------------- invariant audits

class TestCheckConsistency:
    def test_sound_allocator_passes(self):
        a = BlockAllocator(8)
        pages = [a.alloc() for _ in range(3)]
        a.acquire(pages[0])
        assert a.check_consistency() is True
        a.free(pages[0])
        a.free_all(pages)
        assert a.check_consistency() is True

    def test_detects_double_accounting(self):
        a = BlockAllocator(8)
        p = a.alloc()
        a._free.append(p)                      # corrupt: free AND live
        with pytest.raises(RuntimeError, match="both free and referenced"):
            a.check_consistency()

    def test_detects_leak(self):
        a = BlockAllocator(8)
        a.alloc()
        del a._refs[next(iter(a._refs))]       # page vanishes entirely
        with pytest.raises(RuntimeError, match="leak or double-account"):
            a.check_consistency()

    def test_detects_null_page_in_circulation(self):
        a = BlockAllocator(8)
        a._free.append(0)
        with pytest.raises(RuntimeError, match="null page"):
            a.check_consistency()

    def test_scheduler_audit_catches_status_mismatch(self):
        a = BlockAllocator(8)
        s = Scheduler(a, page_size=4, max_batch_size=2, max_pages_per_seq=2)
        req = Request(prompt=[1, 2], max_new_tokens=2,
                      sampling=SamplingParams())
        req.pages = [a.alloc()]
        s.running.append(req)                  # status still "waiting"
        with pytest.raises(RuntimeError, match="running queue with status"):
            s.check_consistency()
        req.status = "running"
        assert s.check_consistency() is True


# --------------------------------------------------- backpressure/overload

class TestOverload:
    def test_bounded_queue_raises_typed_overload(self):
        eng = _engine(max_batch_size=1, max_waiting=2)
        eng.add_request(_PROMPTS[0])
        eng.add_request(_PROMPTS[1])
        with pytest.raises(EngineOverloaded, match="max_waiting=2"):
            eng.add_request(_PROMPTS[2])
        # the rejected request left no trace and the rest still serve
        assert len(eng.requests) == 2
        out = eng.run()
        assert all(eng.status(r)[0] == "finished" for r in out)

    def test_overload_is_not_a_valueerror_catchall(self):
        assert issubclass(EngineOverloaded, RuntimeError)
        assert not issubclass(EngineOverloaded, ValueError)


# ------------------------------------------------------------ cancellation

class TestCancellation:
    def test_cancel_waiting_request(self):
        eng = _engine(max_batch_size=1)
        a = eng.add_request(_PROMPTS[0], max_new_tokens=4)
        b = eng.add_request(_PROMPTS[1], max_new_tokens=4)
        assert eng.cancel(b) is True           # never admitted
        assert eng.status(b) == ("cancelled", None)
        out = eng.run()
        assert eng.status(a)[0] == "finished"
        assert out[b] == list(_PROMPTS[1])     # no tokens ever generated

    def test_cancel_running_request_releases_pages(self):
        eng = _engine()
        rid = eng.add_request(_PROMPTS[0], max_new_tokens=8)
        eng.step()                             # prefill: now running
        assert eng.requests[rid].status == "running"
        free_before = eng.cache.allocator.num_free
        assert eng.cancel(rid) is True
        assert eng.status(rid)[0] == "cancelled"
        assert eng.cache.allocator.num_free > free_before
        eng.scheduler.check_consistency()
        assert eng.cache.allocator.num_used == 0

    def test_cancel_mid_block_drains_inflight_tokens_first(self):
        eng = _engine(decode_horizon=8)
        ref, _ = _reference(prompts=[_PROMPTS[0]], max_new_tokens=16)
        rid = eng.add_request(_PROMPTS[0], max_new_tokens=16)
        while eng._pending is None:            # dispatch a decode block
            eng.step()
        assert rid in eng._pending["rids"]
        assert eng.cancel(rid) is True
        # the in-flight block's tokens surfaced (spill queue) before the
        # pages were torn down, and they match the fault-free prefix
        got = eng.output(rid)
        assert len(got) > len(_PROMPTS[0])
        assert got == list(ref.values())[0][:len(got)]
        for _ in eng.stream():                 # flushes any spill
            pass
        eng.scheduler.check_consistency()
        assert eng.cache.allocator.num_used == 0

    def test_cancel_unknown_and_terminal_returns_false(self):
        eng = _engine()
        rid = eng.add_request(_PROMPTS[0], max_new_tokens=2)
        eng.run()
        assert eng.status(rid)[0] == "finished"
        assert eng.cancel(rid) is False        # already terminal
        assert eng.cancel(123456) is False     # unknown
        assert eng.status(rid)[0] == "finished"

    def test_cancel_one_prefix_sharer_never_corrupts_survivors(self):
        """ISSUE 6 satellite: two requests share radix-cached prefix
        pages; cancelling one mid-flight must only drop ITS references —
        the survivor keeps decoding on the shared pages and its tokens
        stay identical to an undisturbed run."""
        eng = _engine(enable_prefix_caching=True, num_pages=128)
        shared = [5, 1, 3, 7, 2, 9, 4, 6]      # two full pages at ps=4
        pa, pb = shared + [11, 12], shared + [13, 14, 15]
        # undisturbed oracle (same engine config, cold cache)
        ref_eng = _engine(enable_prefix_caching=True, num_pages=128)
        r0 = ref_eng.add_request(shared + [1], max_new_tokens=1)
        ref_eng.run()                          # warm the radix tree
        ra = ref_eng.add_request(pa, max_new_tokens=8)
        rb = ref_eng.add_request(pb, max_new_tokens=8)
        ref = ref_eng.run()

        w = eng.add_request(shared + [1], max_new_tokens=1)
        eng.run()
        a = eng.add_request(pa, max_new_tokens=8)
        b = eng.add_request(pb, max_new_tokens=8)
        while eng.requests[b].status != "running":
            eng.step()
        shared_pages = [p for p in eng.requests[b].pages
                        if eng.cache.allocator.ref_count(p) > 1]
        assert shared_pages                    # they really do share
        assert eng.cancel(a) is True
        eng.scheduler.check_consistency()
        for p in shared_pages:                 # survivor + tree refs live
            assert eng.cache.allocator.ref_count(p) >= 1
        out = eng.run()
        assert eng.status(b)[0] == "finished"
        assert out[b] == ref[rb]
        eng.scheduler.check_consistency()


# ------------------------------------------------- deadlines/load shedding

class TestDeadlines:
    def test_deadline_validation(self):
        eng = _engine()
        with pytest.raises(ValueError, match="deadline_s"):
            eng.add_request(_PROMPTS[0], deadline_s=0.0)
        with pytest.raises(ValueError, match="deadline_s"):
            eng.add_request(_PROMPTS[0], deadline_s=-1.0)

    @pytest.mark.parametrize("horizon", [1, 8])
    def test_waiting_request_expires_before_admission(self, horizon):
        eng = _engine(max_batch_size=1, decode_horizon=horizon)
        a = eng.add_request(_PROMPTS[0], max_new_tokens=6)
        b = eng.add_request(_PROMPTS[1], max_new_tokens=6,
                            deadline_s=60.0)
        eng.requests[b].deadline_t = time.perf_counter() - 1.0
        out = eng.run()
        assert eng.status(b)[0] == "expired"
        assert out[b] == list(_PROMPTS[1])     # shed before any prefill
        assert eng.status(a)[0] == "finished"
        eng.scheduler.check_consistency()

    @pytest.mark.parametrize("horizon", [1, 8])
    def test_running_request_expires_at_block_boundary(self, horizon):
        eng = _engine(decode_horizon=horizon)
        rid = eng.add_request(_PROMPTS[0], max_new_tokens=16,
                              deadline_s=60.0)
        while eng.requests[rid].status != "running":
            eng.step()
        eng.requests[rid].deadline_t = time.perf_counter() - 1.0
        for _ in eng.stream():
            pass
        assert eng.status(rid)[0] == "expired"
        assert len(eng.requests[rid].generated) < 16
        eng.scheduler.check_consistency()
        assert eng.cache.allocator.num_used == 0

    def test_queue_wait_shedding(self):
        eng = _engine(max_batch_size=1, max_queue_wait_s=30.0)
        a = eng.add_request(_PROMPTS[0], max_new_tokens=6)
        b = eng.add_request(_PROMPTS[1], max_new_tokens=6)
        eng.requests[b].arrival_t -= 60.0      # waited "too long"
        eng.run()
        assert eng.status(a)[0] == "finished"
        assert eng.status(b)[0] == "shed"
        assert eng.stats()["terminal"]["shed"] == 1
        eng.scheduler.check_consistency()


# ------------------------------------------------------ preemption guards

class TestPreemptionGuards:
    def _sched(self, **kw):
        a = BlockAllocator(32)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_batch_size", 2)
        kw.setdefault("max_pages_per_seq", 8)
        return a, Scheduler(a, **kw)

    def _running(self, sched, alloc, prompt, generated):
        req = Request(prompt=list(prompt), max_new_tokens=16,
                      sampling=SamplingParams())
        req.generated = list(generated)
        req.status = "running"
        req.pages = [alloc.alloc() for _ in range(2)]
        sched.running.append(req)
        return req

    def test_preempt_bucket_guard_raises_before_mutation(self):
        a, s = self._sched(max_prefill_tokens=8)
        req = self._running(s, a, range(6), range(4))   # folds to 10 > 8
        with pytest.raises(RuntimeError,
                           match="largest prefill bucket"):
            s._preempt(req)
        # clear error BEFORE teardown: nothing was mutated
        assert req.status == "running" and req in s.running
        assert len(req.pages) == 2 and req.generated == list(range(4))
        s.check_consistency()

    def test_preempt_within_bucket_still_works(self):
        a, s = self._sched(max_prefill_tokens=16)
        req = self._running(s, a, range(6), range(4))
        s._preempt(req)
        assert req.status == "waiting" and req.prompt == list(range(6)) \
            + list(range(4))
        s.check_consistency()

    def test_preemption_storm_parks_victim_at_back(self):
        a, s = self._sched(max_preemptions=2)
        other = Request(prompt=[1], max_new_tokens=2,
                        sampling=SamplingParams())
        s.waiting.append(other)
        req = self._running(s, a, range(4), [])
        req.preemptions = 2                    # already at the limit
        s._preempt(req)
        assert req.parked and req.preemptions == 3
        # parked: BACK of the queue, not jumping the line anymore
        assert s.waiting == [other, req]

    def test_below_storm_limit_requeues_at_front(self):
        a, s = self._sched(max_preemptions=2)
        other = Request(prompt=[1], max_new_tokens=2,
                        sampling=SamplingParams())
        s.waiting.append(other)
        req = self._running(s, a, range(4), [])
        s._preempt(req)
        assert not req.parked
        assert s.waiting == [req, other]


# ------------------------------------------------------ failure isolation

class TestFailureIsolation:
    def test_transient_dispatch_fault_costs_latency_never_tokens(self):
        ref, _ = _reference()
        fi = FaultInjector().fail_every("dispatch", 3)
        eng = _engine(fault_injector=fi)
        rids = [eng.add_request(p, max_new_tokens=6) for p in _PROMPTS]
        out = eng.run()
        assert fi.fired.get("dispatch", 0) >= 2
        assert eng.stats()["transient_retries"] == fi.fired["dispatch"]
        for (r0, v0), (r1, v1) in zip(sorted(ref.items()),
                                      sorted(out.items())):
            assert v0 == v1
        assert all(eng.status(r)[0] == "finished" for r in rids)
        eng.scheduler.check_consistency()

    def test_persistent_prefill_fault_quarantines_exactly_one(self):
        ref, ref_rids = _reference()
        fi = FaultInjector().fail_at("dispatch", 0, transient=False)
        eng = _engine(fault_injector=fi)
        rids = [eng.add_request(p, max_new_tokens=6) for p in _PROMPTS]
        out = eng.run()
        status, err = eng.status(rids[0])
        assert status == "failed"
        assert "InjectedFault" in err and "dispatch" in err
        # exactly one casualty; survivors bit-identical to fault-free
        for a, b in zip(ref_rids[1:], rids[1:]):
            assert eng.status(b)[0] == "finished"
            assert out[b] == ref[a]
        assert eng.stats()["terminal"]["failed"] == 1
        eng.scheduler.check_consistency()
        assert eng.cache.allocator.num_used == 0

    def test_persistent_drain_fault_isolates_block_batch(self):
        fi = FaultInjector().fail_every("drain", 2, transient=False)
        eng = _engine()
        eng._faults = fi                       # arm ONLY the drain site
        rid = eng.add_request(_PROMPTS[0], max_new_tokens=8)
        for _ in eng.stream():
            pass
        assert eng.status(rid)[0] == "failed"
        assert "drain" in eng.status(rid)[1]
        eng.scheduler.check_consistency()
        assert eng.cache.allocator.num_used == 0
        assert eng._pending is None

    def test_injected_alloc_faults_degrade_losslessly(self):
        ref, _ = _reference()
        fi = FaultInjector().fail_every("alloc", 2)
        eng = _engine(fault_injector=fi)
        rids = [eng.add_request(p, max_new_tokens=6) for p in _PROMPTS]
        out = eng.run()
        assert fi.fired["alloc"] >= 1
        assert all(eng.status(r)[0] == "finished" for r in rids)
        for (r0, v0), (r1, v1) in zip(sorted(ref.items()),
                                      sorted(out.items())):
            assert v0 == v1
        eng.scheduler.check_consistency()

    def test_injected_prefix_fault_degrades_to_cache_miss(self):
        fi = FaultInjector().fail_every("prefix_match", 1)
        eng = _engine(enable_prefix_caching=True, num_pages=128,
                      fault_injector=fi)
        shared = [5, 1, 3, 7, 2, 9, 4, 6]
        w = eng.add_request(shared + [1], max_new_tokens=1)
        eng.run()
        rid = eng.add_request(shared + [11, 12], max_new_tokens=4)
        out = eng.run()
        assert fi.fired["prefix_match"] >= 1
        # every lookup faulted -> zero hits, but the request still ran
        assert eng.requests[rid].cached_tokens == 0
        assert eng.status(rid)[0] == "finished"
        # parity against an uninjected prefix-cache engine
        ref_eng = _engine(enable_prefix_caching=True, num_pages=128)
        ref_eng.add_request(shared + [1], max_new_tokens=1)
        ref_eng.run()
        rr = ref_eng.add_request(shared + [11, 12], max_new_tokens=4)
        assert ref_eng.run()[rr] == out[rid]
        eng.scheduler.check_consistency()


# ----------------------------------------------------------- chaos parity

class TestChaosParity:
    def test_seeded_chaos_survivor_parity(self):
        """THE acceptance criterion: a seeded schedule of alloc faults,
        transient dispatch faults, and a mid-block cancellation; every
        non-quarantined, non-cancelled request's token stream must be
        identical to the fault-free run, with the allocator + scheduler
        invariants holding after EVERY step."""
        prompts = _PROMPTS + [[9, 9, 2, 4, 1, 6]]
        ref, ref_rids = _reference(prompts=prompts, max_new_tokens=10)

        fi = (FaultInjector(seed=42)
              .fail_every("alloc", 4)
              .fail_every("dispatch", 5)       # transient: retried
              .fail_rate("drain", 0.2))        # transient: retried
        eng = _engine(fault_injector=fi)
        rids = [eng.add_request(p, max_new_tokens=10) for p in prompts]
        cancelled = None
        for _ in range(400):
            if not (eng.scheduler.has_work() or eng._pending is not None
                    or eng._spill):
                break
            eng.step()
            eng.scheduler.check_consistency()
            if cancelled is None and eng._pending is not None:
                victim = eng._pending["rids"][-1]
                assert eng.cancel(victim)      # mid-block, tokens in flight
                cancelled = victim
                eng.scheduler.check_consistency()
        else:
            pytest.fail("chaos run did not converge")
        assert fi.total_fired() > 0 and cancelled is not None
        out = {r: eng.output(r) for r in rids}
        for a, b in zip(ref_rids, rids):
            if b == cancelled:
                assert eng.status(b)[0] == "cancelled"
                # drained prefix still matches the fault-free stream
                assert out[b] == ref[a][:len(out[b])]
            else:
                assert eng.status(b)[0] == "finished"
                assert out[b] == ref[a]
        eng.scheduler.check_consistency()
        assert eng.cache.allocator.num_used == 0


# ------------------------------------------------------ zero-overhead pin

class TestZeroResilienceHotPath:
    def test_disabled_resilience_executes_no_resilience_code(
            self, monkeypatch):
        """Raise-on-touch guard (the enable_metrics=False discipline):
        with no FaultInjector bound, no deadlines and no queue bounds, a
        full request lifecycle must never enter ANY resilience entry
        point — injector checks, transience tests, quarantine, expiry
        sweeps, terminal finalization, invariant audits."""
        import paddle_tpu.serving.engine as eng_mod
        import paddle_tpu.serving.kv_cache as kv_mod
        import paddle_tpu.serving.scheduler as sched_mod

        eng = _engine()
        eng.add_request([9, 8, 7], max_new_tokens=3)
        eng.run()                              # warm compiles first

        def boom(*a, **kw):
            raise AssertionError("resilience code on a clean hot path")

        for obj, meth in [
                (FaultInjector, "check"),
                (eng_mod.ServingEngine, "_quarantine"),
                (eng_mod.ServingEngine, "_expire_and_shed"),
                (eng_mod.ServingEngine, "cancel"),
                (sched_mod.Scheduler, "finalize"),
                (sched_mod.Scheduler, "check_consistency"),
                (kv_mod.BlockAllocator, "check_consistency")]:
            monkeypatch.setattr(obj, meth, boom)
        monkeypatch.setattr(eng_mod, "is_transient", boom)
        monkeypatch.setattr(sched_mod, "InjectedFault", ())  # except ()
        rid = eng.add_request([1, 2, 3], max_new_tokens=4)
        out = eng.run()
        assert len(out[rid]) == 7
        assert eng.status(rid)[0] == "finished"


# ---------------------------------------------------------- trace summary

def _trace_summary_mod():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTraceSummaryFlagsCasualties:
    EVENTS = [
        {"name": "serving.request[1].enqueued", "ph": "X", "ts": 0,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[1].finished", "ph": "X", "ts": 50,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[2].enqueued", "ph": "X", "ts": 5,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[2].failed", "ph": "X", "ts": 30,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[3].enqueued", "ph": "X", "ts": 6,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[3].expired", "ph": "X", "ts": 20,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[4].enqueued", "ph": "X", "ts": 7,
         "dur": 0, "pid": 1, "tid": 2},
        {"name": "serving.request[4].cancelled", "ph": "X", "ts": 9,
         "dur": 0, "pid": 1, "tid": 2},
    ]

    def test_failed_expired_shed_are_flagged(self):
        ts = _trace_summary_mod()
        out = ts.format_requests(
            ts.request_timelines(list(map(dict, self.EVENTS))))
        assert "request 1:" in out and "request 1:  !!" not in out
        assert "request 2:  !! failed" in out
        assert "request 3:  !! expired" in out
        # caller-initiated cancel is shown but not flagged
        assert "request 4:  !!" not in out and "cancelled" in out
        assert "2 of 4 requests did not finish" in out
        assert "1 failed" in out and "1 expired" in out

    def test_all_finished_prints_no_flags(self):
        ts = _trace_summary_mod()
        evs = [e for e in map(dict, self.EVENTS)
               if "[1]" in e["name"]]
        out = ts.format_requests(ts.request_timelines(evs))
        assert "!!" not in out


# ----------------------------------------------------------- engine stats

class TestResilienceStats:
    def test_terminal_counts_surface_with_metrics_on_and_off(self):
        for enable in (True, False):
            eng = _engine(enable_metrics=enable, max_batch_size=1)
            a = eng.add_request(_PROMPTS[0], max_new_tokens=3)
            b = eng.add_request(_PROMPTS[1], max_new_tokens=3)
            eng.cancel(b)
            eng.run()
            st = eng.stats()
            assert st["terminal"]["cancelled"] == 1
            assert st["requests"][a]["status"] == "finished"
            assert st["requests"][b]["status"] == "cancelled"
            if enable:
                snap = eng.metrics.snapshot()
                assert any(
                    m.get("labels", {}).get("status") == "cancelled"
                    and m["value"] == 1
                    for m in snap["metrics"]
                    if m["name"] == "serving_requests_terminated_total")
