"""Custom-device plugin seam (ref: paddle/phi/backends/custom/custom_device.cc
+ python/paddle/device/__init__.py CustomPlace plumbing, upstream layout,
unverified — mount empty).

Paddle's CustomDevice loads a vendor runtime .so implementing its C device
API. The TPU-native equivalent of "bring your own accelerator runtime" is a
PJRT plugin: a vendor ships a PJRT C-API library, and the framework
registers it with the jax runtime — every layer above (ops, jit, meshes,
collectives) works unchanged because XLA talks PJRT, not device specifics.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["register_custom_device", "list_custom_devices",
           "is_custom_device_registered"]

_REGISTERED: Dict[str, str] = {}


def register_custom_device(device_type: str,
                           library_path: Optional[str] = None,
                           priority: int = 400,
                           options: Optional[Dict] = None) -> None:
    """Register a PJRT plugin as a paddle custom device.

    `library_path` points at the vendor's PJRT C-API shared library (the
    CustomDevice runtime .so analog). Must run before any jax computation
    initializes the backends; select it with
    ``paddle.device.set_device(device_type)`` /
    ``JAX_PLATFORMS=<device_type>``.
    """
    if not device_type or not device_type.isidentifier():
        raise ValueError(f"invalid custom device name {device_type!r}")
    if device_type in _REGISTERED:
        raise ValueError(
            f"custom device {device_type!r} is already registered "
            f"(library: {_REGISTERED[device_type]})")
    if library_path is None:
        raise ValueError(
            "register_custom_device requires library_path to the vendor's "
            "PJRT C-API shared library")
    if not os.path.exists(library_path):
        raise FileNotFoundError(
            f"PJRT plugin library not found: {library_path}")
    from jax._src import xla_bridge as xb

    xb.register_plugin(device_type, library_path=library_path,
                       priority=priority, options=options)
    _REGISTERED[device_type] = library_path


def list_custom_devices() -> List[str]:
    """Names of custom devices registered through this seam."""
    return sorted(_REGISTERED)


def is_custom_device_registered(device_type: str) -> bool:
    return device_type in _REGISTERED
