"""paddle.distributed.TCPStore — ctypes binding over the native C++ store.

Ref: paddle/fluid/distributed/store/tcp_store.* (upstream layout,
unverified — mount empty). The C++ server/client live in
core/native/tcp_store.cc, compiled on first use through the same
g++ pipeline as utils.cpp_extension (no pybind in this image — plain
C ABI + ctypes, per the build-environment contract).

Master (is_master=True) starts the in-process server AND a client to it;
workers connect as clients. API mirrors the reference: set/get (get waits
for the key), wait, add (atomic counter — the rendezvous primitive),
plus a counter-based barrier helper.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

_LIB = None
_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                    "core", "native", "tcp_store.cc")


def _lib():
    global _LIB
    if _LIB is None:
        from ..utils.cpp_extension import _compile

        so = _compile("paddle_tpu_tcp_store", [_SRC],
                      extra_cflags=["-std=c++17", "-pthread"])
        lib = ctypes.CDLL(so)
        lib.ts_server_start.restype = ctypes.c_void_p
        lib.ts_server_start.argtypes = [ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int)]
        lib.ts_server_stop.argtypes = [ctypes.c_void_p]
        lib.ts_client_connect.restype = ctypes.c_void_p
        lib.ts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int]
        lib.ts_client_close.argtypes = [ctypes.c_void_p]
        lib.ts_set.restype = ctypes.c_int
        lib.ts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.ts_get.restype = ctypes.c_int
        lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                               ctypes.c_int]
        lib.ts_add.restype = ctypes.c_int
        lib.ts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int, ctypes.c_longlong,
                               ctypes.POINTER(ctypes.c_longlong)]
        _LIB = lib
    return _LIB


class TCPStore:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 30.0):
        lib = _lib()
        self._lib = lib
        self._server = None
        self.host = host
        self.timeout_ms = int(timeout * 1000)
        self.world_size = world_size
        if is_master:
            out_port = ctypes.c_int(0)
            self._server = lib.ts_server_start(port,
                                               ctypes.byref(out_port))
            if not self._server:
                raise RuntimeError(f"TCPStore master failed to bind "
                                   f"port {port}")
            port = out_port.value
        self.port = port
        self._client = lib.ts_client_connect(host.encode(), port,
                                             self.timeout_ms)
        if not self._client:
            if self._server:
                lib.ts_server_stop(self._server)
            raise RuntimeError(
                f"TCPStore could not connect to {host}:{port}")

    # ----------------------------------------------------------- KV API
    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        k = key.encode()
        if self._lib.ts_set(self._client, k, len(k), data, len(data)) != 0:
            raise RuntimeError("TCPStore set failed (connection lost)")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        """Blocks until the key exists (reference wait-then-get contract)."""
        k = key.encode()
        tmo = self.timeout_ms if timeout is None else int(timeout * 1000)
        buf = ctypes.create_string_buffer(1 << 20)
        n = self._lib.ts_get(self._client, k, len(k), buf, len(buf), tmo)
        if n == -1:
            raise TimeoutError(f"TCPStore get({key!r}) timed out")
        if n < 0:
            raise RuntimeError(f"TCPStore get({key!r}) failed (code {n})")
        return buf.raw[:n]

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        for k in ([keys] if isinstance(keys, str) else keys):
            self.get(k, timeout)

    def add(self, key: str, amount: int = 1) -> int:
        k = key.encode()
        out = ctypes.c_longlong(0)
        rc = self._lib.ts_add(self._client, k, len(k), amount,
                              ctypes.byref(out))
        if rc != 0:
            raise RuntimeError("TCPStore add failed (connection lost)")
        return int(out.value)

    def barrier(self, name: str = "barrier",
                timeout: Optional[float] = None) -> None:
        """Reusable counter barrier over `world_size` participants: each
        pass is an epoch, so calling barrier() in a loop re-synchronizes
        every time instead of sailing through on stale state."""
        arrived = self.add(f"__barrier/{name}", 1)
        epoch = (arrived - 1) // self.world_size
        if arrived % self.world_size == 0:
            self.set(f"__barrier/{name}/release/{epoch}", b"1")
        self.get(f"__barrier/{name}/release/{epoch}", timeout)

    def close(self) -> None:
        if self._client:
            self._lib.ts_client_close(self._client)
            self._client = None
        if self._server:
            self._lib.ts_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown: the
            # ctypes lib or socket may already be gone; raising in
            # __del__ only prints noise to stderr
            pass
