"""paddle.autograd analog: backward, grad, PyLayer, no_grad.

Ref: python/paddle/autograd/ (upstream layout, unverified — mount empty).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.tape import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from ..core.tensor import Tensor


def is_grad_enabled() -> bool:
    return tape_mod.grad_enabled()


def backward(tensors, grad_tensors=None, retain_graph=False):
    tape_mod.backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — returns grads of `outputs` w.r.t. `inputs` without
    touching .grad. create_graph (higher-order via the tape) is not yet
    supported; use paddle_tpu.incubate.functional_grad for nested grads."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True on the eager tape is not supported yet; "
            "use jax-level transforms (paddle_tpu.jit) for higher-order AD"
        )
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    store = {}
    targets = {id(t) for t in inputs}
    retain = bool(retain_graph) if retain_graph is not None else False
    tape_mod.backward(outputs, grad_tensors=grad_outputs,
                      retain_graph=retain, targets=targets, store=store,
                      accumulate_leaf=False)
    results: List[Optional[Tensor]] = []
    for t in inputs:
        if id(t) in store:
            results.append(Tensor(store[id(t)], stop_gradient=True))
        else:
            if not allow_unused:
                raise RuntimeError(
                    "one of the inputs received no gradient; pass "
                    "allow_unused=True to get None instead"
                )
            results.append(None)
    return results


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd function (paddle.autograd.PyLayer analog).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = x.exp()
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        record = tape_mod.grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_tensors = [
            o if isinstance(o, Tensor) else Tensor(o) for o in out_list
        ]
        if record:
            n_out = len(out_tensors)

            def vjp_fn(cts):
                if n_out == 1 and not isinstance(cts, (tuple, list)):
                    cts = (cts,)
                ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                if not isinstance(gin, (tuple, list)):
                    gin = (gin,)
                gin = list(gin)
                # map returned grads onto tensor inputs
                out = []
                gi = 0
                for t in tensor_inputs:
                    g = gin[gi] if gi < len(gin) else None
                    gi += 1
                    if g is None:
                        out.append(jnp.zeros(t._data.shape, t._data.dtype))
                    else:
                        out.append(g._data if isinstance(g, Tensor)
                                   else jnp.asarray(g))
                return tuple(out)

            node = tape_mod.GradNode(
                vjp_fn if len(out_tensors) > 1 else
                (lambda ct: vjp_fn((ct,))),
                tensor_inputs,
                n_outputs=len(out_tensors),
                name=cls.__name__,
                out_avals=[(o._data.shape, o._data.dtype)
                           for o in out_tensors],
            )
            for i, t in enumerate(out_tensors):
                t._grad_node = node
                t._out_index = i
                t.stop_gradient = False
        return tuple(out_tensors) if multi else out_tensors[0]


def set_to_zero_if_none(grads, refs):
    return [
        g if g is not None else Tensor(jnp.zeros(r._data.shape, r._data.dtype))
        for g, r in zip(grads, refs)
    ]
