"""paddle.text — NLP datasets + Viterbi decoding.

Ref: python/paddle/text/ (upstream layout, unverified — mount empty). Same
zero-egress contract as paddle.vision: canonical on-disk formats parse when
present, otherwise deterministic synthetic corpora keep the pipelines
exercisable. ViterbiDecoder is real max-sum dynamic programming over
lax.scan — compiler-friendly sequence decoding, no Python loop over time.
"""
from __future__ import annotations

import os
import warnings
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..io import Dataset
from ..nn import Layer

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens",
           "WMT14", "WMT16", "viterbi_decode", "ViterbiDecoder"]

_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_HOME", "~/.cache/paddle_tpu"))


def _dseed(*parts):
    return zlib.crc32("/".join(str(p) for p in parts).encode()) % (2 ** 31)


def _synth_warn(name):
    warnings.warn(f"{name}: no local data and no network access; using "
                  "deterministic synthetic samples.")


class Imdb(Dataset):
    """Binary sentiment corpus: (token_ids, label). Synthetic fallback makes
    class-separable sequences (positive class draws from the upper half of
    the vocab) so classifiers actually learn."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        self.mode = mode
        self.vocab_size = 5000
        _synth_warn("Imdb")
        rng = np.random.RandomState(_dseed("imdb", mode))
        n = 2000 if mode == "train" else 500
        self.labels = rng.randint(0, 2, size=n).astype(np.int64)
        self.docs = []
        half = self.vocab_size // 2
        for y in self.labels:
            length = rng.randint(20, 100)
            lo = half if y else 0
            self.docs.append(
                rng.randint(lo, lo + half, size=length).astype(np.int64))

    def word_idx(self):
        return {f"w{i}": i for i in range(self.vocab_size)}

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(Dataset):
    """PTB-style n-gram language-model dataset: n-token windows."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        self.window_size = window_size
        self.vocab_size = 2000
        _synth_warn("Imikolov")
        rng = np.random.RandomState(_dseed("imikolov", mode))
        n_sent = 500 if mode == "train" else 100
        self.samples = []
        for _ in range(n_sent):
            sent = rng.zipf(1.5, size=rng.randint(window_size, 30))
            sent = np.clip(sent, 0, self.vocab_size - 1).astype(np.int64)
            for i in range(len(sent) - window_size + 1):
                self.samples.append(sent[i:i + window_size])

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return tuple(self.samples[i])


class UCIHousing(Dataset):
    """13-feature regression (Boston housing shape); synthetic linear+noise
    data with a fixed ground-truth weight vector."""

    N_FEATURES = 13

    def __init__(self, data_file=None, mode="train", download=True):
        _synth_warn("UCIHousing")
        rng = np.random.RandomState(_dseed("uci", mode))
        w = np.random.RandomState(_dseed("uci", "w")).randn(self.N_FEATURES)
        n = 400 if mode == "train" else 100
        self.x = rng.randn(n, self.N_FEATURES).astype(np.float32)
        noise = rng.randn(n).astype(np.float32) * 0.1
        self.y = (self.x @ w.astype(np.float32) + noise)[:, None]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Conll05st(Dataset):
    """SRL dataset shape: (word_ids, predicate, label_ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True):
        _synth_warn("Conll05st")
        rng = np.random.RandomState(_dseed("conll", mode))
        n = 300 if mode == "train" else 60
        self.samples = []
        for _ in range(n):
            length = rng.randint(5, 40)
            words = rng.randint(0, 5000, size=length).astype(np.int64)
            pred = rng.randint(0, length)
            labels = rng.randint(0, 20, size=length).astype(np.int64)
            self.samples.append((words, np.int64(pred), labels))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class Movielens(Dataset):
    """(user_id, gender, age, job, movie_id, category, title, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        _synth_warn("Movielens")
        rng = np.random.RandomState(_dseed("ml", mode))
        n = 1000 if mode == "train" else 100
        self.samples = []
        for _ in range(n):
            self.samples.append((
                np.int64(rng.randint(0, 6040)), np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(0, 7)), np.int64(rng.randint(0, 21)),
                np.int64(rng.randint(0, 3952)),
                rng.randint(0, 18, size=3).astype(np.int64),
                rng.randint(0, 5000, size=4).astype(np.int64),
                np.float32(rng.randint(1, 6)),
            ))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class _SynthTranslation(Dataset):
    def __init__(self, name, mode, src_vocab, tgt_vocab):
        _synth_warn(name)
        rng = np.random.RandomState(_dseed(name, mode))
        n = 500 if mode == "train" else 50
        self.samples = []
        for _ in range(n):
            ls = rng.randint(4, 30)
            src = rng.randint(3, src_vocab, size=ls).astype(np.int64)
            tgt = rng.randint(3, tgt_vocab, size=ls + rng.randint(-2, 3)
                              ).astype(np.int64)
            self.samples.append((src, np.concatenate([[1], tgt]),
                                 np.concatenate([tgt, [2]])))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class WMT14(_SynthTranslation):
    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        super().__init__("wmt14", mode, dict_size, dict_size)


class WMT16(_SynthTranslation):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__("wmt16", mode, src_dict_size, trg_dict_size)


# ----------------------------------------------------------------- decoding

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag: bool = True):
    """Max-sum decode of a linear-chain CRF.

    potentials: [B, T, N] emission scores; transition_params: [N, N] (with
    optional BOS=N-2/EOS=N-1 rows when include_bos_eos_tag). Runs as a
    lax.scan over time — single fused XLA loop, batch-parallel.
    Returns (scores [B], paths [B, T]).
    Ref: python/paddle/text/viterbi_decode.py (upstream layout, unverified).
    """
    emissions = potentials._data if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params._data if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    B, T, N = emissions.shape
    if lengths is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = (lengths._data if isinstance(lengths, Tensor)
                else jnp.asarray(lengths)).astype(jnp.int32)

    if include_bos_eos_tag:
        n_real = N - 2
        bos, eos = N - 2, N - 1
        alpha0 = emissions[:, 0, :n_real] + trans[bos, :n_real]
    else:
        n_real = N
        alpha0 = emissions[:, 0, :n_real]

    def step(carry, t):
        alpha, _ = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, t, j]
        scores = alpha[:, :, None] + trans[:n_real, :n_real][None]
        best_prev = jnp.argmax(scores, axis=1)                   # [B, N]
        new_alpha = jnp.max(scores, axis=1) + emissions[:, t, :n_real]
        # masked: beyond a sequence's length, freeze alpha
        active = (t < lens)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        bp = jnp.where(active, best_prev,
                       jnp.broadcast_to(jnp.arange(n_real)[None], best_prev.shape))
        return (new_alpha, None), bp

    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, None), jnp.arange(1, T))
    if include_bos_eos_tag:
        alpha = alpha + trans[:n_real, eos][None]

    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)

    def backtrace(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev.astype(jnp.int32), tag

    _, path_rev = jax.lax.scan(backtrace, last_tag, backptrs, reverse=True)
    paths = jnp.concatenate([path_rev, last_tag[None]], axis=0).T  # [B, T]
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
