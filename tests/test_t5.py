"""T5 encoder-decoder family (upstream: PaddleNLP t5 modeling — ecosystem
layout, unverified; mount empty). Covers the seq2seq-specific machinery:
relative position buckets, trainable position bias, cross-attention,
tied-logit scaling, shift_right, cached greedy decoding parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import T5Config, T5ForConditionalGeneration
from paddle_tpu.models.t5 import _relative_position_bucket


def _tiny(dropout=0.0):
    cfg = T5Config.tiny()
    cfg.dropout_rate = dropout
    return cfg


class TestRelativeBuckets:
    def test_bidirectional_buckets_split_sign(self):
        import jax.numpy as jnp

        rp = jnp.asarray([[-3, -1, 0, 1, 3]])
        b = np.asarray(_relative_position_bucket(rp, True, 8, 16))
        # negative and positive relative positions land in disjoint halves
        assert b[0, 2] == 0
        assert all(x < 4 for x in b[0, :2])
        assert all(x >= 4 for x in b[0, 3:])

    def test_causal_buckets_clip_future(self):
        import jax.numpy as jnp

        rp = jnp.asarray([[-2, 0, 5]])  # 5 = future (mem > ctx)
        b = np.asarray(_relative_position_bucket(rp, False, 8, 16))
        assert b[0, 2] == 0             # future positions collapse to 0
        assert b[0, 0] > 0

    def test_log_buckets_monotonic(self):
        import jax.numpy as jnp

        rp = -jnp.arange(64, dtype=jnp.int32)[None]
        b = np.asarray(_relative_position_bucket(rp, False, 16, 32))[0]
        assert (np.diff(b) >= 0).all()
        assert b.max() == 15            # distant positions hit the cap


class TestT5Forward:
    def test_shapes_and_loss_decreases(self):
        paddle.seed(0)
        model = T5ForConditionalGeneration(_tiny())
        model.train()
        rng = np.random.RandomState(0)
        src = paddle.to_tensor(rng.randint(0, 256, (2, 12)))
        labels = paddle.to_tensor(rng.randint(1, 256, (2, 8)))
        dec_in = model.shift_right(labels)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        first = None
        for _ in range(8):
            logits = model(src, dec_in)
            assert tuple(logits.shape) == (2, 8, 256)
            loss = model.loss(logits, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss.numpy())
        assert float(loss.numpy()) < first

    def test_relative_bias_receives_gradient(self):
        paddle.seed(1)
        model = T5ForConditionalGeneration(_tiny())
        model.train()
        rng = np.random.RandomState(1)
        src = paddle.to_tensor(rng.randint(0, 256, (1, 6)))
        labels = paddle.to_tensor(rng.randint(1, 256, (1, 5)))
        logits = model(src, model.shift_right(labels))
        model.loss(logits, labels).backward()
        enc_bias = model.t5.encoder_layers[0].attn.relative_attention_bias
        dec_bias = model.t5.decoder_layers[0].self_attn \
            .relative_attention_bias
        for bias in (enc_bias, dec_bias):
            g = bias.weight.grad
            assert g is not None
            assert float(np.abs(g.numpy()).max()) > 0.0

    def test_causal_decoder(self):
        # future target tokens must not influence earlier logits
        paddle.seed(2)
        model = T5ForConditionalGeneration(_tiny())
        model.eval()
        rng = np.random.RandomState(2)
        src = paddle.to_tensor(rng.randint(0, 256, (1, 6)))
        dec = rng.randint(1, 256, (1, 6))
        dec2 = dec.copy()
        dec2[0, -1] = (dec2[0, -1] + 7) % 256
        la = model(src, paddle.to_tensor(dec)).numpy()
        lb = model(src, paddle.to_tensor(dec2)).numpy()
        np.testing.assert_allclose(la[0, :-1], lb[0, :-1], atol=1e-5)
        assert not np.allclose(la[0, -1], lb[0, -1])

    def test_encoder_is_bidirectional(self):
        paddle.seed(3)
        model = T5ForConditionalGeneration(_tiny())
        model.eval()
        rng = np.random.RandomState(3)
        src = rng.randint(0, 256, (1, 6))
        src2 = src.copy()
        src2[0, -1] = (src2[0, -1] + 3) % 256
        e1 = model.t5.encode(paddle.to_tensor(src)).numpy()
        e2 = model.t5.encode(paddle.to_tensor(src2)).numpy()
        # changing the LAST source token changes EVERY encoder position
        assert not np.allclose(e1[0, 0], e2[0, 0])

    def test_tied_logit_scale(self):
        cfg = _tiny()
        paddle.seed(4)
        model = T5ForConditionalGeneration(cfg)
        model.eval()
        h = paddle.to_tensor(
            np.random.RandomState(4).randn(1, 2, cfg.d_model)
            .astype(np.float32))
        got = model._logits(h).numpy()
        want = (h.numpy() * cfg.d_model ** -0.5) @ \
            model.t5.shared.weight.numpy().T
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_shift_right(self):
        model = T5ForConditionalGeneration(_tiny())
        lab = paddle.to_tensor(np.asarray([[5, 6, -100]]))
        out = model.shift_right(lab).numpy()
        np.testing.assert_array_equal(
            out, [[model.config.decoder_start_token_id, 5, 6]])


class TestT5Generate:
    def test_cached_decode_matches_full_forward(self):
        """Greedy decode with KV caches must equal argmax over the full
        uncached decoder forward at every step."""
        paddle.seed(5)
        model = T5ForConditionalGeneration(_tiny())
        model.eval()
        rng = np.random.RandomState(5)
        src = paddle.to_tensor(rng.randint(0, 256, (2, 7)))
        out = model.generate(src, max_new_tokens=5).numpy()
        assert out.shape == (2, 5)
        # reference: re-run the full decoder on the greedy prefix
        enc = model.t5.encode(src)
        cur = np.full((2, 1), model.config.decoder_start_token_id,
                      np.int32)
        for t in range(5):
            h = model.t5.decode(paddle.to_tensor(cur), enc)
            step_logits = model._logits(h).numpy()[:, -1]
            nxt = step_logits.argmax(-1)
            np.testing.assert_array_equal(nxt, out[:, t])
            cur = np.concatenate([cur, nxt[:, None].astype(np.int32)],
                                 axis=1)

    def test_eos_padding(self):
        paddle.seed(6)
        model = T5ForConditionalGeneration(_tiny())
        model.eval()
        src = paddle.to_tensor(
            np.random.RandomState(6).randint(0, 256, (1, 4)))
        out = model.generate(src, max_new_tokens=6, eos_token_id=1).numpy()
        hits = np.where(out[0] == 1)[0]
        if hits.size:                      # everything after eos is eos
            assert (out[0, hits[0]:] == 1).all()


def test_generate_jit_cache_memoized():
    """Repeated generate() with the same shape must reuse the jitted
    encode/decode pair (no per-call recompile) and give identical greedy
    output."""
    paddle.seed(7)
    model = T5ForConditionalGeneration(_tiny())
    model.eval()
    src = paddle.to_tensor(
        np.random.RandomState(7).randint(0, 256, (1, 5)))
    a = model.generate(src, max_new_tokens=4).numpy()
    assert len(model._t5_gen_jit_cache) == 1
    b = model.generate(src, max_new_tokens=4).numpy()
    assert len(model._t5_gen_jit_cache) == 1   # memoized, not re-jitted
    np.testing.assert_array_equal(a, b)


def test_t5_through_hapi_model_fit():
    """Seq2seq through the hapi product path: paddle.Model.fit drives the
    dual-input (src, decoder_in) forward with a CE loss over labels —
    loss must fall on a learnable copy task."""
    import paddle_tpu.nn as nn
    from paddle_tpu.io import Dataset

    cfg = _tiny()
    paddle.seed(11)
    net = T5ForConditionalGeneration(cfg)

    rng = np.random.RandomState(11)
    SRC = rng.randint(2, 40, (64, 6)).astype(np.int64)

    class CopyTask(Dataset):
        def __len__(self):
            return len(SRC)

        def __getitem__(self, i):
            src = SRC[i]
            label = src.copy()                      # copy task
            dec_in = np.concatenate(
                [[cfg.decoder_start_token_id], label[:-1]])
            return src, dec_in, label

    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-2,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    before = float(np.asarray(
        model.evaluate(CopyTask(), batch_size=16, verbose=0)["loss"]))
    model.fit(CopyTask(), batch_size=16, epochs=15, verbose=0,
              num_workers=0)
    after = float(np.asarray(
        model.evaluate(CopyTask(), batch_size=16, verbose=0)["loss"]))
    assert after < before * 0.7, (before, after)
