"""Crash recovery for the serving engine (ISSUE 8).

PR 5 made the engine resilient to faults it could ISOLATE — a bad
dispatch quarantines one request, the rest keep serving. But a wedged
runtime, a device reset, or a persistent-fault storm kills the engine
itself, and with it every in-flight request. This module makes the
engine a REPLACEABLE part: kill it at any point and rebuild it with
every unfinished request resuming bit-identically. Three pieces:

- **`RequestJournal`** — an append-only, optionally file-backed log of
  request lifecycle events (`submit` / `tokens` / `terminal` /
  `restart`) that is the single source of truth for what a `stream()`
  consumer has been shown. Tokens enter the journal exactly when the
  engine RETURNS them to the caller (the host-visible delivery point),
  so recovery re-admits each unfinished request as a folded prompt of
  `original prompt + journaled tokens`: everything delivered is absorbed
  into the prompt (never re-delivered), everything undelivered — a
  dispatched-but-undrained decode block, spilled events lost to the
  crash — was by construction never journaled and is recomputed
  bit-identically. That is exactly-once delivery across restarts.

- **`EngineSnapshot` / `ServingEngine.restore()`** — the serializable
  boundary state: per-request metadata, queue order, wall-clock-anchored
  deadlines, and per-request PRNG key state. KV pages are deliberately
  NOT captured: the per-request sampling-key chain advances one split
  per DELIVERED token (`replay_key_state` recomputes it from the seed),
  and the folded re-prefill recreates the K/V through the ordinary
  chunked-prefill / prefix-cache paths — so recovery costs a re-prefill,
  never a re-decode, and the continuation stream is bit-identical for
  greedy and seeded-stochastic sampling (the same fold-and-re-prefill
  parity preemption already relies on).

- **`EngineSupervisor`** — owns the escalation ladder above PR 5's
  retry/quarantine: a FATAL fault (`is_fatal`, e.g. the injector's
  `device_lost` site), a step exceeding `max_step_wall_s` (watchdog), or
  a fault-rate threshold over a sliding window triggers
  drain-what-you-can -> snapshot -> rebuild (via the engine factory) ->
  re-admit, with `check_consistency()` audits on both sides, restart
  counters + a time-to-recover histogram in the metrics registry, and
  `serving.recovery[<k>].<reason>` spans in chrome traces
  (`tools/trace_summary.py` renders them as restart dividers).

Everything here is zero-cost when unused: an engine without a journal
runs one `None` check per step, and no supervisor code exists unless one
is constructed.
"""
from __future__ import annotations

import dataclasses
import json
import time
import warnings
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .resilience import EngineDead, TERMINAL_STATUSES, is_fatal

__all__ = ["EngineSnapshot", "EngineSupervisor", "RequestJournal",
           "RequestRecord", "RequestSnapshot", "replay_key_state"]


def replay_key_state(seed: int, delivered: int):
    """Per-request PRNG key data after `delivered` tokens, recomputed
    from the effective seed: the engine's sampling chain starts at
    `key(seed)` and advances exactly one `split` per delivered token
    (prefill's first token and every drained decode-block token each
    consume one), with intermediate prefill chunks leaving the state
    untouched. Key adoption syncs host state at block boundaries, so for
    a live request this equals the engine's `_key_state` at any step
    boundary — which is why a boundary snapshot (or a journal replay
    after a crash) restores sampling bit-identically."""
    import jax

    key = jax.random.key(int(seed))
    for _ in range(delivered):
        key = jax.random.split(key)[0]
    return jax.random.key_data(key)


# --------------------------------------------------------------- journal

@dataclasses.dataclass
class RequestRecord:
    """Aggregated journal view of one request: the submit metadata plus
    everything delivered so far and how (whether) it ended."""

    request_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    top_k: int
    top_p: float
    seed: int                     # effective sampling seed (never None)
    eos_token_id: Optional[int]
    deadline_wall: Optional[float]   # absolute time.time() deadline
    arrival_wall: float
    delivered: List[int] = dataclasses.field(default_factory=list)
    status: Optional[str] = None     # terminal status, None while live
    error: Optional[str] = None
    first_token_wall: Optional[float] = None
    last_token_wall: Optional[float] = None
    # PRNG splits consumed BEFORE this record's first delivered token.
    # 0 for ordinary submissions; a hedge clone admitted as a fold of an
    # older request inherits that request's split count, so
    # `replay_key_state(seed, key_splits + len(delivered))` is the
    # correct chain position for ANY record, however many times it has
    # been folded or migrated.
    key_splits: int = 0

    @property
    def live(self) -> bool:
        return self.status is None

    def is_complete(self) -> bool:
        """Delivered stream already satisfies the stopping rule (budget
        or EOS) — nothing left to recompute even without a journaled
        `finished` event (the finish record itself can be lost to a
        crash; the tokens cannot, or they were never delivered)."""
        if len(self.delivered) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and bool(self.delivered)
                and self.delivered[-1] == self.eos_token_id)


class RequestJournal:
    """Append-only request journal: the exactly-once delivery ledger.

    The engine appends `submit` on `add_request`, `tokens` at the moment
    a step RETURNS events to the caller, and `terminal` when a request
    reaches a terminal status; the supervisor appends `restart` epochs.
    `path=` makes it file-backed (one JSON object per line, flushed per
    append) so a journal can outlive the process; `RequestJournal.load`
    rebuilds one from such a file.

    Tokens recorded here have been SHOWN to a `stream()`/`step()`
    consumer; recovery folds them into the re-admitted prompt, so they
    are never delivered twice. Tokens the engine computed but never
    returned (an undrained decode block, spill lost mid-crash) never
    reach the journal and are recomputed bit-identically. Token records
    arriving after a terminal record (a cancel drained its block first)
    are kept for the audit trail but never change the terminal outcome.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._records: Dict[int, RequestRecord] = {}
        self._order: List[int] = []          # submission order
        self.restarts: List[dict] = []
        self._fh = open(path, "a", encoding="utf-8") if path else None

    # ------------------------------------------------------------ appends
    def _persist(self, obj: dict) -> None:
        if self._fh is not None:
            self._fh.write(json.dumps(obj) + "\n")
            self._fh.flush()

    def submit(self, *, request_id: int, prompt: List[int],
               max_new_tokens: int, temperature: float, top_k: int,
               top_p: float, seed: int, eos_token_id: Optional[int],
               deadline_wall: Optional[float],
               arrival_wall: Optional[float] = None,
               key_splits: int = 0) -> None:
        if request_id in self._records:
            raise ValueError(
                f"request {request_id} already journaled")
        rec = RequestRecord(
            request_id=request_id, prompt=list(prompt),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), seed=int(seed),
            eos_token_id=eos_token_id, deadline_wall=deadline_wall,
            arrival_wall=(time.time() if arrival_wall is None
                          else arrival_wall),
            key_splits=int(key_splits))
        self._records[request_id] = rec
        self._order.append(request_id)
        obj = {"ev": "submit", "rid": request_id,
               "prompt": rec.prompt,
               "max_new_tokens": rec.max_new_tokens,
               "temperature": rec.temperature,
               "top_k": rec.top_k, "top_p": rec.top_p,
               "seed": rec.seed,
               "eos_token_id": rec.eos_token_id,
               "deadline_wall": rec.deadline_wall,
               "arrival_wall": rec.arrival_wall}
        if rec.key_splits:
            obj["key_splits"] = rec.key_splits
        self._persist(obj)

    def adopt(self, rec: RequestRecord) -> None:
        """Register a copy of another journal's record (cluster
        migration: the consumer-visible history of a request moving off
        a dead replica). The copy is live (terminal state stays with the
        old incarnation), carries the ORIGINAL prompt plus everything
        delivered so far, and persists as an equivalent submit + tokens
        pair so a reload of THIS journal reconstructs it."""
        if rec.request_id in self._records:
            raise ValueError(
                f"request {rec.request_id} already journaled")
        self.submit(request_id=rec.request_id, prompt=rec.prompt,
                    max_new_tokens=rec.max_new_tokens,
                    temperature=rec.temperature, top_k=rec.top_k,
                    top_p=rec.top_p, seed=rec.seed,
                    eos_token_id=rec.eos_token_id,
                    deadline_wall=rec.deadline_wall,
                    arrival_wall=rec.arrival_wall,
                    key_splits=rec.key_splits)
        if rec.delivered:
            self.tokens(rec.request_id, list(rec.delivered),
                        t_wall=rec.last_token_wall)
            self._records[rec.request_id].first_token_wall = \
                rec.first_token_wall

    def tokens(self, request_id: int, toks: List[int],
               t_wall: Optional[float] = None) -> None:
        rec = self._records[request_id]
        if t_wall is None:
            t_wall = time.time()
        if rec.first_token_wall is None:
            rec.first_token_wall = t_wall
        rec.last_token_wall = t_wall
        rec.delivered.extend(int(t) for t in toks)
        self._persist({"ev": "tokens", "rid": request_id,
                       "toks": [int(t) for t in toks],
                       "t_wall": t_wall})

    def terminal(self, request_id: int, status: str,
                 error: Optional[str] = None) -> None:
        if status not in TERMINAL_STATUSES:
            raise ValueError(f"not a terminal status: {status!r}")
        rec = self._records[request_id]
        if rec.status is not None:
            return                   # idempotent: first terminal wins
        rec.status = status
        rec.error = error
        self._persist({"ev": "terminal", "rid": request_id,
                       "status": status, "error": error})

    def restart(self, epoch: int, reason: str, t_recover_s: float,
                readmitted: int = 0, replayed_tokens: int = 0) -> None:
        obj = {"ev": "restart", "epoch": epoch, "reason": reason,
               "t_recover_s": t_recover_s, "readmitted": readmitted,
               "replayed_tokens": replayed_tokens,
               "t_wall": time.time()}
        self.restarts.append(obj)
        self._persist(obj)

    # ------------------------------------------------------------ queries
    def record(self, request_id: int) -> RequestRecord:
        return self._records[request_id]

    def known(self, request_id: int) -> bool:
        return request_id in self._records

    def request_ids(self) -> List[int]:
        return list(self._order)

    def delivered(self, request_id: int) -> List[int]:
        return list(self._records[request_id].delivered)

    def live_records(self) -> List[RequestRecord]:
        """Submission-ordered records with no terminal status — the set a
        restore must account for (re-admit, expire, or complete)."""
        return [self._records[r] for r in self._order
                if self._records[r].status is None]

    def check_consistency(self) -> bool:
        """Journal invariant audit: per request at most `max_new_tokens`
        delivered, no tokens past a delivered EOS, submission order
        consistent. Raises RuntimeError on the first violation."""
        if sorted(self._order) != sorted(self._records):
            raise RuntimeError("journal corrupt: order/record mismatch")
        for rec in self._records.values():
            if len(rec.delivered) > rec.max_new_tokens:
                raise RuntimeError(
                    f"journal corrupt: request {rec.request_id} "
                    f"delivered {len(rec.delivered)} tokens over its "
                    f"budget {rec.max_new_tokens}")
            if rec.eos_token_id is not None \
                    and rec.eos_token_id in rec.delivered[:-1]:
                raise RuntimeError(
                    f"journal corrupt: request {rec.request_id} "
                    "delivered tokens past EOS")
        return True

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    @classmethod
    def load(cls, path: str) -> "RequestJournal":
        """Rebuild a journal from its JSONL file (a restart in a fresh
        process): replays every record through the ordinary append path
        with persistence off, then re-attaches the file for appends.

        A TORN FINAL LINE — the writer died mid-append, so the file ends
        in a partial JSON record — is tolerated: the tail is truncated
        off (with a warning) and everything before it loads normally.
        One torn record is exactly what a kill-anywhere crash can
        produce, and by the delivery contract a token record that never
        finished hitting the disk was never shown to a consumer, so
        dropping it is correct (the token is recomputed, not lost).
        Corruption anywhere BEFORE the final record is still an error:
        that is not a torn append but a damaged file."""
        j = cls()
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        for raw in data.splitlines(keepends=True):
            start, pos = pos, pos + len(raw)
            line = raw.strip()
            if not line:
                continue
            try:
                obj = json.loads(line.decode("utf-8"))
                ev = obj["ev"]
                if ev == "submit":
                    j.submit(request_id=obj["rid"], prompt=obj["prompt"],
                             max_new_tokens=obj["max_new_tokens"],
                             temperature=obj["temperature"],
                             top_k=obj["top_k"], top_p=obj["top_p"],
                             seed=obj["seed"],
                             eos_token_id=obj["eos_token_id"],
                             deadline_wall=obj["deadline_wall"],
                             arrival_wall=obj["arrival_wall"],
                             key_splits=obj.get("key_splits", 0))
                elif ev == "tokens":
                    j.tokens(obj["rid"], obj["toks"],
                             t_wall=obj["t_wall"])
                elif ev == "terminal":
                    j.terminal(obj["rid"], obj["status"], obj["error"])
                elif ev == "restart":
                    j.restarts.append(obj)
            except (ValueError, KeyError, UnicodeDecodeError) as e:
                if data[pos:].strip():
                    # damage with intact records AFTER it cannot be a
                    # torn append — refuse to guess
                    raise ValueError(
                        f"corrupt journal record at byte {start} of "
                        f"{path}: {line[:80]!r}") from e
                warnings.warn(
                    f"journal {path}: dropping torn final record "
                    f"({len(raw)} bytes, writer died mid-append)",
                    RuntimeWarning, stacklevel=2)
                with open(path, "r+b") as fh:
                    fh.truncate(start)
                break
        j.path = path
        j._fh = open(path, "a", encoding="utf-8")
        return j


# -------------------------------------------------------------- snapshot

@dataclasses.dataclass
class RequestSnapshot:
    """One unfinished request's restorable state. `prompt` is the
    ORIGINAL prompt and `delivered` the journaled tokens — the restore
    side folds them (`prompt + delivered`) and re-prefills; `key_data`
    is the (2,) uint32 PRNG key state after `len(delivered)` splits
    (== `replay_key_state(seed, len(delivered))`), so the continuation
    samples bit-identically."""

    request_id: int
    prompt: List[int]
    delivered: List[int]
    max_new_tokens: int              # ORIGINAL budget
    temperature: float
    top_k: int
    top_p: float
    seed: int
    eos_token_id: Optional[int]
    deadline_wall: Optional[float]
    arrival_wall: float
    first_token_wall: Optional[float]
    last_token_wall: Optional[float]
    preemptions: int
    parked: bool
    key_data: Tuple[int, int]


@dataclasses.dataclass
class EngineSnapshot:
    """Boundary state of a ServingEngine: scheduler queue order (running
    in admission order, then waiting in queue order — FCFS survives the
    restart), per-request metadata/progress, and the config the restore
    target is validated against. KV pages and undrained decode blocks
    are deliberately absent — see the module docstring for why that is
    safe (and cheaper than checkpointing pools)."""

    config: Dict[str, object]
    requests: List[RequestSnapshot]
    taken_wall: float

    def to_json(self) -> str:
        return json.dumps({
            "config": self.config, "taken_wall": self.taken_wall,
            "requests": [dataclasses.asdict(r) for r in self.requests],
        })

    @classmethod
    def from_json(cls, s: str) -> "EngineSnapshot":
        obj = json.loads(s)
        return cls(config=obj["config"], taken_wall=obj["taken_wall"],
                   requests=[RequestSnapshot(
                       **{**r, "key_data": tuple(r["key_data"])})
                       for r in obj["requests"]])


# ------------------------------------------------------------ supervisor

class EngineSupervisor:
    """Keeps a ServingEngine alive across engine-level failures.

    The supervisor owns the journal and an engine FACTORY (a zero-arg
    callable returning a fresh `ServingEngine`; share one
    MetricsRegistry across incarnations by closing over `metrics=` in
    the factory). Drive it exactly like an engine — `add_request` /
    `step` / `stream` / `run` / `cancel` / `status` / `output` — and it
    transparently restarts the engine when:

    - a step raises a FATAL fault (`is_fatal`: the injector's
      `device_lost` site, or any exception carrying `fatal=True`);
    - a step's wall time exceeds `max_step_wall_s` (watchdog — a wedged
      dispatch is indistinguishable from a dead device, and a step that
      slow is evidence the runtime is sick);
    - `fault_rate_threshold` faults accumulate over the last
      `fault_rate_window` steps (transient-retry storms and quarantine
      cascades stop being isolated incidents at some rate).

    A restart runs: drain-what-you-can (`engine.salvage()` — tokens an
    answering device can still surface are delivered and journaled, a
    dead one loses only what was never delivered), `check_consistency()`
    on the wreck, `snapshot()`, factory-rebuild, `restore()` (folded
    re-prefill re-admission), `check_consistency()` on the new engine.
    `cancel(rid)` issued while a restore is in flight is recorded and
    wins over re-admission; a request whose wall-clock deadline passed
    during the outage is expired, never resurrected.
    """

    RESTART_REASONS = ("fatal_fault", "watchdog", "fault_storm",
                      "manual")

    def __init__(self, factory: Callable[[], object], *,
                 journal: Optional[RequestJournal] = None,
                 metrics=None,
                 max_step_wall_s: Optional[float] = None,
                 fault_rate_threshold: Optional[int] = None,
                 fault_rate_window: int = 32,
                 max_restarts: int = 8,
                 clock: Callable[[], float] = time.perf_counter):
        self._factory = factory
        self.journal = journal if journal is not None else RequestJournal()
        self.max_step_wall_s = max_step_wall_s
        self.fault_rate_threshold = fault_rate_threshold
        self.max_restarts = max_restarts
        self._clock = clock
        self._fault_window: deque = deque(maxlen=max(fault_rate_window, 1))
        self._pending_cancels: set = set()
        self._restoring = False
        # set when max_restarts is exhausted: the engine object is
        # dropped (`self.engine = None` — it IS gone) and every
        # drive-the-engine entry point raises EngineDead, while
        # status/output/stats keep answering from the journal
        self.dead_reason: Optional[str] = None
        # forensics (ISSUE 13): the EngineDead path builds a post-mortem
        # bundle from the dying engine BEFORE dropping it — stashed here
        # (and written to the engine's postmortem_dir when it has one)
        # so a ServingCluster can fold migration events in and re-dump.
        # `_dead_recorder` keeps the dead engine's flight-recorder ring
        # reachable after `self.engine = None`.
        self.postmortem: Optional[dict] = None
        self.postmortem_path: Optional[str] = None
        self._dead_recorder = None
        # test/ops hook: called between snapshot and re-admission, the
        # window where a concurrent control-plane cancel() must still win
        self._mid_restore_hook: Optional[Callable] = None
        self.restarts: List[dict] = []
        self.metrics = metrics
        if metrics is not None:
            self._m_restarts = {
                reason: metrics.counter(
                    "serving_engine_restarts_total",
                    "engine rebuilds by escalation reason",
                    labels={"reason": reason})
                for reason in self.RESTART_REASONS}
            self._m_recover = metrics.histogram(
                "serving_recovery_seconds",
                "drain+snapshot+rebuild+re-admit wall time")
            self._m_replayed = metrics.counter(
                "serving_recovery_replayed_tokens_total",
                "folded-prompt tokens re-prefilled by restores")
        else:
            self._m_restarts = None
            self._m_recover = None
            self._m_replayed = None
        self.engine = factory()
        self.engine.attach_journal(self.journal)

    # --------------------------------------------------------- dead state
    @property
    def dead(self) -> bool:
        return self.dead_reason is not None

    def _check_alive(self) -> None:
        if self.dead_reason is not None:
            raise EngineDead(
                f"engine is dead ({self.dead_reason}); journal queries "
                "(status/output/stats) still answer",
                reason=self.dead_reason, restarts=len(self.restarts))

    # ------------------------------------------------------- request API
    def add_request(self, *args, **kwargs) -> int:
        self._check_alive()
        return self.engine.add_request(*args, **kwargs)

    def cancel(self, request_id: int) -> bool:
        if self._restoring:
            # mid-restore: the engine being rebuilt must not resurrect
            # this request — recorded here, applied by restore()
            self._pending_cancels.add(request_id)
            return True
        if self.engine is None:
            # dead supervisor: no engine to stop, but the journal record
            # must still end so consumers (and a migrating cluster) see
            # the cancel — first terminal wins as usual
            rec = self.journal.record(request_id)
            if rec.status is not None:
                return False
            self.journal.terminal(request_id, "cancelled")
            return True
        return self.engine.cancel(request_id)

    def status(self, request_id: int) -> Tuple[str, Optional[str]]:
        """(status, error), falling back to the journal for requests that
        ended before the last restart (terminal requests are not carried
        into rebuilt engines — the journal is their record) and for
        everything once the supervisor is dead."""
        req = (self.engine.requests.get(request_id)
               if self.engine is not None else None)
        if req is not None:
            return req.status, req.error
        rec = self.journal.record(request_id)
        return (rec.status if rec.status is not None else "waiting",
                rec.error)

    def output(self, request_id: int) -> List[int]:
        req = (self.engine.requests.get(request_id)
               if self.engine is not None else None)
        if req is not None:
            return self.engine.output(request_id)
        rec = self.journal.record(request_id)
        return list(rec.prompt) + list(rec.delivered)

    # ------------------------------------------------------------- steps
    def has_work(self) -> bool:
        eng = self.engine
        if eng is None:
            return False
        return (eng.scheduler.has_work() or eng._pending is not None
                or bool(eng._spill))

    def step(self) -> List[Tuple[int, int]]:
        self._check_alive()
        eng = self.engine
        faults_before = eng.fault_events
        t0 = self._clock()
        try:
            events = eng.step()
        except Exception as e:  # noqa: BLE001 — escalation boundary
            if not is_fatal(e):
                raise
            return self._restart("fatal_fault", exc=e)
        dt = self._clock() - t0
        if self.fault_rate_threshold is not None:
            self._fault_window.append(eng.fault_events - faults_before)
        if self.max_step_wall_s is not None and dt > self.max_step_wall_s:
            # the step DID return, but a step this slow means the runtime
            # is wedging; restart proactively at a clean boundary
            return events + self._escalate("watchdog", events)
        if self.fault_rate_threshold is not None and \
                sum(self._fault_window) >= self.fault_rate_threshold:
            self._fault_window.clear()
            return events + self._escalate("fault_storm", events)
        return events

    def _escalate(self, reason: str,
                  events: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
        """Post-step escalation: the step returned (and journaled)
        `events` before the restart decision. If the restart budget is
        gone the EngineDead raise would otherwise swallow them — already
        marked delivered in the journal, never shown to the caller — so
        they ride on the exception for the caller (a ServingCluster) to
        deliver before migrating."""
        try:
            return self._restart(reason)
        except EngineDead as e:
            e.undelivered = list(events)
            raise

    def stream(self) -> Iterable[Tuple[int, int, bool]]:
        """Generator of (request_id, token, done) across restarts: the
        engine under the hood may be rebuilt mid-stream, the token
        sequence each consumer sees is exactly-once regardless."""
        while True:
            eng = self.engine
            if eng.scheduler.has_work():
                events = self.step()
            elif eng._pending is not None or eng._spill:
                events = eng.drain_all()
            else:
                break
            for i, (rid, tok) in enumerate(events):
                # status() rather than the engine's request table: a
                # salvaged event may belong to a request that finished
                # during the restart and was not carried into the
                # rebuilt engine — the journal still knows it
                status, _ = self.status(rid)
                done = (status == "finished"
                        and all(r != rid for r, _ in events[i + 1:]))
                yield rid, tok, done

    def run(self) -> Dict[int, List[int]]:
        for _ in self.stream():
            pass
        return {rid: self.output(rid)
                for rid in self.journal.request_ids()}

    def restart(self) -> List[Tuple[int, int]]:
        """Operator-initiated restart (planned maintenance, config
        rollouts): same drain/snapshot/rebuild/re-admit ladder as the
        automatic escalations."""
        self._check_alive()
        return self._restart("manual")

    # ---------------------------------------------------------- recovery
    def _restart(self, reason: str,
                 exc: Optional[BaseException] = None
                 ) -> List[Tuple[int, int]]:
        from ..profiler import add_host_span

        if len(self.restarts) >= self.max_restarts:
            # the budget is gone — declare the replica dead. The engine
            # object is dropped (the device it wrapped is the thing that
            # kept failing); the journal stays as the record of every
            # request, which is what stats/status/output answer from and
            # what a ServingCluster replays to migrate the survivors.
            self.dead_reason = (
                f"{reason}" + (f": {exc}" if exc else ""))
            # forensics BEFORE the engine object is dropped: record the
            # death in the ring, build the bundle, keep the ring alive
            # for the cluster to append migration events, and dump if a
            # postmortem_dir is configured. All duck-typed and guarded —
            # forensics must never mask the EngineDead raise.
            old = self.engine
            try:
                rec = getattr(old, "_recorder", None)
                if rec is not None:
                    rec.record("dead", reason=reason,
                               error=(str(exc) if exc else None),
                               restarts=len(self.restarts))
                self._dead_recorder = rec
                build = getattr(old, "build_postmortem", None)
                if build is not None:
                    self.postmortem = build(
                        f"dead-{reason}",
                        info={"restarts": list(self.restarts),
                              "dead_reason": self.dead_reason})
                if (self.postmortem is not None
                        and getattr(old, "_postmortem_dir", None)):
                    from ..observability.flight_recorder import \
                        dump_postmortem
                    self.postmortem_path = dump_postmortem(
                        self.postmortem, old._postmortem_dir)
            except Exception:  # noqa: BLE001 — forensics must not mask death
                pass
            self.engine = None
            raise EngineDead(
                f"engine restarted {len(self.restarts)} times "
                f"(max_restarts={self.max_restarts}); giving up on "
                f"{reason}" + (f": {exc}" if exc else ""),
                reason=reason, restarts=len(self.restarts))
        t0 = time.perf_counter()
        old = self.engine
        try:
            # drain-what-you-can: a still-answering device surfaces (and
            # journals) its pending block; a dead one only loses tokens
            # that were never delivered — the rebuild recomputes them
            events = old.salvage()
        except Exception:  # noqa: BLE001 — the device may be truly gone
            events = []
        old.scheduler.check_consistency()
        snap = old.snapshot()
        self._restoring = True
        try:
            if self._mid_restore_hook is not None:
                self._mid_restore_hook(self)
            new = self._factory()
            new.attach_journal(self.journal)
            cancelled, self._pending_cancels = self._pending_cancels, set()
            readmitted = new.restore(snap, cancelled=cancelled)
        finally:
            self._restoring = False
        self.engine = new
        new.scheduler.check_consistency()
        t1 = time.perf_counter()
        replayed = sum(len(new.requests[rid].prompt)
                       for rid in readmitted)
        epoch = len(self.restarts) + 1
        info = {"epoch": epoch, "reason": reason,
                "t_recover_s": t1 - t0, "readmitted": len(readmitted),
                "replayed_tokens": replayed,
                "error": repr(exc) if exc is not None else None}
        self.restarts.append(info)
        self.journal.restart(epoch, reason, t1 - t0,
                             readmitted=len(readmitted),
                             replayed_tokens=replayed)
        rec = getattr(new, "_recorder", None)
        if rec is not None:
            # factories that share one FlightRecorder across rebuilds
            # (the journal discipline) get a continuous ring with the
            # restart marked in-line
            rec.record("restart", epoch=epoch, reason=reason,
                       readmitted=len(readmitted))
        # chrome-trace marker: trace_summary renders this span as a
        # `-- restart #k --` divider inside request timelines
        add_host_span(f"serving.recovery[{epoch}].{reason}", t0, t1,
                      event_type="Recovery")
        if self._m_restarts is not None:
            self._m_restarts[reason].inc()
            self._m_recover.observe(t1 - t0)
            self._m_replayed.inc(replayed)
        return events

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, object]:
        """Engine stats plus restart history. After the supervisor is
        declared dead (max_restarts exhausted) the engine object is
        gone, so the summary is rebuilt from the journal — reporting the
        terminal reason instead of raising."""
        if self.engine is None:
            terminal: Dict[str, int] = {}
            live = 0
            for rid in self.journal.request_ids():
                rec = self.journal.record(rid)
                if rec.status is None:
                    live += 1
                else:
                    terminal[rec.status] = terminal.get(rec.status, 0) + 1
            s: Dict[str, object] = {
                "num_requests": len(self.journal.request_ids()),
                "num_finished": terminal.get("finished", 0),
                "num_live": live,
                "terminal": terminal,
            }
        else:
            s = self.engine.stats()
        s["dead"] = self.engine is None
        s["dead_reason"] = self.dead_reason
        s["restarts"] = list(self.restarts)
        s["num_restarts"] = len(self.restarts)
        return s
