"""Bench supervisor: sticky backend-init probe verdict (ISSUE 20).

BENCH_r05 failure mode under test: attempt 1's child wedges inside
backend init; attempt 2 re-imports jax on the SAME dead runtime and
burns its whole 700 s with no parsed metric. The fix is a sticky
verdict: once init is known-wedged — probe-detected (verdict file) or
hard-wedged (partial's wedged_phase=init|smoke) — every later attempt
starts pinned to `BENCH_FORCE_CPU=1`.

All hermetic: the probe is faked via the BENCH_BACKEND_PROBE_CMD test
seam (a real subprocess that wedges/dies on cue), and the supervisor
loop runs with `_run_child` stubbed — no jax import, no TPU."""
import json
import os
import sys

import pytest

import bench


@pytest.fixture()
def scratch(tmp_path, monkeypatch):
    """Point every bench scratch path at a tmp dir."""
    monkeypatch.setattr(bench, "TRACE_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "PARTIAL_PATH",
                        str(tmp_path / "bench_partial.json"))
    monkeypatch.setattr(bench, "VERDICT_PATH",
                        str(tmp_path / "backend_probe_verdict.json"))
    return tmp_path


class TestProbe:
    def test_healthy_probe_returns_none(self, monkeypatch):
        monkeypatch.setenv("BENCH_BACKEND_PROBE_CMD", "pass")
        assert bench._probe_backend_init(30.0) is None

    def test_wedging_probe_times_out(self, monkeypatch):
        # the fake wedged backend: hangs far past the probe budget
        monkeypatch.setenv("BENCH_BACKEND_PROBE_CMD",
                           "import time; time.sleep(60)")
        reason = bench._probe_backend_init(1.0)
        assert reason is not None and "timed out" in reason

    def test_dying_probe_reports_exit(self, monkeypatch):
        monkeypatch.setenv("BENCH_BACKEND_PROBE_CMD",
                           "raise SystemExit(7)")
        reason = bench._probe_backend_init(30.0)
        assert reason is not None and "exit 7" in reason

    def test_verdict_round_trip(self, scratch):
        assert bench._read_probe_verdict() is None
        bench._write_probe_verdict("probe timed out after 1s")
        assert bench._read_probe_verdict() == "probe timed out after 1s"

    def test_garbled_verdict_reads_as_none(self, scratch):
        with open(bench.VERDICT_PATH, "w") as f:
            f.write("not json{")
        assert bench._read_probe_verdict() is None


class TestWedgedVerdict:
    def test_none_without_signals(self, scratch):
        assert bench._backend_wedged_verdict() is None

    def test_verdict_file_wins(self, scratch):
        bench._write_probe_verdict("probe exit 1: dead")
        assert bench._backend_wedged_verdict() == "probe exit 1: dead"

    @pytest.mark.parametrize("phase", ["init", "smoke"])
    def test_wedged_init_phase_counts(self, scratch, phase):
        with open(bench.PARTIAL_PATH, "w") as f:
            json.dump({"detail": {"wedged_phase": phase}}, f)
        v = bench._backend_wedged_verdict()
        assert v is not None and phase in v

    def test_late_wedge_does_not_count(self, scratch):
        # the backend came up and died later — retrying TPU is correct
        with open(bench.PARTIAL_PATH, "w") as f:
            json.dump({"detail": {"wedged_phase": "serving_prefix"}}, f)
        assert bench._backend_wedged_verdict() is None


def _fake_metric_line(device: str = "cpu") -> str:
    return json.dumps({"metric": bench.METRIC, "value": 123.0,
                       "unit": bench.UNIT, "vs_baseline": 1.0,
                       "detail": {"device": device}})


class _Supervisor:
    """Run bench.main() in supervisor mode with _run_child stubbed.

    `script` maps attempt index -> behaviour: a callable invoked with
    the attempt's extra_env; returns the child's line (or None for a
    failed/timed-out attempt)."""

    def __init__(self, monkeypatch, script):
        self.envs = []
        self.emitted = []
        self.printed = []

        def run_child(extra_env, timeout):
            idx = len(self.envs)
            self.envs.append(dict(extra_env))
            return script[idx](extra_env) if idx < len(script) else None

        monkeypatch.setattr(bench, "_run_child", run_child)
        monkeypatch.setattr(bench, "_emit",
                            lambda obj: self.emitted.append(obj))
        monkeypatch.setattr(bench, "_log", lambda msg: None)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setattr(bench, "print",
                            lambda *a, **k: self.printed.append(a),
                            raising=False)
        monkeypatch.delenv("BENCH_CHILD", raising=False)


class TestStickySupervisor:
    def test_attempt2_pinned_to_cpu_after_init_wedge(self, scratch,
                                                     monkeypatch):
        """THE regression: attempt 1 dies at init (probe verdict left
        behind), attempt 2 must start with BENCH_FORCE_CPU=1 and its
        successful number must be marked as the CPU fallback."""
        def attempt1(extra_env):
            # the child's probe found the backend wedged and wrote the
            # sticky verdict — then the child itself died anyway
            bench._write_probe_verdict("probe timed out after 180s")
            return None

        def attempt2(extra_env):
            assert extra_env.get("BENCH_FORCE_CPU") == "1"
            return _fake_metric_line("cpu")

        sup = _Supervisor(monkeypatch, [attempt1, attempt2])
        bench.main()
        assert len(sup.envs) == 2
        assert "BENCH_FORCE_CPU" not in sup.envs[0]
        assert sup.envs[1].get("BENCH_FORCE_CPU") == "1"
        assert len(sup.emitted) == 1
        out = sup.emitted[0]
        assert out["error"] == "tpu backend unavailable; CPU fallback number"
        assert out["vs_baseline"] == 0.0
        assert "probe timed out" in out["detail"]["backend_verdict"]

    def test_attempt2_pinned_after_hard_init_wedge(self, scratch,
                                                   monkeypatch):
        """No probe verdict (the child hard-wedged before writing one),
        but the per-phase watchdog recorded wedged_phase=init."""
        def attempt1(extra_env):
            with open(bench.PARTIAL_PATH, "w") as f:
                json.dump({"detail": {"wedged_phase": "init"}}, f)
            return None

        def attempt2(extra_env):
            return _fake_metric_line("cpu")

        sup = _Supervisor(monkeypatch, [attempt1, attempt2])
        bench.main()
        assert sup.envs[1].get("BENCH_FORCE_CPU") == "1"
        assert sup.emitted[0]["vs_baseline"] == 0.0

    def test_late_failure_retries_tpu(self, scratch, monkeypatch):
        """Attempt 1 died AFTER init — the backend works; attempt 2
        must retry the default (TPU) backend, and its clean line is
        printed unmarked."""
        def attempt1(extra_env):
            with open(bench.PARTIAL_PATH, "w") as f:
                json.dump({"detail": {"wedged_phase": "pretrain"}}, f)
            return None

        def attempt2(extra_env):
            assert "BENCH_FORCE_CPU" not in extra_env
            return _fake_metric_line("tpu")

        sup = _Supervisor(monkeypatch, [attempt1, attempt2])
        bench.main()
        assert "BENCH_FORCE_CPU" not in sup.envs[1]
        assert sup.emitted == []          # clean line printed, not marked
        assert len(sup.printed) == 1

    def test_stale_verdict_cleared_at_run_start(self, scratch,
                                                monkeypatch):
        """A verdict from a PREVIOUS run must not pin this run's
        attempt 1 (or 2): the supervisor clears it up front."""
        bench._write_probe_verdict("stale from yesterday")

        def attempt1(extra_env):
            assert "BENCH_FORCE_CPU" not in extra_env
            return _fake_metric_line("tpu")

        sup = _Supervisor(monkeypatch, [attempt1])
        bench.main()
        assert len(sup.envs) == 1
        assert not os.path.exists(bench.VERDICT_PATH)
        assert len(sup.printed) == 1


class TestChildStickyPath:
    def test_child_honors_existing_verdict_without_reprobing(
            self, scratch, monkeypatch):
        """Belt-and-braces: a CHILD that starts with a verdict on disk
        must skip the probe entirely (no subprocess spawn) — re-running
        a probe against a known-dead backend wastes its budget."""
        bench._write_probe_verdict("probe timed out after 180s")
        calls = []
        monkeypatch.setattr(bench, "_probe_backend_init",
                            lambda t: calls.append(t) or None)
        # replicate only the init-decision logic the child runs
        assert os.environ.get("BENCH_FORCE_CPU") != "1"
        sticky = bench._read_probe_verdict()
        assert sticky is not None
        assert calls == []
