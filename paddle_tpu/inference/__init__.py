"""paddle.inference — Config / Predictor API over the StableHLO export path
(ref: paddle/fluid/inference/api/analysis_predictor.* +
python/paddle/inference/wrapper.py, upstream layout, unverified — mount
empty).

Paddle's AnalysisPredictor runs IR analysis passes then executes on a
runtime; here the whole analyze+optimize+schedule pipeline IS XLA: the
artifact saved by `static.save_inference_model` / `jit.save` is a serialized
`jax.export` module (compiled ahead-of-time per input signature), and the
Predictor is a thin handle layer (named input/output tensors, copy_from_cpu/
copy_to_cpu) over its execution. Config toggles that steer upstream's
IR passes (ir_optim, memory_optim, mkldnn, ...) are accepted and recorded
for API parity — XLA already performs the corresponding optimizations.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


def get_version() -> str:
    from .. import __version__

    return __version__


class Config:
    """Predictor configuration (AnalysisConfig analog)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle accepts Config(model_dir) or Config(prog, params); both
        # resolve here to the .tpu_model artifact directory
        self._model_path = prog_file
        self._params_file = params_file
        self._use_device = "tpu" if _default_is_accel() else "cpu"
        self._memory_pool_init_mb = 100
        self._flags: Dict[str, object] = {
            "ir_optim": True, "memory_optim": False, "mkldnn": False,
            "glog_info": False, "precision": PrecisionType.Float32,
        }

    # ----------------------------------------------------------- model path
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._model_path = prog_file
        self._params_file = params_file

    def model_dir(self) -> Optional[str]:
        return self._model_path

    def prog_file(self) -> Optional[str]:
        return self._model_path

    def params_file(self) -> Optional[str]:
        return self._params_file

    # -------------------------------------------------------------- devices
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0, precision=None):
        # accelerator selection is owned by the jax backend; record intent.
        # precision is ACTED ON: Half/Bfloat16 select the bf16 StableHLO
        # variant exported next to the f32 module (see Predictor)
        self._use_device = "accelerator"
        self._memory_pool_init_mb = memory_pool_init_size_mb
        if precision is not None:
            self._flags["precision"] = precision

    def set_precision(self, precision):
        """Select the executed artifact's precision (PrecisionType.*):
        Half/Bfloat16 run the bf16-compute StableHLO module."""
        self._flags["precision"] = precision

    def precision(self):
        return self._flags["precision"]

    def disable_gpu(self):
        self._use_device = "cpu"

    def use_gpu(self) -> bool:
        return self._use_device != "cpu"

    def enable_xpu(self, *a, **k):
        self._use_device = "accelerator"

    def enable_custom_device(self, device_type: str, device_id: int = 0):
        self._use_device = device_type

    def set_cpu_math_library_num_threads(self, n: int):
        self._flags["cpu_threads"] = int(n)

    # ------------------------------------------------- optimization toggles
    def switch_ir_optim(self, enabled: bool = True):
        self._flags["ir_optim"] = bool(enabled)

    def ir_optim(self) -> bool:
        return bool(self._flags["ir_optim"])

    def enable_memory_optim(self, enabled: bool = True):
        self._flags["memory_optim"] = bool(enabled)

    def enable_mkldnn(self):
        self._flags["mkldnn"] = True

    def disable_glog_info(self):
        self._flags["glog_info"] = False

    def switch_use_feed_fetch_ops(self, enabled: bool):
        pass  # feed/fetch are function args under XLA

    def switch_specify_input_names(self, enabled: bool = True):
        pass

    def summary(self) -> str:
        lines = [f"model: {self._model_path}",
                 f"device: {self._use_device}"]
        lines += [f"{k}: {v}" for k, v in sorted(self._flags.items())]
        return "\n".join(lines)


class Tensor:
    """Named input/output handle (paddle.inference.Tensor analog)."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = list(shape or [])
        self._dtype = np.dtype(dtype) if dtype is not None else None
        self._value: Optional[np.ndarray] = None

    def reshape(self, shape):
        self._shape = list(shape)

    def copy_from_cpu(self, data: np.ndarray):
        data = np.asarray(data)
        if self._dtype is not None and data.dtype != self._dtype:
            data = data.astype(self._dtype)
        self._value = data
        self._shape = list(data.shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"tensor {self.name!r} has no value; did "
                               "Predictor.run() succeed?")
        return np.asarray(self._value)

    def shape(self) -> List[int]:
        return list(self._shape)

    def type(self):
        return self._dtype


class Predictor:
    """Executes the exported module with named handles (AnalysisPredictor
    analog; XLA is the analysis + runtime)."""

    def __init__(self, config: Config):
        from ..static.io import LoadedInferenceModel

        path = config.model_dir()
        if path is None:
            raise ValueError("Config has no model path; use set_model()")
        out_dir = path if os.path.isdir(path) else str(path) + ".tpu_model"
        if not os.path.isdir(out_dir):
            raise FileNotFoundError(
                f"no inference artifact at {path!r} (expected a directory "
                "or a save_inference_model/jit.save prefix)")
        self._config = config
        prec = config._flags.get("precision", PrecisionType.Float32)
        prec_name = {PrecisionType.Float32: "float32",
                     PrecisionType.Half: "float16",
                     PrecisionType.Bfloat16: "bfloat16"}.get(prec,
                                                             "float32")
        self._model = LoadedInferenceModel(out_dir, precision=prec_name)
        self._inputs = {
            d["name"]: Tensor(d["name"], d.get("shape"), d.get("dtype"))
            for d in self._model.meta["feed"]
        }
        self._outputs = {
            d["name"]: Tensor(d["name"], d.get("shape"), d.get("dtype"))
            for d in self._model.meta["fetch"]
        }

    def get_input_names(self) -> List[str]:
        return list(self._model.feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._model.fetch_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either positional `inputs` (new paddle API) or values
        previously copy_from_cpu'd into the input handles."""
        if inputs is not None:
            for name, arr in zip(self._model.feed_names, inputs):
                self._inputs[name].copy_from_cpu(np.asarray(arr))
        feed = {}
        for name, handle in self._inputs.items():
            if handle._value is None:
                raise RuntimeError(
                    f"input {name!r} not set; call copy_from_cpu first")
            feed[name] = handle._value
        outs = self._model.run(feed)
        results = []
        for name, val in zip(self._model.fetch_names, outs):
            arr = np.asarray(val)
            self._outputs[name]._value = arr
            self._outputs[name]._shape = list(arr.shape)
            results.append(arr)
        return results

    def clone(self) -> "Predictor":
        """Share the loaded module; fresh handles (paddle clone contract —
        one predictor per thread/stream)."""
        clone = object.__new__(Predictor)
        clone._config = self._config
        clone._model = self._model
        clone._inputs = {
            n: Tensor(n, t._shape, t._dtype)
            for n, t in self._inputs.items()
        }
        clone._outputs = {
            n: Tensor(n, t._shape, t._dtype)
            for n, t in self._outputs.items()
        }
        return clone

    def try_shrink_memory(self):
        pass  # XLA owns buffers; nothing to shrink host-side


def _default_is_accel() -> bool:
    try:
        return jax.devices()[0].platform != "cpu"
    except RuntimeError:
        return False


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
