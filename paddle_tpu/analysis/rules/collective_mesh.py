"""COLLECTIVE-MESH — collectives must name a real mesh axis, every
``check_rep=False`` must say why, and ``ppermute`` rings must be sized
from the mesh.

Three contracts from the tensor-parallel work (PR 9 + ISSUE 18), all
about ``shard_map``:

  1. **Axis names.** ``jax.lax.psum(y, TP_AXIS)`` inside a
     shard_map-wrapped function runs on the axis the *wrap site's* mesh
     declares. A typo'd or stale axis name is the PR 5 swallowed-axis
     class all over again — it surfaces as a wrong *value*, not an
     error, once ``check_rep`` is off. The EQuARX/T3 roadmap items will
     multiply these sites, so the rule checks every collective whose
     axis operand *resolves to a string constant* (module-level
     constants like ``TP_AXIS = "tp"`` resolve, through from-imports
     too, via the project call graph's constant chase) against the
     union of axes declared by the module's resolvable ``Mesh(...)``
     constructors. Axis names that come in as function parameters
     (spmd_pipeline, moe) resolve to nothing and are skipped —
     conservative silence, not a guess.
  2. **check_rep=False.** Disabling replication checking is sometimes
     required (PR 9's wrappers return per-shard outputs) but never
     free: every ``check_rep=False`` must carry
     ``# noqa: COLLECTIVE-MESH — <reason>`` *with a reason* on its
     line. A reasonless noqa is itself the finding — the rule inspects
     the noqa's reason tail directly and bypasses the normal
     suppression path for this sub-check, so you cannot silence the
     demand for a reason with the bare marker it is demanding.
  3. **Split-collective rings (ISSUE 18).** The overlap work moves
     psum payloads over fixed-order ``lax.ppermute`` rings. A
     permutation table written as a *literal* — ``[(0, 1), (1, 0)]``,
     or a comprehension over ``range(2)`` — encodes ONE tp degree: at
     any other degree it silently drops shards (values wrong, no
     error, same class as a stale axis name). Tables must be built
     from the declared mesh axis size (``parallel.mesh.ring_perm``);
     a table that arrives as a variable or helper call resolves to
     nothing and is trusted — same conservative silence as the axis
     check.

Scoped to modules that call shard_map at all; modules with no
resolvable mesh axes get only the check_rep audit and the ppermute
ring check (the literal-table hazard needs no mesh resolution).
"""
import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..core import Finding, ParsedModule, Rule, dotted_chain

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "ppermute", "pshuffle", "psum_scatter", "all_to_all"}
_MESH_TAILS = {"Mesh", "make_mesh"}


def _axis_operands(call: ast.Call) -> List[ast.expr]:
    """The expressions that may carry the axis name for a collective."""
    out = [kw.value for kw in call.keywords if kw.arg == "axis_name"]
    if not out and len(call.args) >= 2:
        out = [call.args[1]]
    return out


def _perm_operand(call: ast.Call) -> Optional[ast.expr]:
    """The expression carrying ppermute's permutation table, if present."""
    for kw in call.keywords:
        if kw.arg == "perm":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _is_literal_perm(node: ast.expr) -> bool:
    """True when a perm table is hard-coded for one ring size.

    Fires on literal lists/tuples of pairs (``[(0, 1), (1, 0)]``) and on
    comprehensions whose only iterable is ``range(<constant>)`` — both
    pin the shard count at write time. Names and helper calls
    (``ring_perm(axis_size)``) are trusted: conservative silence.
    """
    if isinstance(node, (ast.List, ast.Tuple)):
        try:
            ast.literal_eval(node)
        except (ValueError, TypeError, SyntaxError, MemoryError):
            return False
        return True
    if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
        if len(node.generators) != 1:
            return False
        it = node.generators[0].iter
        return (isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range"
                and bool(it.args)
                and all(isinstance(a, ast.Constant) for a in it.args))
    return False


class CollectiveMeshRule(Rule):
    name = "COLLECTIVE-MESH"
    description = ("shard_map collectives whose axis name is not "
                   "declared by the module's mesh, and check_rep=False "
                   "without a reasoned noqa")

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        from ..callgraph import Project
        return self.project_check(module, Project.single(module))

    def _resolve_axes(self, node: ast.expr, module: ParsedModule,
                      project) -> Tuple[Set[str], bool]:
        """(axis names, fully_resolved) for one axis-names expression."""
        if isinstance(node, ast.Constant):
            if isinstance(node.value, str):
                return {node.value}, True
            return set(), False
        if isinstance(node, (ast.Tuple, ast.List)):
            axes: Set[str] = set()
            complete = True
            for elt in node.elts:
                sub, ok = self._resolve_axes(elt, module, project)
                axes |= sub
                complete = complete and ok
            return axes, complete
        if isinstance(node, ast.Name):
            val = project.callgraph.resolve_constant(module.path, node.id)
            if isinstance(val, str):
                return {val}, True
            if isinstance(val, (tuple, list)) \
                    and all(isinstance(v, str) for v in val):
                return set(val), True
        return set(), False

    def _mesh_axes(self, module: ParsedModule,
                   project) -> Optional[Set[str]]:
        """Union of axis names of every resolvable Mesh constructor in
        the module; None when nothing resolves (skip axis checks)."""
        axes: Set[str] = set()
        found = False
        for node in module.nodes():
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if chain is None or chain[-1] not in _MESH_TAILS:
                continue
            operand = None
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    operand = kw.value
            if operand is None and len(node.args) >= 2:
                operand = node.args[1]
            if operand is None:
                continue
            sub, ok = self._resolve_axes(operand, module, project)
            if ok and sub:
                axes |= sub
                found = True
        return axes if found else None

    def _is_shard_map(self, chain: Optional[List[str]], module,
                      project) -> bool:
        if not chain:
            return False
        if chain[-1] == "shard_map":
            return True
        if len(chain) == 1:
            # `from ... import shard_map as _shard_map`: chase the alias
            binding = project.callgraph.imports_of(module.path) \
                .get(chain[0])
            return (binding is not None and binding[0] == "sym"
                    and binding[2] == "shard_map")
        return False

    def project_check(self, module: ParsedModule,
                      project) -> Iterator[Finding]:
        # call sites and `shard_map as _alias` imports both carry the
        # literal text; modules without it cannot have a shard site
        if "shard_map" not in module.source:
            return
        shard_sites = [
            node for node in module.nodes()
            if isinstance(node, ast.Call)
            and self._is_shard_map(dotted_chain(node.func), module,
                                   project)]
        if not shard_sites:
            return

        hits: List[Tuple[int, str]] = []
        mesh_axes = self._mesh_axes(module, project)
        for node in module.nodes():
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if chain is None or chain[-1] not in _COLLECTIVES:
                continue
            if chain[0] not in module.jax_aliases \
                    and chain[0] != "lax":
                continue
            if mesh_axes is not None:
                for operand in _axis_operands(node):
                    axes, ok = self._resolve_axes(operand, module,
                                                  project)
                    if not ok:
                        continue  # parameter-carried axis: skip
                    for axis in sorted(axes - mesh_axes):
                        hits.append((node.lineno, (
                            f"collective `{'.'.join(chain)}` names axis "
                            f"'{axis}' but this module's shard_map "
                            f"meshes declare "
                            f"{sorted(mesh_axes)} — a stale axis name "
                            f"is the PR 5 swallowed-axis class: wrong "
                            f"values, no error, once check_rep is off")))
            if chain[-1] == "ppermute":
                perm = _perm_operand(node)
                if perm is not None and _is_literal_perm(perm):
                    hits.append((node.lineno, (
                        f"`{'.'.join(chain)}` builds its permutation "
                        f"table from a literal — a ring written for one "
                        f"tp degree silently drops shards at any other "
                        f"(wrong values, no error, the stale-axis class "
                        f"again); build it from the declared mesh axis "
                        f"size: `parallel.mesh.ring_perm(axis_size)`")))
        yield from self.findings(module, hits)

        # check_rep=False audit: bypasses inline suppression — a
        # reasonless `# noqa: COLLECTIVE-MESH` is exactly the bug
        occ: dict = {}
        for site in sorted(shard_sites, key=lambda n: (n.lineno,
                                                       n.col_offset)):
            for kw in site.keywords:
                if kw.arg != "check_rep":
                    continue
                if not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False):
                    continue
                line = kw.value.lineno
                reason = module.noqa_reason(line)
                if reason:
                    continue  # reasoned suppression: the contract held
                what = ("carries a reasonless `# noqa`" if reason == ""
                        else "has no `# noqa`")
                message = (
                    f"shard_map(check_rep=False) {what} — disabling "
                    f"replication checking hides axis mistakes (the "
                    f"PR 9 contract); justify it in place: "
                    f"`# noqa: COLLECTIVE-MESH — <why per-shard "
                    f"outputs are intended>`")
                snippet = module.line_text(line)
                k = (snippet, message)
                occ[k] = occ.get(k, -1) + 1
                yield Finding(rule=self.name, path=module.path,
                              line=line, message=message,
                              snippet=snippet, occurrence=occ[k])
