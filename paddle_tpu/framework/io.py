"""paddle.save / paddle.load — pickle protocol over state_dict.

Ref: python/paddle/framework/io.py (upstream layout, unverified — mount
empty). Tensors are serialized as numpy arrays (host pull) and rehydrated as
Tensors on load; nested dicts/lists/tuples and optimizer state round-trip.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_SENTINEL = "__paddle_tpu_tensor__"


def _pack(obj):
    if isinstance(obj, Tensor):
        return {_SENTINEL: True, "data": obj.numpy(),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):
            arr = obj["data"]
            if return_numpy:
                return arr
            t = Tensor(arr, stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", "")
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Serialize a Tensor / state_dict / nested structure to `path`."""
    if isinstance(path, (str, os.PathLike)):
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(_pack(obj), f, protocol=protocol)
    else:  # file-like object
        pickle.dump(_pack(obj), path, protocol=protocol)


def load(path, **configs):
    """Load what `save` wrote. `return_numpy=True` yields numpy arrays."""
    return_numpy = configs.get("return_numpy", False)
    if isinstance(path, (str, os.PathLike)):
        with open(path, "rb") as f:
            raw = pickle.load(f)
    else:
        raw = pickle.load(path)
    return _unpack(raw, return_numpy)
