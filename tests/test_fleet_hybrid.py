"""L6 tests: TP layers, PP 1F1B, GroupSharded, SP, ring/Ulysses attention,
MoE, recompute — each checked sharded-vs-replica allclose (SURVEY §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:                                    # jax >= 0.5 exports it at top level
    from jax import shard_map
except ImportError:                     # jax 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core import tape as tape_mod
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.functional import call_functional, extract_state
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, GroupShardedStage3, LayerDesc, PipelineLayer,
    PipelineParallel, RowParallelLinear, VocabParallelEmbedding,
    get_rng_state_tracker, group_sharded_parallel, mp_shardings,
    ring_flash_attention, ulysses_attention,
)
from paddle_tpu.distributed.fleet import (
    CommunicateTopology, DistributedStrategy, HybridCommunicateGroup, fleet,
    recompute,
)


def _mp_mesh(n=4):
    return Mesh(np.asarray(jax.devices()[:n]), ("mp",))


# --------------------------------------------------------------- TP layers
def test_tp_layers_match_dense():
    """Column->Row parallel MLP under mp=4 shardings == dense replica."""
    paddle.seed(0)

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnParallelLinear(16, 32, gather_output=False)
            self.fc2 = RowParallelLinear(32, 8, input_is_parallel=True)

        def forward(self, x):
            return self.fc2(nn.functional.relu(self.fc1(x)))

    net = MLP()
    x = np.random.RandomState(0).rand(4, 16).astype("float32")

    # dense run (eager, no mesh)
    net.eval()
    y_dense = net(paddle.to_tensor(x)).numpy()

    # sharded run: params placed per dist_spec on an mp mesh
    mesh = _mp_mesh(4)
    params, buffers = extract_state(net)
    shardings = mp_shardings(net, mesh)
    placed = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}

    def fwd(p, b, xx):
        out, _ = call_functional(net, p, b, (xx,), training=False)
        return out

    y_sharded = jax.jit(fwd, in_shardings=(shardings, None, None))(
        placed, buffers, jnp.asarray(x))
    np.testing.assert_allclose(y_dense, np.asarray(y_sharded), rtol=2e-5,
                               atol=1e-6)
    # the weight really is sharded over mp
    assert placed["fc1.weight"].sharding.spec == P(None, "mp")


def test_vocab_parallel_embedding():
    paddle.seed(1)
    emb = VocabParallelEmbedding(64, 8)
    ids = np.random.RandomState(1).randint(0, 64, (2, 10))
    y_dense = emb(paddle.to_tensor(ids)).numpy()

    mesh = _mp_mesh(4)
    params, buffers = extract_state(emb)
    sh = mp_shardings(emb, mesh)
    placed = {k: jax.device_put(v, sh[k]) for k, v in params.items()}

    def fwd(p, b, xx):
        out, _ = call_functional(emb, p, b, (xx,), training=False)
        return out

    y_sharded = jax.jit(fwd, in_shardings=(sh, None, None))(
        placed, buffers, jnp.asarray(ids))
    np.testing.assert_allclose(y_dense, np.asarray(y_sharded), rtol=1e-6)
    assert placed["weight"].sharding.spec == P("mp", None)


def test_rng_states_tracker():
    tr = get_rng_state_tracker()
    paddle.seed(5)
    with tr.rng_state("model-parallel-rng"):
        a = paddle.rand([4])
    with tr.rng_state("model-parallel-rng"):
        b = paddle.rand([4])
    # separate draws from the same stream differ
    assert not np.allclose(a.numpy(), b.numpy())
    # the default generator was untouched by the tracker context
    paddle.seed(5)
    c = paddle.rand([4])
    paddle.seed(5)
    d = paddle.rand([4])
    np.testing.assert_allclose(c.numpy(), d.numpy())


# ---------------------------------------------------------------------- PP
def _pp_engine_and_replica(num_stages=2, micro=4):
    paddle.seed(7)
    layers = [LayerDesc(nn.Linear, 8, 16), LayerDesc(nn.ReLU),
              LayerDesc(nn.Linear, 16, 16), LayerDesc(nn.ReLU),
              LayerDesc(nn.Linear, 16, 4)]
    loss_fn = nn.CrossEntropyLoss()
    pipe = PipelineLayer(layers, num_stages=num_stages, loss_fn=loss_fn)

    # replica: same weights flattened into one sequential
    replica = nn.Sequential(*pipe._all_layers)
    return pipe, replica, loss_fn


def test_pipeline_parallel_matches_replica():
    topo = CommunicateTopology(["pp", "dp", "sharding", "sep", "mp"],
                               [2, 1, 1, 1, 1])
    hcg = HybridCommunicateGroup(topo, global_rank=0)
    pipe, replica, loss_fn = _pp_engine_and_replica(2)
    rng = np.random.RandomState(3)
    x = rng.rand(8, 8).astype("float32")
    y = rng.randint(0, 4, (8, 1))

    # replica loss with the SAME weights (shared layer objects) — must run
    # BEFORE engine construction places stage params on their submeshes
    with tape_mod.no_grad():
        ref_loss = float(loss_fn(replica(paddle.to_tensor(x)),
                                 paddle.to_tensor(y)).numpy())

    strategy = DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 4}
    engine = PipelineParallel(pipe, hcg, strategy)

    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=pipe.parameters())

    loss = engine.train_batch(
        (paddle.to_tensor(x), paddle.to_tensor(y)), opt)
    # micro-batched mean loss == full-batch loss for mean-reduced CE
    assert abs(float(loss.numpy()) - ref_loss) < 1e-5

    # params actually moved
    l2 = engine.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)
    assert float(l2.numpy()) < float(loss.numpy())


def test_pipeline_vs_single_process_sgd():
    """Two SGD steps through the PP engine == two eager full-model steps."""
    paddle.seed(11)
    layers_a = [nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3)]
    paddle.seed(11)
    layers_b = [nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3)]
    for la, lb in zip(layers_a, layers_b):
        for pa, pb in zip(la.parameters(), lb.parameters()):
            np.testing.assert_allclose(pa.numpy(), pb.numpy())

    loss_fn = nn.CrossEntropyLoss()
    pipe = PipelineLayer([LayerDesc(l) for l in layers_a], num_stages=2,
                         loss_fn=loss_fn)
    topo = CommunicateTopology(["pp", "dp", "sharding", "sep", "mp"],
                               [2, 1, 1, 1, 1])
    hcg = HybridCommunicateGroup(topo, global_rank=0)
    st = DistributedStrategy()
    st.pipeline_configs = {"accumulate_steps": 2}
    engine = PipelineParallel(pipe, hcg, st)
    opt_a = paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=pipe.parameters())

    seq = nn.Sequential(*layers_b)
    opt_b = paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=seq.parameters())

    rng = np.random.RandomState(5)
    x = rng.rand(4, 6).astype("float32")
    y = rng.randint(0, 3, (4, 1))

    for _ in range(2):
        engine.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt_a)
        out = seq(paddle.to_tensor(x))
        loss = loss_fn(out, paddle.to_tensor(y))
        loss.backward()
        opt_b.step()
        opt_b.clear_grad()

    for pa, pb in zip(pipe.parameters(), seq.parameters()):
        np.testing.assert_allclose(pa.numpy(), pb.numpy(), rtol=2e-4,
                                   atol=1e-5)


def test_interleaved_vpp_matches_single_process():
    """Interleaved schedule (num_virtual_pipeline_stages=2): S=2 stages x
    V=2 chunks, chunk c on stage c%S, numerics == eager full model."""
    def build():
        paddle.seed(13)
        return [nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 12), nn.ReLU(),
                nn.Linear(12, 3)]

    layers_a, layers_b = build(), build()
    loss_fn = nn.CrossEntropyLoss()
    pipe = PipelineLayer([LayerDesc(l) for l in layers_a], num_stages=2,
                         loss_fn=loss_fn, num_virtual_pipeline_stages=2)
    assert pipe.num_chunks == 4
    # round-robin chunk placement (Megatron interleaved layout)
    assert [pipe.chunk_to_stage(c) for c in range(4)] == [0, 1, 0, 1]
    # physical stage 0 holds chunks 0 and 2
    assert pipe.stage_layers[0] == pipe.chunk_layers[0] + pipe.chunk_layers[2]

    topo = CommunicateTopology(["pp", "dp", "sharding", "sep", "mp"],
                               [2, 1, 1, 1, 1])
    hcg = HybridCommunicateGroup(topo, global_rank=0)
    st = DistributedStrategy()
    st.pipeline_configs = {"accumulate_steps": 2}
    engine = PipelineParallel(pipe, hcg, st)
    opt_a = paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=pipe.parameters())

    seq = nn.Sequential(*layers_b)
    opt_b = paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=seq.parameters())

    rng = np.random.RandomState(6)
    x = rng.rand(4, 6).astype("float32")
    y = rng.randint(0, 3, (4, 1))

    for _ in range(2):
        engine.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt_a)
        out = seq(paddle.to_tensor(x))
        loss = loss_fn(out, paddle.to_tensor(y))
        loss.backward()
        opt_b.step()
        opt_b.clear_grad()

    for pa, pb in zip(pipe.parameters(), seq.parameters()):
        np.testing.assert_allclose(pa.numpy(), pb.numpy(), rtol=2e-4,
                                   atol=1e-5)


def test_vpp_too_few_layers_raises():
    with pytest.raises(ValueError, match="virtual"):
        PipelineLayer([LayerDesc(nn.Linear, 4, 4)] * 3, num_stages=2,
                      num_virtual_pipeline_stages=2)


# ------------------------------------------------------------ GroupSharded
def test_group_sharded_stage3_matches_replica():
    def build():
        paddle.seed(21)
        return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8),
                             nn.ReLU(), nn.Linear(8, 4))

    rng = np.random.RandomState(2)
    x = rng.rand(32, 16).astype("float32")
    y = rng.randint(0, 4, (32, 1))

    net1 = build()
    m1 = paddle.Model(net1)
    m1.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=net1.parameters()),
               nn.CrossEntropyLoss())
    losses1 = [float(m1.train_batch([x], [y])[0]) for _ in range(3)]

    net2 = build()
    opt2 = paddle.optimizer.Adam(learning_rate=0.01,
                                 parameters=net2.parameters())
    wrapped, opt2w = group_sharded_parallel(net2, opt2, level="p_g_os")
    m2 = paddle.Model(wrapped)
    m2.prepare(opt2w._optim, nn.CrossEntropyLoss())
    losses2 = [float(m2.train_batch([x], [y])[0]) for _ in range(3)]

    np.testing.assert_allclose(losses1, losses2, rtol=3e-5)
    # stage-3: divisible dim-0 params really sharded
    w32 = dict(wrapped.named_parameters())["2.weight"]
    assert w32._data.sharding.spec in (P("sharding"), P(("sharding",)))


def test_group_sharded_levels():
    net = nn.Linear(8, 8)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    for level, stage in (("os", 1), ("os_g", 2), ("p_g_os", 3)):
        w, o = group_sharded_parallel(nn.Linear(8, 8),
                                      paddle.optimizer.Adam(
                                          parameters=net.parameters()),
                                      level=level)
        assert w.stage == stage


# ------------------------------------------------- ring/Ulysses attention
def _attn_inputs(b=2, h=4, s=32, d=8, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(b, h, s, d).astype("float32")
    k = rng.randn(b, h, s, d).astype("float32")
    v = rng.randn(b, h, s, d).astype("float32")
    return q, k, v


def _dense_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = q.shape[2]
        mask = np.tril(np.ones((s, s), dtype=bool))
        scores = np.where(mask[None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    q, k, v = _attn_inputs()
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("sep",))

    def f(q, k, v):
        return ring_flash_attention(q, k, v, axis_name="sep", causal=causal)

    out = shard_map(f, mesh=mesh,
                    in_specs=(P(None, None, "sep", None),) * 3,
                    out_specs=P(None, None, "sep", None))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = _attn_inputs(h=8)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("sep",))

    def f(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sep", causal=causal)

    out = shard_map(f, mesh=mesh,
                    in_specs=(P(None, None, "sep", None),) * 3,
                    out_specs=P(None, None, "sep", None))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------- MoE
def test_moe_layer_routes_and_learns():
    from paddle_tpu.incubate.distributed.models.moe import (
        GShardGate, MoELayer,
    )

    paddle.seed(3)
    d = 16
    experts = [nn.Linear(d, d) for _ in range(4)]
    gate = GShardGate(d, num_expert=4, topk=2)
    moe = MoELayer(d_model=d, experts=experts, gate=gate)
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 8, d)
                         .astype("float32"))
    out = moe(x)
    assert out.shape == [2, 8, d]
    assert moe.aux_loss is not None and float(moe.aux_loss.numpy()) > 0
    # with generous capacity every token is routed: combine weights ~ 1
    out2 = moe(x)
    np.testing.assert_allclose(out.numpy(), out2.numpy())  # deterministic


def test_moe_expert_parallel_alltoall_matches_dense():
    """EP dispatch over the 8-device ep axis (lax.all_to_all inside
    shard_map) == the dense einsum path, forward AND grads (no drops)."""
    from paddle_tpu.incubate.distributed.models.moe import (
        GShardGate, MoELayer,
    )

    paddle.seed(17)
    d, E = 16, 8
    experts = [nn.Linear(d, d) for _ in range(E)]
    # capacity_factor 8 → no token ever dropped, so both paths agree exactly
    gate = GShardGate(d, num_expert=E, topk=2, capacity=(8.0, 16.0))
    moe = MoELayer(d_model=d, experts=experts, gate=gate)
    x_np = np.random.RandomState(1).rand(2, 16, d).astype("float32")

    x1 = paddle.to_tensor(x_np)
    x1.stop_gradient = False
    dense = moe(x1)
    dense.sum().backward()
    g_dense = {n: p.grad.numpy().copy()
               for n, p in moe.named_parameters() if p.grad is not None}
    for p in moe.parameters():
        p.clear_gradient()

    mesh = Mesh(np.array(jax.devices()), ("ep",))
    x2 = paddle.to_tensor(x_np)
    x2.stop_gradient = False
    ep = moe.expert_parallel_forward(x2, mesh, ep_axis="ep")
    np.testing.assert_allclose(ep.numpy(), dense.numpy(), rtol=2e-5,
                               atol=2e-6)
    ep.sum().backward()
    g_ep = {n: p.grad.numpy().copy()
            for n, p in moe.named_parameters() if p.grad is not None}
    assert set(g_ep) == set(g_dense)
    for n in g_dense:
        np.testing.assert_allclose(g_ep[n], g_dense[n], rtol=2e-4,
                                   atol=2e-5, err_msg=n)


# ----------------------------------------------------------------- recompute
def test_recompute_matches_plain():
    paddle.seed(9)
    net = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 8))
    x = paddle.to_tensor(np.random.RandomState(4).rand(4, 8)
                         .astype("float32"), stop_gradient=False)

    y1 = net(x)
    loss1 = y1.sum()
    loss1.backward()
    g1 = {n: p.grad.numpy().copy() for n, p in net.named_parameters()}
    for p in net.parameters():
        p.clear_gradient()

    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    y2 = recompute(net, x2)
    loss2 = y2.sum()
    loss2.backward()
    g2 = {n: p.grad.numpy() for n, p in net.named_parameters()}

    np.testing.assert_allclose(y1.numpy(), y2.numpy(), rtol=1e-6)
    for n in g1:
        np.testing.assert_allclose(g1[n], g2[n], rtol=1e-5, atol=1e-7)


# ----------------------------------------------------- sequence parallel
def test_sequence_parallel_linears_match_dense():
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear,
    )

    paddle.seed(13)
    col = ColumnSequenceParallelLinear(8, 16, gather_output=False)
    row = RowSequenceParallelLinear(16, 8, input_is_parallel=True)
    x = paddle.to_tensor(np.random.RandomState(6).rand(2, 12, 8)
                         .astype("float32"))
    # eager (no mesh): pure dense behavior
    y = row(col(x))
    ref = x.matmul(col.weight).matmul(row.weight) + row.bias
    np.testing.assert_allclose(y.numpy(), ref.numpy(), rtol=1e-5)


# ------------------------------------------- hybrid global-norm grad clip
def test_hybrid_clip_grad_tp_matches_dense():
    """ClipGradByGlobalNorm under TP sharding == dense replica (round-2:
    HybridParallelOptimizer owns the cross-mesh clip, previously untested)."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        HybridParallelOptimizer,
    )

    def build():
        paddle.seed(11)

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = ColumnParallelLinear(16, 32, gather_output=False)
                self.fc2 = RowParallelLinear(32, 8, input_is_parallel=True)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        return MLP()

    rng = np.random.RandomState(3)
    x = rng.rand(8, 16).astype("float32") * 4  # big grads so the clip bites
    y = rng.rand(8, 8).astype("float32")

    def train(net, opt, sharded):
        params, buffers = extract_state(net)
        if sharded:
            sh = mp_shardings(net, _mp_mesh(4))
            params = {k: jax.device_put(v, sh[k])
                      for k, v in params.items()}
        for name, p in net.named_parameters():
            p._data = params[name]
        for _ in range(3):
            out = net(paddle.to_tensor(x))
            loss = ((out - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return {k: np.asarray(v.numpy())
                for k, v in net.named_parameters()}

    net1 = build()
    opt1 = paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net1.parameters(),
        grad_clip=paddle.nn.ClipGradByGlobalNorm(0.05))
    dense = train(net1, opt1, sharded=False)

    net2 = build()
    opt2 = HybridParallelOptimizer(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net2.parameters(),
        grad_clip=paddle.nn.ClipGradByGlobalNorm(0.05)))
    sharded = train(net2, opt2, sharded=True)

    for k in dense:
        np.testing.assert_allclose(dense[k], sharded[k], rtol=2e-4,
                                   atol=1e-6, err_msg=k)


def test_hybrid_clip_psum_inside_shard_map():
    """Inside shard_map the clip psums distributed-param norms over mp and
    counts replicated params once."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        HybridParallelClipGrad,
    )
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm

    mesh = _mp_mesh(4)
    clip = HybridParallelClipGrad(ClipGradByGlobalNorm(1.0))

    # distributed param shard: each rank holds [1.0], global vector of 4
    # replicated param: [2.0] on every rank
    dist_shard = jnp.ones((4,))          # sharded dim-0 over mp
    repl = jnp.full((1,), 2.0)

    def body(d, r):
        class P_:
            need_clip = True
            is_distributed = True
            stop_gradient = False

        class R_:
            need_clip = True
            is_distributed = False
            stop_gradient = False

        from paddle_tpu.core.tensor import Tensor as T

        out = clip([(P_(), T(d)), (R_(), T(r))])
        return out[0][1]._data, out[1][1]._data

    d_clipped, r_clipped = shard_map(
        body, mesh=mesh, in_specs=(P("mp"), P(None)),
        out_specs=(P("mp"), P(None)))(dist_shard, repl)
    # global norm = sqrt(4*1 + 4) = sqrt(8); factor = 1/sqrt(8)
    expect = 1.0 / np.sqrt(8.0)
    np.testing.assert_allclose(np.asarray(d_clipped),
                               np.full(4, expect), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(r_clipped),
                               np.full(1, 2 * expect), rtol=1e-5)


def test_recompute_accepts_none_args_and_matches():
    """r5 regression: a literal None argument (attention_mask=None) used to
    collide with recompute's tensor-slot sentinel and crash; and the
    rematerialized backward must reproduce the exact losses (dropout keys
    ride the functional trace stream)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.jit.functional import extract_state
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining
    import bench

    def run(recompute):
        paddle.seed(3)
        cfg = ErnieConfig.tiny()
        cfg.recompute = recompute
        model = ErnieForPretraining(cfg)
        model.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        params, buffers = extract_state(model)
        opt_state = opt.functional_state(params)
        step = jax.jit(bench.make_train_step(model, opt))
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 32)))
        paddle.seed(7)
        from paddle_tpu.core.rng import default_generator

        losses = []
        for t in range(1, 3):
            key = default_generator().next_key()
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, jnp.float32(1e-3),
                jnp.int32(t), key, ids, ids)
            losses.append(float(np.asarray(loss)))
        return losses

    np.testing.assert_allclose(run(False), run(True), rtol=1e-5)
