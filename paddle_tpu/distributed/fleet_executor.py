"""FleetExecutor — carrier/interceptor async runtime (ref: paddle/fluid/
distributed/fleet_executor/{fleet_executor,carrier,interceptor,task_node,
message_bus}.*, upstream layout, unverified — mount empty).

Upstream's C++ FleetExecutor runs program *sections* as a DAG of TaskNodes:
each node is owned by an Interceptor object (Source / Compute / Amplifier /
Sink behaviors), Interceptors exchange InterceptorMessages through their
rank's Carrier, Carriers route cross-rank traffic over a message bus, and
bounded buffers give 1F1B-style credit flow control. The TPU-native runtime
keeps that exact execution model in-process:

* one Carrier per rank, owning the worker threads of its rank's
  interceptors (multi-program coordination = multiple carriers driven by
  one executor);
* InterceptorMessage(src, dst, micro_step, payload) over bounded channels —
  a full channel blocks the producer (credit-based backpressure);
* interceptor BEHAVIOR by node_type: Source emits feeds, Compute runs the
  node's callable/program section, Amplifier re-emits each upstream message
  `amplify` times (the upstream amplifier interceptor that multiplies
  micro-batch traffic for 1F1B), Sink collects results;
* the heavy compute inside a node stays a jitted callable or a static
  Program segment — XLA owns on-device scheduling.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["TaskNode", "FleetExecutor", "Carrier", "Interceptor",
           "InterceptorMessage"]


class _Stopped(Exception):
    """Internal: a sibling failed; unwind this worker quietly."""


class InterceptorMessage:
    """The upstream InterceptorMessage proto analog."""

    __slots__ = ("src", "dst", "micro_step", "payload")

    def __init__(self, src: int, dst: int, micro_step: int, payload):
        self.src = src
        self.dst = dst
        self.micro_step = micro_step
        self.payload = payload

    def __repr__(self):
        return (f"InterceptorMessage({self.src}->{self.dst}, "
                f"step={self.micro_step})")


class TaskNode:
    """One section of work, run `max_run_times` micro-steps."""

    _counter = [0]

    def __init__(self, rank: int = 0, node_type: str = "Compute",
                 task_id: Optional[int] = None,
                 program=None, run_fn: Optional[Callable] = None,
                 max_run_times: int = 1, amplify: int = 1):
        if task_id is None:
            task_id = TaskNode._counter[0]
            TaskNode._counter[0] += 1
        self.task_id = task_id
        self.rank = rank
        # Source/Sink/Amplifier get special interceptor behavior; any other
        # label (upstream also has Feed/Fetch/Cond roles) runs as Compute
        self.node_type = node_type
        self.program = program
        self.run_fn = run_fn
        self.max_run_times = max_run_times
        self.amplify = amplify          # Amplifier: out msgs per in msg
        self.downstream: Dict[int, int] = {}   # task_id -> buffer_size
        self.upstream: Dict[int, int] = {}

    def add_downstream_task(self, task_id: int, buffer_size: int = 2):
        self.downstream[task_id] = buffer_size
        return self

    def add_upstream_task(self, task_id: int, buffer_size: int = 2):
        self.upstream[task_id] = buffer_size
        return self

    def __repr__(self):
        return (f"TaskNode(id={self.task_id}, type={self.node_type}, "
                f"rank={self.rank}, up={sorted(self.upstream)}, "
                f"down={sorted(self.downstream)})")


class Interceptor:
    """Owns one TaskNode: receives messages for it, runs its behavior,
    emits messages downstream through the carrier."""

    def __init__(self, node: TaskNode, carrier: "Carrier", run):
        self.node = node
        self.carrier = carrier
        self._run = run           # shared run-state (channels, results, ...)

    # -- channel helpers (credit-based: bounded queues block) -------------
    def _recv(self, src: int, q):
        run = self._run
        while True:
            if run.stop.is_set():
                raise _Stopped()
            try:
                msg = q.get(timeout=0.05)
                assert msg.dst == self.node.task_id
                return msg
            except queue.Empty:
                if time.monotonic() > run.deadline:
                    raise TimeoutError(
                        f"interceptor {self.node.task_id} timed out waiting "
                        f"on {src}")

    def _send(self, dst: int, micro_step: int, payload):
        run = self._run
        q = run.channels[(self.node.task_id, dst)]
        msg = InterceptorMessage(self.node.task_id, dst, micro_step, payload)
        while True:
            if run.stop.is_set():
                raise _Stopped()
            try:
                return q.put(msg, timeout=0.05)
            except queue.Full:
                if time.monotonic() > run.deadline:
                    raise TimeoutError(
                        f"interceptor {self.node.task_id} -> {dst} "
                        "backpressured past the deadline")

    # -- behaviors --------------------------------------------------------
    def run_loop(self):
        node = self.node
        run = self._run
        try:
            if node.node_type == "Amplifier":
                self._amplifier_loop()
                return
            for step in range(node.max_run_times):
                if run.stop.is_set():
                    return
                inputs = {}
                for src in node.upstream:
                    msg = self._recv(src, run.channels[(src, node.task_id)])
                    inputs[src] = msg.payload
                if node.task_id in run.feed:
                    inputs["feed"] = run.feed[node.task_id][step]
                out = self._compute(step, inputs)
                run.results[node.task_id].append(out)
                for dst in node.downstream:
                    self._send(dst, step, out)
        except _Stopped:
            return
        except BaseException as e:   # surface to the caller, stop the DAG
            run.errors.append(e)
            run.stop.set()

    def _amplifier_loop(self):
        """Upstream's amplifier interceptor: every upstream message is
        re-emitted `amplify` times (micro-batch fan-out for 1F1B traffic
        shaping); runs until its upstreams complete."""
        node = self.node
        run = self._run
        out_step = 0
        for step in range(node.max_run_times):
            if run.stop.is_set():
                return
            for src in node.upstream:
                msg = self._recv(src, run.channels[(src, node.task_id)])
                for _ in range(max(1, node.amplify)):
                    run.results[node.task_id].append(msg.payload)
                    for dst in node.downstream:
                        self._send(dst, out_step, msg.payload)
                    out_step += 1

    def _compute(self, step: int, inputs):
        node = self.node
        if node.run_fn is not None:
            return node.run_fn(step, inputs)
        if node.program is not None:
            from ..static.executor import Executor

            # program sections take dict feeds: the explicit feed plus
            # every upstream output that is a dict (fetches-by-name)
            section_feed = dict(inputs.get("feed") or {})
            for src in node.upstream:
                if isinstance(inputs[src], dict):
                    section_feed.update(inputs[src])
            return Executor().run(node.program, feed=section_feed)
        # Source/Sink without a callable: pass the feed / inputs through
        if node.node_type == "Source":
            return inputs.get("feed")
        if len(inputs) == 1:
            return next(iter(inputs.values()))
        return inputs


class Carrier:
    """One rank's interceptor host: creates the rank's interceptors and
    drives each on its own worker thread (upstream: carrier.cc). Cross-rank
    messages ride the shared channel table — the in-process message bus."""

    def __init__(self, rank: int):
        self.rank = rank
        self.interceptors: Dict[int, Interceptor] = {}
        self._threads: List[threading.Thread] = []

    def create_interceptor(self, node: TaskNode, run) -> Interceptor:
        ic = Interceptor(node, self, run)
        self.interceptors[node.task_id] = ic
        return ic

    def start(self):
        self._threads = [
            threading.Thread(target=ic.run_loop, daemon=True,
                             name=f"carrier{self.rank}-ic{tid}")
            for tid, ic in self.interceptors.items()]
        for t in self._threads:
            t.start()

    def join(self, timeout: float):
        for t in self._threads:
            t.join(timeout=timeout)

    def alive(self) -> bool:
        return any(t.is_alive() for t in self._threads)


class _RunState:
    """Shared per-run state: the message bus (channel table), results,
    stop flag, deadline."""

    def __init__(self, channels, feed, results, deadline):
        self.channels = channels
        self.feed = feed
        self.results = results
        self.errors: List[BaseException] = []
        self.stop = threading.Event()
        self.deadline = deadline


class FleetExecutor:
    """Execute a TaskNode DAG through per-rank Carriers of Interceptors."""

    def __init__(self, task_nodes: Optional[List[TaskNode]] = None):
        self._nodes: Dict[int, TaskNode] = {}
        self.carriers: Dict[int, Carrier] = {}
        if task_nodes:
            self.init(task_nodes)

    def init(self, task_nodes: List[TaskNode]):
        self._nodes = {n.task_id: n for n in task_nodes}
        # symmetrize edges so users may declare only one direction
        for n in task_nodes:
            for tid, buf in n.downstream.items():
                self._nodes[tid].upstream.setdefault(n.task_id, buf)
            for tid, buf in n.upstream.items():
                self._nodes[tid].downstream.setdefault(n.task_id, buf)
        self._validate_acyclic()
        self.carriers = {}
        for n in task_nodes:
            self.carriers.setdefault(n.rank, Carrier(n.rank))
        return self

    def _validate_acyclic(self):
        state: Dict[int, int] = {}

        def visit(tid):
            if state.get(tid) == 1:
                raise ValueError("TaskNode graph has a cycle")
            if state.get(tid) == 2:
                return
            state[tid] = 1
            for d in self._nodes[tid].downstream:
                visit(d)
            state[tid] = 2

        for tid in self._nodes:
            visit(tid)

    def run(self, feed=None, fetch_task_ids: Optional[List[int]] = None,
            timeout: float = 300.0):
        """Drive every interceptor for its node's micro-steps.

        `feed`: optional {task_id: [per-step inputs]} for source nodes.
        Returns {task_id: [per-step outputs]} for `fetch_task_ids` (default:
        all sink nodes).
        """
        feed = feed or {}
        channels: Dict[tuple, queue.Queue] = {}
        for n in self._nodes.values():
            for dst, buf in n.downstream.items():
                channels[(n.task_id, dst)] = queue.Queue(maxsize=max(1, buf))

        sinks = [tid for tid, n in self._nodes.items() if not n.downstream]
        fetch_ids = list(fetch_task_ids or sinks)
        results: Dict[int, List] = {tid: [] for tid in self._nodes}
        run = _RunState(channels, feed, results,
                        time.monotonic() + timeout)

        for n in self._nodes.values():
            self.carriers[n.rank].create_interceptor(n, run)
        for c in self.carriers.values():
            c.start()
        for c in self.carriers.values():
            c.join(timeout=timeout)
        try:
            if run.errors:
                raise run.errors[0]
            if any(c.alive() for c in self.carriers.values()):
                run.stop.set()
                raise TimeoutError("FleetExecutor DAG did not complete")
            return {tid: results[tid] for tid in fetch_ids}
        finally:
            # drop per-run interceptors: they hold the _RunState (results,
            # feeds, channel payloads) and would pin a finished run's data
            # for the executor's lifetime
            for c in self.carriers.values():
                c.interceptors.clear()
                c._threads = []
