"""Driver benchmark: ERNIE-1.0 pretrain tokens/sec/chip (BASELINE.json metric).

Runs the full framework train step (hapi-style jitted functional step: forward
+ MLM loss + jax.grad + Adam, bf16 autocast) on the available accelerator and
prints ONE JSON line. vs_baseline is measured MFU / 0.40 — the fraction of
the north-star target (no published reference numbers exist; see BASELINE.md).

Robustness contract (round-1 postmortem: the axon TPU backend died mid-run
with rc=1 and the round had no perf number at all):
- the measurement runs in a CHILD process; this supervisor retries a fresh
  child on failure, then falls back to CPU, and ALWAYS emits a JSON line
  (with an "error" field when degraded) and exits 0;
- the child smoke-tests the backend with a tiny compile before the big one,
  prints per-phase progress to stderr, and has an internal watchdog that
  emits an error JSON and hard-exits rather than hanging.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

METRIC = "ernie1.0_pretrain_tokens_per_sec_per_chip"
UNIT = "tokens/s/chip"

PEAK_BF16_FLOPS = {
    # device_kind substring -> peak bf16 FLOP/s per chip
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _error_json(err: str) -> dict:
    return {"metric": METRIC, "value": 0.0, "unit": UNIT,
            "vs_baseline": 0.0, "error": err[-2000:]}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in PEAK_BF16_FLOPS.items():
        if sub in kind:
            return peak
    return None


# --------------------------------------------------------------------------
# child: the actual measurement
# --------------------------------------------------------------------------

def _start_watchdog(seconds: float) -> None:
    """Emit an error JSON and hard-exit if the child wedges (e.g. a PJRT
    transport hang where block_until_ready never returns)."""
    import threading

    def fire():
        _log(f"watchdog fired after {seconds}s — backend wedged")
        _emit(_error_json(f"watchdog: child exceeded {seconds}s"))
        os._exit(3)  # nonzero: supervisor treats the run as failed

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()


def bench_child() -> None:
    _start_watchdog(float(os.environ.get("BENCH_WATCHDOG_SECS", "720")))
    _log("phase=init: importing jax")
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # the axon sitecustomize pins jax_platforms at interpreter start;
        # env vars alone cannot undo it — config.update before backend init
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.core import tape as tape_mod
    from paddle_tpu.core.rng import default_generator
    from paddle_tpu.jit.functional import call_functional, extract_state
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    _log(f"phase=init: backend up, device={getattr(dev, 'device_kind', dev.platform)}")

    # tiny compile first: verifies the backend can compile+run at all before
    # we sink 20-40s into the big StableHLO program
    x = jnp.ones((128, 128), jnp.bfloat16)
    y = jax.jit(lambda a: (a @ a).sum())(x)
    float(np.asarray(y))
    _log("phase=smoke: tiny matmul compiled and ran")

    if on_tpu:
        cfg = ErnieConfig.ernie_base()  # ERNIE-1.0: L12 H768 A12 vocab 18k
        batch, seq, steps, warmup = 32, 512, 20, 3
    else:  # CPU smoke fallback; driver runs on TPU
        cfg = ErnieConfig.tiny()
        batch, seq, steps, warmup = 8, 128, 5, 1
    # sweep hooks (used by the perf-tuning harness; driver runs defaults)
    batch = int(os.environ.get("BENCH_BATCH", batch))
    seq = int(os.environ.get("BENCH_SEQ", seq))
    steps = int(os.environ.get("BENCH_STEPS", steps))

    model = ErnieForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                parameters=model.parameters())

    def make_state():
        p, b = extract_state(model)
        return p, b, opt.functional_state(p)

    params, buffers, opt_state = make_state()

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    _log(f"phase=build: model built, batch={batch} seq={seq}")

    def train_step(params, buffers, opt_state, lr, t, key, ids, labels):
        def loss_of(p):
            with amp.auto_cast(level="O1", dtype="bfloat16"):
                (logits, nsp), new_buffers = call_functional(
                    model, p, buffers, (ids,), rng_key=key, training=True)
            with tape_mod.no_grad():
                loss = model.loss(paddle.Tensor(logits), paddle.Tensor(nsp),
                                  paddle.Tensor(labels))
            return loss._data, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.functional_step(params, grads, opt_state,
                                                  lr, t)
        return loss, new_params, new_buffers, new_opt

    jitted = jax.jit(train_step, donate_argnums=(0, 1, 2))
    lr = jnp.float32(1e-4)
    step_no = [0]

    def run_steps(n, ids, labels, sync_each=False):
        nonlocal params, buffers, opt_state
        loss = None
        t0 = time.perf_counter()
        for _ in range(n):
            step_no[0] += 1
            key = default_generator().next_key()
            loss, params, buffers, opt_state = jitted(
                params, buffers, opt_state, lr, jnp.int32(step_no[0]), key,
                ids, labels)
            if sync_each:
                float(np.asarray(loss))
        # sync via a device->host value fetch: the final loss depends on
        # every queued step, and on some PJRT transports (axon relay)
        # block_until_ready returns before queued work drains
        final = float(np.asarray(loss))
        return time.perf_counter() - t0, final

    def data_for(b):
        return (jnp.asarray(rng.randint(0, cfg.vocab_size, (b, seq))),
                jnp.asarray(rng.randint(0, cfg.vocab_size, (b, seq))))

    # batch micro-sweep (TPU only, no explicit BENCH_BATCH override): the
    # round-2 bench pinned batch=32 without a sweep (verdict weak #4);
    # larger batches usually buy MFU on v5e until HBM saturates
    sweep = os.environ.get("BENCH_SWEEP", "32,64")
    if on_tpu and "BENCH_BATCH" not in os.environ and sweep:
        best_b, best_tps = batch, 0.0
        for b in [int(s) for s in sweep.split(",") if s]:
            try:
                bi, bl = data_for(b)
                run_steps(2, bi, bl, sync_each=True)      # compile + warm
                dt_s, _ = run_steps(6, bi, bl)
                tps = b * seq * 6 / dt_s
                _log(f"phase=sweep: batch={b} -> {tps:,.0f} tok/s")
                if tps > best_tps:
                    best_b, best_tps = b, tps
            except Exception as e:  # OOM etc.: keep the last good batch
                _log(f"phase=sweep: batch={b} failed ({type(e).__name__})")
                # the failed jitted call donated/poisoned the state arrays;
                # rebuild before the main measurement
                params, buffers, opt_state = make_state()
                break
        batch = best_b
        _log(f"phase=sweep: picked batch={batch}")
        ids, labels = data_for(batch)

    run_steps(warmup, ids, labels, sync_each=True)
    _log(f"phase=warmup: {warmup} steps done (batch={batch})")
    dt, final_loss = run_steps(steps, ids, labels)
    _log(f"phase=measure: {steps} steps in {dt:.2f}s")

    tokens_per_sec = batch * seq * steps / dt

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # PaLM-style: 6N per token (fwd+bwd) + attention 12*L*H*seq
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * \
        cfg.hidden_size * seq
    peak = _peak_flops(dev)
    mfu = (tokens_per_sec * flops_per_token / peak) if peak else 0.0

    _emit({
        "metric": METRIC,
        "value": round(tokens_per_sec, 1),
        "unit": UNIT,
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "device": getattr(dev, "device_kind", dev.platform),
            "batch": batch, "seq": seq, "steps": steps,
            "step_time_ms": round(dt / steps * 1e3, 2),
            "mfu": round(mfu, 4),
            "params": n_params,
            "final_loss": final_loss,
        },
    })


# --------------------------------------------------------------------------
# supervisor: fresh child per attempt, CPU fallback, guaranteed JSON
# --------------------------------------------------------------------------

def _run_child(extra_env: dict, timeout: float) -> str | None:
    """Run one child attempt; return its JSON line on success else None."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=sys.stderr,
            text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        _log(f"attempt timed out after {timeout}s")
        return None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if parsed.get("metric") == METRIC and "error" not in parsed:
                return line
    _log(f"attempt failed rc={proc.returncode}")
    return None


def main() -> None:
    if os.environ.get("BENCH_CHILD") == "1":
        try:
            bench_child()
        except BaseException as e:  # noqa: BLE001 — must emit JSON, not die
            _log(f"child failed: {type(e).__name__}: {e}")
            _emit(_error_json(f"{type(e).__name__}: {e}"))
            sys.exit(3)
        return

    # supervisor: retry the default (TPU) backend twice, then CPU fallback
    timeouts = [900.0, 600.0]
    for i, timeout in enumerate(timeouts):
        _log(f"supervisor: attempt {i + 1}/{len(timeouts)} (timeout {timeout}s)")
        line = _run_child({}, timeout)
        if line is not None:
            print(line, flush=True)
            return
        if i + 1 < len(timeouts):
            time.sleep(10)  # backoff: give a flaky backend time to recover

    _log("supervisor: TPU attempts exhausted, falling back to CPU")
    line = _run_child({"BENCH_FORCE_CPU": "1"}, 600.0)
    if line is not None:
        parsed = json.loads(line)
        parsed["error"] = "tpu backend unavailable; CPU fallback number"
        parsed["vs_baseline"] = 0.0
        _emit(parsed)
        return

    _emit(_error_json("all attempts failed (tpu x2, cpu x1)"))


if __name__ == "__main__":
    main()
