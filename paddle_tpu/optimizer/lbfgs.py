"""L-BFGS optimizer (ref: python/paddle/optimizer/lbfgs.py, upstream
layout, unverified — mount empty).

Closure-based quasi-Newton: `step(closure)` re-evaluates loss+grads as the
line search probes points. The two-loop recursion and strong-Wolfe search
run host-side over a flattened parameter vector (L-BFGS is inherently
sequential; each inner evaluation is still XLA-compiled through the
ordinary eager path), matching the reference's dygraph implementation
shape rather than a lax.while_loop — the loop bounds are tiny (history
~10, line-search evals ~25) and data-dependent.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn: Optional[str] = None,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        if grad_clip is not None:
            raise NotImplementedError(
                "LBFGS does not support grad_clip (clipping the gradient "
                "would break the line-search/curvature conditions)")
        from . import L2Decay
        if self.regularization is not None and \
                not isinstance(self.regularization, L2Decay):
            raise NotImplementedError(
                "LBFGS supports only L2 weight decay (float or L2Decay); "
                "other regularizers would change the line-search objective")
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist: list = []
        self._y_hist: list = []
        self._rho_hist: list = []
        self._n_evals = 0

    # ------------------------------------------------------- flat helpers
    def _params(self):
        return [p for p in self._parameter_list if p.trainable]

    def _gather_flat_grad(self):
        gs = []
        for p in self._params():
            if p.grad is None:
                gs.append(jnp.zeros(int(np.prod(p.shape)), jnp.float32))
            else:
                gs.append(p.grad._data.astype(jnp.float32).reshape(-1))
        return jnp.concatenate(gs)

    def _gather_flat_params(self):
        return jnp.concatenate([p._data.astype(jnp.float32).reshape(-1)
                                for p in self._params()])

    def _set_flat_params(self, flat):
        offset = 0
        for p in self._params():
            n = int(np.prod(p.shape))
            p._data = flat[offset:offset + n].reshape(p._data.shape).astype(
                p._data.dtype)
            offset += n

    def _eval(self, closure, flat_x):
        """Loss and flat gradient at x (restores nothing — caller owns).
        Coupled L2 weight decay is folded into BOTH loss and gradient so
        the strong-Wolfe conditions see one consistent objective."""
        self._set_flat_params(flat_x)
        self.clear_grad()
        loss = closure()
        self._n_evals += 1
        ld = loss._data if isinstance(loss, Tensor) else loss
        f = float(np.asarray(ld))
        g = self._gather_flat_grad()
        coeff = self.regularization.coeff if self.regularization is not None \
            else 0.0
        if coeff:
            f += 0.5 * coeff * float(jnp.dot(flat_x, flat_x))
            g = g + coeff * flat_x
        return f, g

    # ------------------------------------------------------- direction
    def _two_loop(self, flat_grad):
        q = flat_grad
        alphas = []
        for s, y, rho in zip(reversed(self._s_hist),
                             reversed(self._y_hist),
                             reversed(self._rho_hist)):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._y_hist:
            y_last, s_last = self._y_hist[-1], self._s_hist[-1]
            gamma = jnp.dot(s_last, y_last) / jnp.maximum(
                jnp.dot(y_last, y_last), 1e-12)
            q = q * gamma
        for (s, y, rho), a in zip(zip(self._s_hist, self._y_hist,
                                      self._rho_hist), reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + s * (a - b)
        return -q

    # ------------------------------------------------------- line search
    def _strong_wolfe(self, closure, x0, f0, g0, d, t, c1=1e-4, c2=0.9,
                      max_ls=25):
        """Strong-Wolfe line search (bracket + zoom, bisection steps)."""
        dg0 = float(jnp.dot(g0, d))
        f_prev, t_prev = f0, 0.0
        g_new = g0
        lo = hi = None
        f_lo = None
        t_cur = t
        for _ in range(max_ls):
            f_new, g_new = self._eval(closure, x0 + t_cur * d)
            dg_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t_cur * dg0 or \
                    (t_prev > 0 and f_new >= f_prev):
                lo, hi, f_lo = t_prev, t_cur, f_prev
                break
            if abs(dg_new) <= -c2 * dg0:
                return t_cur, f_new, g_new
            if dg_new >= 0:
                lo, hi, f_lo = t_cur, t_prev, f_new
                break
            f_prev, t_prev = f_new, t_cur
            t_cur *= 2.0
        else:
            # bracket loop exhausted: (t_prev, f_prev, g_new) is the last
            # point actually evaluated (t_cur was doubled past it)
            return t_prev, f_prev, g_new
        # zoom by bisection
        for _ in range(max_ls):
            t_mid = 0.5 * (lo + hi)
            f_mid, g_mid = self._eval(closure, x0 + t_mid * d)
            dg_mid = float(jnp.dot(g_mid, d))
            if f_mid > f0 + c1 * t_mid * dg0 or f_mid >= f_lo:
                hi = t_mid
            else:
                if abs(dg_mid) <= -c2 * dg0:
                    return t_mid, f_mid, g_mid
                if dg_mid * (hi - lo) >= 0:
                    hi = lo
                lo, f_lo = t_mid, f_mid
            if abs(hi - lo) < 1e-10:
                break
        return t_mid, f_mid, g_mid

    # ------------------------------------------------------- checkpoint
    def state_dict(self):
        out = super().state_dict()
        out["@lbfgs_history"] = {
            "s": [Tensor(a) for a in self._s_hist],
            "y": [Tensor(a) for a in self._y_hist],
            "rho": list(self._rho_hist),
        }
        return out

    def set_state_dict(self, state_dict):
        state_dict = dict(state_dict)
        hist = state_dict.pop("@lbfgs_history", None)
        super().set_state_dict(state_dict)
        if hist:
            unwrap = lambda a: a._data if isinstance(a, Tensor) \
                else jnp.asarray(np.asarray(a))  # noqa: E731
            self._s_hist = [unwrap(a) for a in hist["s"]]
            self._y_hist = [unwrap(a) for a in hist["y"]]
            self._rho_hist = [float(r) for r in hist["rho"]]

    # ------------------------------------------------------------- step
    def step(self, closure=None):
        if closure is None:
            raise RuntimeError("LBFGS.step requires a closure that "
                               "recomputes loss and gradients")
        self._n_evals = 0
        lr = self.get_lr()
        x = self._gather_flat_params()
        loss0, flat_grad = self._eval(closure, x)
        loss = loss0
        if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
            return Tensor(jnp.asarray(loss))

        for _ in range(self.max_iter):
            d = self._two_loop(flat_grad)
            t = min(1.0, 1.0 / max(float(jnp.sum(jnp.abs(flat_grad))),
                                   1e-12)) * lr if not self._s_hist else lr
            if self.line_search_fn == "strong_wolfe":
                t, loss, g_new = self._strong_wolfe(closure, x, loss,
                                                    flat_grad, d, t)
                x_new = x + t * d
            else:
                x_new = x + t * d
                loss, g_new = self._eval(closure, x_new)
            s = x_new - x
            y = g_new - flat_grad
            sy = float(jnp.dot(s, y))
            if sy > 1e-10:
                if len(self._s_hist) >= self.history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
                    self._rho_hist.pop(0)
                self._s_hist.append(s)
                self._y_hist.append(y)
                self._rho_hist.append(1.0 / sy)
            x, flat_grad = x_new, g_new
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
                break
            if float(jnp.max(jnp.abs(s))) <= self.tolerance_change:
                break
            if self._n_evals >= self.max_eval:
                break
        self._set_flat_params(x)
        return Tensor(jnp.asarray(loss))
