"""paddle.utils — dlpack interop, deterministic-unique-name, download, lazy
import, cpp_extension gate.

Ref: python/paddle/utils/ (upstream layout, unverified — mount empty).
dlpack is real interop (jax speaks the protocol natively); download degrades
gracefully in this zero-egress environment by honoring pre-populated caches.
"""
from __future__ import annotations

import hashlib
import itertools
import os
from typing import Optional

from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import cpp_extension  # noqa: F401
from .lazy_import import try_import  # noqa: F401

__all__ = ["dlpack", "download", "cpp_extension", "try_import", "unique_name",
           "deprecated", "run_check", "require_version"]


class _UniqueNameGenerator:
    def __init__(self):
        self._counters = {}

    def __call__(self, key: str = "tmp") -> str:
        c = self._counters.setdefault(key, itertools.count())
        return f"{key}_{next(c)}"

    def guard(self, new_generator=None):
        import contextlib

        return contextlib.nullcontext()


unique_name = _UniqueNameGenerator()


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Decorator mirroring paddle.utils.deprecated: warn once per call site."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"{fn.__name__} is deprecated since {since or 'this release'}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def run_check() -> None:
    """paddle.utils.run_check: verify the framework can compile and run a
    matmul on the active backend, and report the device inventory."""
    import jax
    import numpy as np

    import paddle_tpu as paddle

    a = paddle.to_tensor(np.ones((4, 4), dtype="float32"))
    out = paddle.matmul(a, a)
    assert float(out.numpy()[0, 0]) == 4.0
    n = len(jax.devices())
    print(f"PaddleTPU works! devices: {n} x "
          f"{getattr(jax.devices()[0], 'device_kind', jax.devices()[0].platform)}")


def require_version(min_version: str, max_version: Optional[str] = None):
    import paddle_tpu

    def parse(v):
        return tuple(int(x) for x in v.split(".")[:3])

    cur = parse(paddle_tpu.__version__)
    if parse(min_version) > cur:
        raise RuntimeError(
            f"paddle_tpu>={min_version} required, found "
            f"{paddle_tpu.__version__}")
    if max_version and parse(max_version) < cur:
        raise RuntimeError(
            f"paddle_tpu<={max_version} required, found "
            f"{paddle_tpu.__version__}")
