"""Worker for the two-process PIPELINE-PARALLEL test (VERDICT r4 #5;
SURVEY §7 hard part #2 — the single riskiest component).

2 processes x 4 local cpu devices = 8 global devices, mesh ("pp", "dp") =
(2, 4): the pp axis SPANS THE HOST BOUNDARY (host 0 owns pp slice 0, host
1 owns pp slice 1), so every activation handoff in the collective GPipe
schedule is a cross-process collective-permute — the send_v2/recv_v2
analog the single-controller engine structurally cannot exercise. Prints
per-step losses; the parent asserts rank agreement and parity with the
sequential (unpipelined) reference.
"""
if __name__ == "__main__":
    # force=True: a spawned worker must not inherit the parent pytest
    # process's 8-device XLA_FLAGS
    from _device_env import ensure_fake_devices

    ensure_fake_devices(4, force=True)
    from paddle_tpu.distributed import env as dist_env

    dist_env.init_parallel_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (  # noqa: E402
    make_spmd_pipeline_fn,
)

PP, DP, MICRO, STEPS, F, B = 2, 4, 4, 4, 8, 16
LR = 0.05


def stage_fn(params, x):
    return x + jnp.tanh(x @ params["w1"]) @ params["w2"]


def make_params():
    rng = np.random.default_rng(42)
    return {
        "w1": rng.standard_normal((PP, F, 16)).astype(np.float32) * 0.3,
        "w2": rng.standard_normal((PP, 16, F)).astype(np.float32) * 0.3,
    }


def batches():
    rng = np.random.default_rng(7)
    for _ in range(STEPS):
        yield (rng.standard_normal((B, F)).astype(np.float32),
               rng.standard_normal((B, F)).astype(np.float32))


def sequential_reference_losses():
    """Ground truth: the unpipelined model, plain SGD — microbatched GPipe
    with a mean loss is numerically identical."""
    params = make_params()

    def seq(p, x):
        for s in range(PP):
            x = stage_fn({k: v[s] for k, v in p.items()}, x)
        return x

    def loss_fn(p, x, y):
        return jnp.mean((seq(p, x) - y) ** 2)

    losses = []
    for x, y in batches():
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g_: p - LR * g_,
                                        params, g)
        losses.append(float(loss))
    return losses


def main():
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == PP * DP
    rank = jax.process_index()

    mesh = Mesh(np.asarray(jax.devices()).reshape(PP, DP), ("pp", "dp"))
    # host 0 owns every device of pp slice 0, host 1 of slice 1: the stage
    # boundary IS the process boundary
    stage_hosts = {d.process_index for d in mesh.devices[0]}
    assert stage_hosts == {0}, stage_hosts

    pipe = make_spmd_pipeline_fn(stage_fn, mesh, num_stages=PP,
                                 num_micro=MICRO)

    def loss_fn(p, x, y):
        return jnp.mean((pipe(p, x) - y) ** 2)

    @jax.jit
    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        return loss, jax.tree_util.tree_map(
            lambda pv, gv: pv - LR * gv, p, g)

    stacked_sh = NamedSharding(mesh, P("pp"))
    data_sh = NamedSharding(mesh, P("dp"))
    params = {k: jax.device_put(v, stacked_sh)
              for k, v in make_params().items()}

    t = 0
    for x, y in batches():
        t += 1
        # every process holds the full batch (deterministic generator);
        # device_put with the dp sharding places the local shards
        gx, gy = jax.device_put(x, data_sh), jax.device_put(y, data_sh)
        loss, params = step(params, gx, gy)
        print(f"rank={rank} pp_step={t} loss={float(np.asarray(loss)):.6f}",
              flush=True)


if __name__ == "__main__":
    main()
