"""Process-wide flag registry (ref: paddle/common/flags.cc upstream layout,
unverified — mount empty).

Paddle exposes C++ `FLAGS_*` through paddle.get_flags/set_flags and `FLAGS_*`
env vars. We keep the same three-tier shape: registered flags with defaults,
env-var override at first read (`FLAGS_<name>`), and set_flags() at runtime.
A native (C shared-lib) backing store is attached when available so C++
runtime components see the same flags; the python dict is authoritative.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_FLAGS: Dict[str, Dict[str, Any]] = {}


def define_flag(name: str, default, doc: str = "", flag_type=None):
    if name in _FLAGS:
        return
    flag_type = flag_type or type(default)
    _FLAGS[name] = {
        "value": default,
        "default": default,
        "doc": doc,
        "type": flag_type,
        "env_read": False,
    }


def _coerce(value, flag_type):
    if flag_type is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    return flag_type(value)


def get_flags(names):
    single = isinstance(names, str)
    if single:
        names = [names]
    out = {}
    for name in names:
        if name not in _FLAGS:
            raise KeyError(f"flag {name!r} is not registered")
        entry = _FLAGS[name]
        if not entry["env_read"]:
            env = os.environ.get(name if name.startswith("FLAGS_") else f"FLAGS_{name}")
            if env is not None:
                entry["value"] = _coerce(env, entry["type"])
            entry["env_read"] = True
        out[name] = entry["value"]
    return out


def get_flag(name: str):
    return get_flags(name)[name]


def set_flags(flags: Dict[str, Any]):
    for name, value in flags.items():
        if name not in _FLAGS:
            raise KeyError(f"flag {name!r} is not registered")
        entry = _FLAGS[name]
        entry["value"] = _coerce(value, entry["type"])
        entry["env_read"] = True
        if name == "FLAGS_check_nan_inf":
            # the eager dispatcher checks op outputs itself; jitted/pjit
            # steps (where the dispatcher never sees values) get the same
            # guard through XLA's nan debugging — paddle's
            # check_numerics-under-graph analog
            import jax

            jax.config.update("jax_debug_nans", bool(entry["value"]))


def list_flags():
    return {k: v["value"] for k, v in _FLAGS.items()}


# ---- core flags (paddle-compatible names where they exist upstream) ----
define_flag("FLAGS_check_nan_inf", False, "check nan/inf on op outputs in eager mode")
define_flag("FLAGS_eager_vjp_jit", True, "jit-wrap eager per-op forward functions")
define_flag("FLAGS_benchmark", False, "block on every op (debug timing)")
define_flag("FLAGS_use_amp_master_weight", True, "keep fp32 master weights under O2")
define_flag("FLAGS_tpu_default_matmul_precision", "default", "jax matmul precision")
define_flag("FLAGS_log_level", 0, "framework log verbosity (GLOG_v analog)")
