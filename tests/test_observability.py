"""paddle_tpu.observability: registry get-or-create semantics, log-bucket
histogram percentile accuracy on known distributions, Prometheus text
exposition (parsed, not eyeballed), JSON snapshot round-trip, the
request-lifecycle tracker folding spans into profiler chrome-trace
exports, and the tools/trace_summary.py CLI over a synthetic trace.

Engine-level observability (stats() as a registry view, lifecycle under
preemption, the metrics-disabled overhead guard) lives in
tests/test_serving.py next to the serving fixtures. Everything here is
model-free and jit-free; only the large-sample distribution sweep is
`slow`.
"""
import importlib.util
import json
import math
import os
import re

import numpy as np
import pytest

from paddle_tpu.observability import (
    Counter, Gauge, Histogram, LifecycleTracker, MetricsRegistry,
    global_registry, registry_from_snapshot, to_prometheus,
)


# ------------------------------------------------------ counters / gauges

class TestCountersAndGauges:
    def test_counter_monotonic(self):
        c = Counter("tokens_total")
        c.inc()
        c.inc(5)
        assert c.value == 6 and isinstance(c.value, int)
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1)

    def test_counter_float_accumulation(self):
        c = Counter("seconds_total")
        c.inc(0.25)
        c.inc(0.5)
        assert abs(c.value - 0.75) < 1e-12

    def test_gauge_set_inc_dec(self):
        g = Gauge("queue_depth")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3


# --------------------------------------------------------------- registry

class TestRegistry:
    def test_get_or_create_returns_same_object(self):
        r = MetricsRegistry()
        a = r.counter("x_total", "help")
        b = r.counter("x_total")
        assert a is b and len(r) == 1

    def test_labels_create_distinct_series(self):
        r = MetricsRegistry()
        a = r.gauge("depth", labels={"state": "waiting"})
        b = r.gauge("depth", labels={"state": "running"})
        assert a is not b and len(r) == 2
        assert r.get("depth", {"state": "waiting"}) is a
        assert r.get("depth") is None        # unlabelled series not created

    def test_kind_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("x_total")
        with pytest.raises(TypeError, match="already registered"):
            r.gauge("x_total")

    def test_name_validation(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError, match="invalid metric name"):
            r.counter("bad-name")
        with pytest.raises(ValueError, match="invalid label name"):
            r.counter("ok_total", labels={"bad-label": "v"})

    def test_global_registry_is_a_singleton(self):
        assert global_registry() is global_registry()


# -------------------------------------------------------------- histogram

class TestHistogram:
    def test_bucket_edges_and_overflow(self):
        h = Histogram("lat", lo=1.0, hi=16.0, growth=2.0)   # 4 buckets
        assert h.num_buckets == 4
        for v in (0.0, -3.0, 0.5):       # underflow incl. zero/negative
            h.observe(v)
        h.observe(1.0)                   # first real bucket [1, 2)
        h.observe(15.9)                  # last real bucket [8, 16)
        h.observe(16.0)                  # overflow
        h.observe(1e9)
        assert h._counts[0] == 3
        assert h._counts[1] == 1
        assert h._counts[h.num_buckets] == 1
        assert h._counts[-1] == 2
        assert h.count == 7

    def test_nan_dropped(self):
        h = Histogram("lat")
        h.observe(float("nan"))
        assert h.count == 0

    def test_empty_percentiles_and_summary(self):
        h = Histogram("lat")
        assert h.percentile(50) == 0.0
        assert h.summary() == Histogram.empty_summary()
        assert h.summary()["p99"] == 0.0

    def test_point_mass_reports_exactly(self):
        """min/max clamping makes a constant stream report its exact
        value at every percentile, despite ~19%-wide buckets."""
        h = Histogram("lat")
        for _ in range(100):
            h.observe(0.125)
        for q in (1, 50, 99, 100):
            assert h.percentile(q) == 0.125
        s = h.summary()
        assert s["count"] == 100 and abs(s["mean"] - 0.125) < 1e-12

    def test_log_uniform_percentiles_within_bucket_error(self):
        """Geometric interpolation is exact for log-uniform data up to
        bucket quantization: p50/p95/p99 within the bucket growth factor
        of numpy's exact percentiles."""
        rng = np.random.default_rng(7)
        vals = np.exp(rng.uniform(np.log(1e-3), np.log(10.0), 4000))
        h = Histogram("lat", lo=1e-5, hi=600.0)
        for v in vals:
            h.observe(float(v))
        for q in (50, 95, 99):
            est, exact = h.percentile(q), float(np.percentile(vals, q))
            assert abs(est - exact) / exact < h.growth - 1.0 + 0.02, \
                f"p{q}: {est} vs exact {exact}"

    @pytest.mark.slow            # distribution-heavy: 200k-sample sweeps
    def test_percentile_accuracy_on_known_distributions(self):
        """Exponential and lognormal at 200k samples: relative error
        bounded by one bucket ratio at the default growth, and by ~9%
        with a finer growth=2**0.125 histogram."""
        rng = np.random.default_rng(123)
        dists = {
            "exponential": rng.exponential(0.05, 200_000),
            "lognormal": rng.lognormal(-3.0, 1.0, 200_000),
        }
        for growth, tol in ((2 ** 0.25, 0.20), (2 ** 0.125, 0.095)):
            for name, vals in dists.items():
                h = Histogram("lat", lo=1e-6, hi=600.0, growth=growth)
                for v in vals:
                    h.observe(float(v))
                for q in (50, 95, 99):
                    est = h.percentile(q)
                    exact = float(np.percentile(vals, q))
                    rel = abs(est - exact) / exact
                    assert rel < tol, \
                        f"{name} p{q} growth={growth}: rel err {rel:.3f}"

    def test_bounded_memory(self):
        """Bucket count is fixed by (lo, hi, growth), never by the number
        of observations."""
        h = Histogram("lat")
        n_buckets = len(h._counts)
        for v in np.linspace(1e-6, 700, 10_000):
            h.observe(float(v))
        assert len(h._counts) == n_buckets
        assert sum(h._counts) == h.count == 10_000


# -------------------------------------------------------------- exporters

def _sample_registry():
    r = MetricsRegistry()
    r.counter("serving_tokens_generated_total", "tokens").inc(42)
    r.counter("serving_jit_compile_misses_total", "misses",
              labels={"family": "prefill"}).inc(2)
    r.gauge("serving_queue_depth", "depth",
            labels={"state": "waiting"}).set(3)
    h = r.histogram("serving_ttft_seconds", "ttft")
    for v in (0.001, 0.002, 0.004, 0.1, 2.0):
        h.observe(v)
    return r


# one sample line: name{labels}? value  (value may be +Inf/-Inf/float/int).
# Label values follow the text-format escaping rules — `\\`, `\n`, `\"`
# — so the value pattern is "any run of non-quote-non-backslash chars or
# backslash escapes" (ISSUE 19 audit: the old `[^"]*` silently accepted
# a BROKEN exposition where a raw `"` inside a value ended it early)
_PROM_VALUE = r'(?:[^"\\\n]|\\.)*'
_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="' + _PROM_VALUE +
    r'"(,[a-zA-Z_][a-zA-Z0-9_]*="' + _PROM_VALUE + r'")*\})?'
    r' (\+Inf|-Inf|-?[0-9.]+(e[+-]?[0-9]+)?)$')


class TestPrometheusExport:
    def test_text_parses_line_by_line(self):
        text = to_prometheus(_sample_registry())
        assert text.endswith("\n")
        types = {}
        for line in text.strip().split("\n"):
            if line.startswith("# TYPE"):
                _, _, name, kind = line.split(" ")
                types[name] = kind
            elif not line.startswith("#"):
                assert _PROM_LINE.match(line), f"unparseable: {line!r}"
        assert types["serving_tokens_generated_total"] == "counter"
        assert types["serving_queue_depth"] == "gauge"
        assert types["serving_ttft_seconds"] == "histogram"

    def test_histogram_exposition_is_cumulative_and_consistent(self):
        text = to_prometheus(_sample_registry())
        buckets = []
        for line in text.split("\n"):
            if line.startswith("serving_ttft_seconds_bucket"):
                buckets.append(int(line.rsplit(" ", 1)[1]))
        assert buckets == sorted(buckets)            # cumulative
        assert buckets[-1] == 5                      # +Inf == count
        assert "serving_ttft_seconds_count 5" in text
        m = re.search(r"serving_ttft_seconds_sum ([0-9.e+-]+)", text)
        assert m and abs(float(m.group(1)) - 2.107) < 1e-9
        assert 'le="+Inf"' in text

    def test_one_type_line_per_name_across_label_series(self):
        r = MetricsRegistry()
        r.gauge("depth", labels={"state": "waiting"}).set(1)
        r.gauge("depth", labels={"state": "running"}).set(2)
        text = to_prometheus(r)
        assert text.count("# TYPE depth gauge") == 1
        assert 'depth{state="running"} 2' in text
        assert 'depth{state="waiting"} 1' in text

    def test_label_escaping(self):
        r = MetricsRegistry()
        r.counter("c_total", labels={"path": 'a"b\\c'}).inc()
        text = to_prometheus(r)
        assert r'path="a\"b\\c"' in text

    def test_label_escaping_order_and_newline(self):
        """Backslash is escaped FIRST, then newline, then quote — so a
        value containing all three round-trips without double-escaping
        (ISSUE 19 audit of export._escape)."""
        r = MetricsRegistry()
        r.counter("c_total", labels={"path": 'a\\n"b\nc'}).inc()
        text = to_prometheus(r)
        # literal backslash+n -> \\n, the quote -> \", real newline -> \n
        assert 'path="a\\\\n\\"b\\nc"' in text
        # no raw newline may survive inside the exposition line
        for line in text.strip().split("\n"):
            if not line.startswith("#"):
                assert _PROM_LINE.match(line), f"unparseable: {line!r}"

    def test_help_escaping_is_backslash_and_newline_only(self):
        """HELP text is unquoted in the exposition format: only backslash
        and newline are escaped there; a literal double-quote must pass
        through untouched (the gap the ISSUE 19 audit fixed — HELP used
        to go through the label-value escaper and emit \\")."""
        r = MetricsRegistry()
        r.counter("c_total", 'tokens "in flight" per\nshard \\ chip').inc()
        text = to_prometheus(r)
        assert ('# HELP c_total tokens "in flight" per\\nshard \\\\ chip'
                in text)
        assert r'\"' not in text.split("\n")[0]

    def test_training_series_round_trip_line_by_line(self):
        """The ISSUE 19 training plane's dp/tp/stage-labeled series —
        phase seconds, shard step seconds, sentinel flag counters,
        throughput gauges — must all survive the line-by-line parser,
        including a hostile label value with quote/backslash/newline."""
        r = MetricsRegistry()
        lab = {"dp": "2", "tp": "2", "stage": "1"}
        for phase in ("batch_build", "dispatch", "host_drain"):
            h = r.histogram("training_step_phase_seconds",
                            "per-phase wall seconds",
                            labels={**lab, "phase": phase})
            h.observe(0.001 * (1 + len(phase)))
        for shard in range(4):
            r.histogram("training_shard_step_seconds",
                        "per-shard probe",
                        labels={**lab, "shard": str(shard)}).observe(2e-4)
        for cond in ("nan", "loss_spike", "grad_spike", "plateau"):
            r.counter("training_sentinel_flags_total",
                      "sentinel flags",
                      labels={**lab, "condition": cond}).inc()
        r.gauge("training_tokens_per_sec", "throughput", labels=lab) \
            .set(123456.789)
        r.gauge("training_tokens_per_sec_per_chip", "per chip",
                labels=lab).set(30864.2)
        r.counter("training_steps_total", "steps", labels=lab).inc(7)
        # hostile value: the escape-aware parser must still take the line
        r.counter("c_total", labels={"note": 'sp"ike\\at\nstep 4'}).inc()
        text = to_prometheus(r)
        names = set()
        for line in text.strip().split("\n"):
            if line.startswith("#"):
                continue
            m = _PROM_LINE.match(line)
            assert m, f"unparseable: {line!r}"
            names.add(line.split("{", 1)[0].split(" ", 1)[0])
        assert "training_step_phase_seconds_bucket" in names
        assert "training_shard_step_seconds_count" in names
        assert "training_sentinel_flags_total" in names
        assert "training_tokens_per_sec_per_chip" in names
        # label sets render sorted and fully escaped
        assert 'phase="dispatch"' in text
        assert 'condition="loss_spike"' in text
        assert 'dp="2",phase="batch_build",stage="1",tp="2"' in text
        assert r'note="sp\"ike\\at\nstep 4"' in text

    def test_parser_rejects_unescaped_quote_in_value(self):
        """The escape-aware pattern is strict, not just permissive: a raw
        `"` inside a label value (what a broken escaper would emit) must
        NOT parse."""
        assert _PROM_LINE.match('m{a="x\\"y"} 1')
        assert not _PROM_LINE.match('m{a="x"y"} 1')
        assert not _PROM_LINE.match('m{a="x\\"} 1')


class TestJsonSnapshot:
    def test_snapshot_roundtrips_through_json(self):
        reg = _sample_registry()
        snap = reg.snapshot()
        wire = json.dumps(snap)                      # must be JSON-able
        rebuilt = registry_from_snapshot(json.loads(wire))
        assert rebuilt.snapshot() == snap
        # rebuilt histograms are LIVE: percentiles still work
        h = rebuilt.get("serving_ttft_seconds")
        orig = reg.get("serving_ttft_seconds")
        assert h.count == 5
        assert h.percentile(50) == orig.percentile(50)
        assert rebuilt.get("serving_tokens_generated_total").value == 42

    def test_empty_registry_roundtrip(self):
        snap = MetricsRegistry().snapshot()
        assert snap == {"metrics": []}
        assert registry_from_snapshot(snap).snapshot() == snap


# ------------------------------------------------------ lifecycle tracker

class TestLifecycleTracker:
    def test_retention_order_and_stages(self):
        lt = LifecycleTracker()
        lt.point(3, "enqueued", t=1.0)
        lt.point(3, "admitted", t=2.0)
        lt.span(3, "prefill", 2.0, 2.5)
        lt.span(3, "decode_block", 2.5, 3.0, retain=False)
        lt.point(3, "finished", t=3.0)
        assert lt.stages(3) == ["enqueued", "admitted", "prefill",
                                "finished"]
        assert lt.events(3)[2] == ("prefill", 2.0, 2.5)
        assert lt.request_ids() == [3]
        assert "prefill" in lt.timeline(3)

    def test_retention_is_bounded(self):
        lt = LifecycleTracker(max_events_per_request=4)
        for i in range(10):
            lt.point(1, f"s{i}", t=float(i))
        assert len(lt.events(1)) == 4
        assert lt.dropped == 6

    def test_spans_fold_into_profiler_chrome_trace(self, tmp_path):
        from paddle_tpu import profiler as P

        lt = LifecycleTracker()
        prof = P.Profiler(timer_only=True,
                          on_trace_ready=P.export_chrome_tracing(
                              str(tmp_path)))
        prof.start()
        lt.point(7, "enqueued")
        lt.span(7, "prefill", 10.0, 10.5)
        prof.stop()
        files = list(tmp_path.glob("*.json"))
        assert files
        with open(files[0]) as f:
            names = [e["name"] for e in json.load(f)["traceEvents"]]
        assert "serving.request[7].enqueued" in names
        assert "serving.request[7].prefill" in names

    def test_unarmed_spans_stay_out_of_profiler_but_are_retained(self):
        from paddle_tpu.profiler import _HOST_TRACER

        lt = LifecycleTracker()
        before = len(_HOST_TRACER.events)
        lt.point(9, "enqueued")
        assert len(_HOST_TRACER.events) == before    # no armed window
        assert lt.stages(9) == ["enqueued"]


# ---------------------------------------------------------- trace summary

def _trace_summary_mod():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_SYNTH_EVENTS = [
    {"name": "step", "ph": "X", "ts": 0, "dur": 100, "pid": 1, "tid": 1},
    {"name": "child", "ph": "X", "ts": 10, "dur": 30, "pid": 1, "tid": 1},
    {"name": "grandchild", "ph": "X", "ts": 12, "dur": 5, "pid": 1,
     "tid": 1},
    {"name": "serving.request[3].prefill", "ph": "X", "ts": 5, "dur": 20,
     "pid": 1, "tid": 2},
    {"name": "serving.request[3].first_token", "ph": "X", "ts": 25,
     "dur": 0, "pid": 1, "tid": 2},
    {"name": "serving.request[4].prefill", "ph": "X", "ts": 30, "dur": 10,
     "pid": 1, "tid": 2},
    {"name": "meta", "ph": "M", "pid": 1, "tid": 1},     # ignored
]


class TestTraceSummary:
    def test_span_stats_total_and_self_time(self):
        ts = _trace_summary_mod()
        stats = ts.span_stats(list(map(dict, _SYNTH_EVENTS)))
        assert stats["step"]["total"] == 100
        assert stats["step"]["self"] == 70           # minus child's 30
        assert stats["child"]["self"] == 25          # minus grandchild's 5
        assert stats["grandchild"]["self"] == 5
        assert "meta" not in stats

    def test_request_timelines_group_and_order(self):
        ts = _trace_summary_mod()
        tl = ts.request_timelines(list(map(dict, _SYNTH_EVENTS)))
        assert sorted(tl) == [3, 4]
        assert [s for s, _, _ in tl[3]] == ["prefill", "first_token"]

    def test_span_gap_between_consecutive_same_name_spans(self):
        # decode-stall in trace form: time between the end of one
        # decode_block span and the start of the next, per thread
        ts = _trace_summary_mod()
        events = [
            {"name": "decode_block", "ph": "X", "ts": 0, "dur": 10,
             "pid": 1, "tid": 1},
            {"name": "decode_block", "ph": "X", "ts": 25, "dur": 10,
             "pid": 1, "tid": 1},
            {"name": "decode_block", "ph": "X", "ts": 40, "dur": 10,
             "pid": 1, "tid": 1},
            # other thread: never merges into tid 1's gap chain
            {"name": "decode_block", "ph": "X", "ts": 500, "dur": 10,
             "pid": 1, "tid": 2},
        ]
        stats = ts.span_stats(events)
        assert stats["decode_block"]["gap"] == (25 - 10) + (40 - 35)
        assert stats["decode_block"]["count"] == 4
        # single spans have no gap
        assert ts.span_stats(list(map(dict, _SYNTH_EVENTS)))[
            "step"]["gap"] == 0.0

    def test_spec_point_folds_into_request_header(self):
        # the engine's drain drops one spec[a=...,t/s=...] point per
        # finished speculative request; the summary folds it into the
        # request header line instead of rendering it as a stage
        ts = _trace_summary_mod()
        events = list(map(dict, _SYNTH_EVENTS)) + [
            {"name": "serving.request[3].spec[a=0.71,t/s=2.9]", "ph": "X",
             "ts": 26, "dur": 0, "pid": 1, "tid": 2},
        ]
        out = ts.format_requests(ts.request_timelines(events))
        assert "request 3 spec a=0.71 t/s=2.9:" in out
        # folded, not a timeline row
        assert "spec[a=0.71,t/s=2.9]" not in out
        # requests without the point are unannotated
        assert "request 4:" in out and "request 4 spec" not in out

    def test_cli_end_to_end(self, tmp_path, capsys):
        ts = _trace_summary_mod()
        path = tmp_path / "trace.json"
        path.write_text(json.dumps({"traceEvents": _SYNTH_EVENTS}))
        assert ts.main([str(path), "--requests", "--top", "5"]) == 0
        out = capsys.readouterr().out
        assert "step" in out
        assert "gap(ms)" in out
        assert "request 3:" in out and "first_token" in out
