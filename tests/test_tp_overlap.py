"""Collective/compute overlap in the TP decode step (ISSUE 18).

The overlapped engine splits each row-parallel psum into K micro-row
chunks moved by a fixed-order ppermute ring, double-buffered so the
transport of chunk j+1 is in flight while chunk j's reduction feeds the
consumer matmul. Because the ring accumulates in static shard order —
the same order `parallel.mesh.ordered_psum` fixed — tokens must be
BIT-IDENTICAL to the serial-psum engine at every tp degree, in fp32 and
composed with the int8 quantized all-reduce. A fast core pins tp=2 for
both model families; the full tp x quant x horizon x chunks matrix is
`slow`. Plus: chunks=1 is proven to emit the literal serial executable
(zero new jit-cache keys), a poisoned-module raise-on-touch proof that
serial engines run zero overlap code, snapshot -> restore across
overlap on/off, the warmed best-of collective probe's monotone
aggregator, the `overlap_fraction` stats surface, and the knob's
validation errors.
"""
import functools
import sys
import types

import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.models import (
    GPTConfig, GPTForCausalLM, LlamaConfig, LlamaForCausalLM,
)
from paddle_tpu.serving import RequestJournal, ServingEngine

if len(jax.devices()) < 4:
    pytest.skip("tp overlap tests need >= 4 fake devices",
                allow_module_level=True)


@functools.lru_cache(maxsize=None)
def _llama4():
    """kv_heads=4: supports tp in {2, 4} (tiny's kv=2 caps at tp=2)."""
    paddle.seed(1234)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4,
        intermediate_size=128, max_position_embeddings=64))
    m.eval()
    return m


@functools.lru_cache(maxsize=None)
def _gpt():
    paddle.seed(1234)
    m = GPTForCausalLM(GPTConfig.tiny())
    m.eval()
    return m


def _fresh_llama():
    paddle.seed(1234)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


_ENGINE_KW = dict(page_size=4, num_pages=64, max_batch_size=4,
                  max_seq_len=48, decode_horizon=4)

_PROMPTS = [[7, 3, 9, 1, 4], [2, 8, 6, 5, 1, 9, 3, 7, 2],
            [4, 4, 1, 8, 8, 2, 6, 3, 9, 5, 1, 7, 3]]


def _staggered(model, prompts=_PROMPTS, max_new=6, **kw):
    """Staggered arrivals, seeded sampling -> tokens in arrival order.
    Seeded sampling is the stricter parity probe: any drift in the
    logits flips the gumbel argmax somewhere in six tokens."""
    eng = ServingEngine(model, **{**_ENGINE_KW, **kw})
    rids = [eng.add_request(p, max_new_tokens=max_new, temperature=0.8,
                            top_k=5, seed=100 + i)
            for i, p in enumerate(prompts[:2])]
    for _ in range(2):
        eng.step()
    for j, p in enumerate(prompts[2:], start=2):
        rids.append(eng.add_request(p, max_new_tokens=max_new,
                                    temperature=0.8, top_k=5,
                                    seed=100 + j))
        eng.step()
    outs = eng.run()
    return eng, [outs[r] for r in rids]


# serial-engine references, one per (model-id, tp, quant, horizon) —
# the overlap contract is bit-identity against the SAME config without
# overlap (qar is lossy vs fp32, so fp32 tokens are the wrong yardstick
# for qar cells)
_REF = {}


def _reference(model, tp, quant, horizon):
    key = (id(model), tp, quant, horizon)
    if key not in _REF:
        _, _REF[key] = _staggered(model, tp_size=tp,
                                  tp_quantized_allreduce=quant,
                                  decode_horizon=horizon)
    return _REF[key]


# --------------------------------------------------------- token parity

class TestBitIdentityCore:
    def test_llama_tp2_chunks2_matches_serial(self):
        want = _reference(_llama4(), 2, False, 4)
        _, got = _staggered(_llama4(), tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=2)
        assert got == want

    def test_gpt_tp2_chunks2_matches_serial(self):
        """GPT drives the fused-QKV seam: the pending previous-layer
        reduction interleaves with chunk slices of one (h, 3h) matmul,
        and the ffn_out bias must re-associate as `resid + (red + bias)`
        to keep the serial add order."""
        want = _reference(_gpt(), 2, False, 4)
        _, got = _staggered(_gpt(), tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=2)
        assert got == want

    def test_llama_tp2_qar_chunks2_matches_serial_qar(self):
        """Composed with the int8 quantized all-reduce: chunking rows
        commutes with per-row block quantization, so the ring moves the
        same (q, scale) payloads the serial qar psum moves."""
        want = _reference(_llama4(), 2, True, 4)
        _, got = _staggered(_llama4(), tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=2,
                            tp_quantized_allreduce=True)
        assert got == want


@pytest.mark.slow
class TestBitIdentityMatrix:
    @pytest.mark.parametrize("tp", [2, 4])
    @pytest.mark.parametrize("quant", [False, True])
    @pytest.mark.parametrize("horizon", [1, 8])
    @pytest.mark.parametrize("chunks", [1, 2, 4])
    def test_llama_matrix(self, tp, quant, horizon, chunks):
        want = _reference(_llama4(), tp, quant, horizon)
        _, got = _staggered(_llama4(), tp_size=tp, tp_overlap=True,
                            tp_overlap_chunks=chunks,
                            tp_quantized_allreduce=quant,
                            decode_horizon=horizon)
        assert got == want

    @pytest.mark.parametrize("tp", [2, 4])
    @pytest.mark.parametrize("chunks", [2, 4])
    def test_gpt_matrix(self, tp, chunks):
        want = _reference(_gpt(), tp, False, 4)
        _, got = _staggered(_gpt(), tp_size=tp, tp_overlap=True,
                            tp_overlap_chunks=chunks)
        assert got == want


# --------------------------------------- chunks=1 is the serial engine

class TestChunksOneIsSerial:
    def test_chunks1_reuses_the_literal_serial_executable(self):
        """tp_overlap_chunks=1 has nothing to pipeline, so the knob
        normalizes OFF: the serial retype runs, the jit keys carry no
        ("ovl", ...) suffix, and the engine reuses the serial engine's
        cached executables byte-for-byte (zero new cache keys)."""
        model = _fresh_llama()
        _staggered(model, tp_size=2)
        serial_keys = set(model._serving_jit_cache)
        assert serial_keys
        eng, _ = _staggered(model, tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=1)
        assert set(model._serving_jit_cache) == serial_keys
        assert eng._tp.overlap is False
        d = eng._tp.describe()
        assert d["overlap"] is False
        assert d["overlap_chunks"] == 1
        assert d["overlap_fraction"] is None

    def test_overlap_keys_are_disjoint_from_serial(self):
        """chunks>=2 compiles NEW executables (the ring is a different
        program) under keys suffixed ("ovl", chunks) — serial and
        overlapped engines sharing one model never exchange them."""
        model = _fresh_llama()
        _staggered(model, tp_size=2)
        serial_keys = set(model._serving_jit_cache)
        _staggered(model, tp_size=2, tp_overlap=True,
                   tp_overlap_chunks=2)
        new = set(model._serving_jit_cache) - serial_keys
        assert new
        for k in new:
            assert k[-2:] == ("ovl", 2), k


# ------------------------------------------------- zero-touch when off

class TestZeroTouchWhenOff:
    def test_serial_engines_never_import_overlap_module(self, monkeypatch):
        """Poison paddle_tpu.serving.overlap: tp=1 and serial tp=2
        engines (and chunks=1, which normalizes off) must run a full
        request without touching it; tp_overlap with chunks>=2 must
        trip the poison — the effective knob is the ONLY gate."""
        poison = types.ModuleType("paddle_tpu.serving.overlap")

        def _boom(name):
            raise AssertionError(
                f"overlap module touched with overlap off: {name}")

        poison.__getattr__ = _boom
        monkeypatch.setitem(sys.modules, "paddle_tpu.serving.overlap",
                            poison)
        _, out = _staggered(_llama4(), prompts=_PROMPTS[:1])
        assert len(out[0]) > len(_PROMPTS[0])
        _staggered(_llama4(), prompts=_PROMPTS[:1], tp_size=2)
        _staggered(_llama4(), prompts=_PROMPTS[:1], tp_size=2,
                   tp_overlap=True, tp_overlap_chunks=1)
        with pytest.raises(AssertionError, match="overlap module touched"):
            ServingEngine(_llama4(), tp_size=2, tp_overlap=True,
                          **_ENGINE_KW)


# --------------------------------------- snapshot across overlap modes

class TestSnapshotAcrossOverlap:
    def test_overlap_snapshot_restores_on_serial_and_back(self):
        """The journal's token record is numerics-independent state, and
        overlap preserves numerics bit-for-bit — so a snapshot taken
        mid-run on an overlapped tp=2 engine restores onto a serial
        tp=4 engine (a different degree AND a different reduction
        program) and finishes with the tp=1 token streams."""
        want = _reference(_llama4(), 2, False, 4)
        eng = ServingEngine(_llama4(), journal=RequestJournal(),
                            tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=2, **_ENGINE_KW)
        rids = [eng.add_request(p, max_new_tokens=6, temperature=0.8,
                                top_k=5, seed=100 + i)
                for i, p in enumerate(_PROMPTS)]
        for _ in range(3):
            eng.step()
        snap = eng.snapshot()
        eng2 = ServingEngine(_llama4(), journal=eng._journal,
                             tp_size=4, **_ENGINE_KW)
        eng2.restore(snap)
        out = eng2.run()
        assert [out[r] for r in rids] == want
        eng._journal.check_consistency()

    def test_serial_snapshot_restores_on_overlap(self):
        want = _reference(_llama4(), 2, False, 4)
        eng = ServingEngine(_llama4(), journal=RequestJournal(),
                            tp_size=2, **_ENGINE_KW)
        rids = [eng.add_request(p, max_new_tokens=6, temperature=0.8,
                                top_k=5, seed=100 + i)
                for i, p in enumerate(_PROMPTS)]
        for _ in range(3):
            eng.step()
        snap = eng.snapshot()
        eng2 = ServingEngine(_llama4(), journal=eng._journal,
                             tp_size=2, tp_overlap=True,
                             tp_overlap_chunks=4, **_ENGINE_KW)
        eng2.restore(snap)
        out = eng2.run()
        assert [out[r] for r in rids] == want


# ----------------------------------------------- probe + observability

class TestProbeAndStats:
    def test_probe_best_of_is_monotone_nonincreasing(self):
        """The collective probe aggregates best-of-N trials with a
        statistic that can only improve as trials accumulate — the
        guard that a noisy extra trial never WORSENS the published
        number (the dispatch-queueing bug this PR fixes was exactly a
        worst-trial leaking through)."""
        from paddle_tpu.serving.tp import TPContext
        trials = [3.0, 2.0, 5.0, 1.0, 4.0]
        prev = None
        for n in range(1, len(trials) + 1):
            cur = TPContext.probe_best_of(trials[:n])
            assert cur > 0.0
            if prev is not None:
                assert cur <= prev
            prev = cur
        assert prev == 1.0

    def test_collective_seconds_warmed_and_positive(self):
        eng, _ = _staggered(_llama4(), tp_size=2)
        ts = eng._tp.collective_seconds(samples=3, rows=2, best_of=2)
        assert len(ts) == 3
        assert all(isinstance(t, float) and t > 0.0 for t in ts)

    def test_overlap_fraction_published_in_stats(self):
        eng, _ = _staggered(_llama4(), tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=2)
        frac = eng.stats()["tp"]["overlap_fraction"]
        assert isinstance(frac, float)
        assert 0.0 <= frac <= 1.0
        d = eng._tp.describe()
        assert d["overlap"] is True
        assert d["overlap_chunks"] == 2

    def test_serial_stats_report_no_overlap(self):
        eng, _ = _staggered(_llama4(), tp_size=2)
        tp = eng.stats()["tp"]
        assert tp["overlap"] is False
        assert tp["overlap_fraction"] is None

    def test_collective_histogram_carries_overlap_label(self):
        eng, _ = _staggered(_llama4(), tp_size=2, tp_overlap=True,
                            tp_overlap_chunks=2)
        h = eng.metrics.get("serving_tp_collective_seconds",
                            labels={"overlap": "on"})
        assert h is not None and h.count >= 3
        assert eng.metrics.get("serving_tp_collective_seconds",
                               labels={"overlap": "off"}) is None


# ----------------------------------------------------------- validation

class TestValidation:
    def test_overlap_at_tp1_is_rejected(self):
        with pytest.raises(ValueError, match="tp_size >= 2"):
            ServingEngine(_llama4(), tp_overlap=True, **_ENGINE_KW)

    def test_zero_chunks_rejected(self):
        with pytest.raises(ValueError, match="chunks"):
            ServingEngine(_llama4(), tp_size=2, tp_overlap=True,
                          tp_overlap_chunks=0, **_ENGINE_KW)
