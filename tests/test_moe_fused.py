"""Fused MoE dispatch (SURVEY §7 Pallas fusion set; VERDICT r4 #9).

gather_rows is the dispatch/combine primitive: out[m] = src[idx[m]] with
zero rows for over-capacity slots, scatter-add transpose for grads. The
fused _routed_forward must match the einsum reference bit-for-tolerance,
forward AND backward, in interpret mode on CPU; the Mosaic compile of the
kernel itself is covered by the AOT tier in test_hlo_perf.py.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate.distributed.models.moe import MoELayer
from paddle_tpu.ops import pallas_kernels as pk


class TestGatherRows:
    def test_forward_with_empty_slots(self):
        rng = np.random.RandomState(0)
        src = jnp.asarray(rng.randn(37, 12).astype("float32"))
        idx = jnp.asarray(np.array([3, 0, -1, 36, 7, 7, -1, 20], np.int32))
        out = pk.gather_rows(src, idx, interpret=True)
        ref = np.where((np.asarray(idx) >= 0)[:, None],
                       np.asarray(src)[np.maximum(np.asarray(idx), 0)], 0)
        np.testing.assert_allclose(np.asarray(out), ref)

    def test_grad_is_scatter_add(self):
        rng = np.random.RandomState(1)
        src = jnp.asarray(rng.randn(16, 8).astype("float32"))
        idx = jnp.asarray(np.array([5, 5, -1, 0, 15], np.int32))
        w = jnp.arange(1.0, 6.0)[:, None]

        g = jax.grad(lambda s: (pk.gather_rows(s, idx, interpret=True)
                                * w).sum())(src)
        ref = np.zeros((16, 8), np.float32)
        for m, i in enumerate(np.asarray(idx)):
            if i >= 0:
                ref[i] += (m + 1)
        np.testing.assert_allclose(np.asarray(g), ref)

    def test_jit_and_odd_sizes(self):
        rng = np.random.RandomState(2)
        src = jnp.asarray(rng.randn(301, 9).astype("float32"))
        idx = jnp.asarray(rng.randint(-1, 301, 413).astype(np.int32))
        out = jax.jit(lambda s, i: pk.gather_rows(s, i, interpret=True))(
            src, idx)
        ref = np.where((np.asarray(idx) >= 0)[:, None],
                       np.asarray(src)[np.maximum(np.asarray(idx), 0)], 0)
        np.testing.assert_allclose(np.asarray(out), ref)


def _build_moe(d_model=16, n_experts=4, topk=2, seed=0):
    paddle.seed(seed)
    experts = [nn.Sequential(nn.Linear(d_model, 32), nn.GELU(),
                             nn.Linear(32, d_model))
               for _ in range(n_experts)]
    return MoELayer(d_model=d_model, experts=experts, gate={"type": "gshard", "top_k": topk})


class TestFusedDispatchParity:
    def _routed(self, layer, x, gate_w, fused):
        def expert_run(expert_in):
            outs = []
            from paddle_tpu.core import tape as tape_mod
            from paddle_tpu.core.tensor import Tensor

            with tape_mod.no_grad():
                for e, expert in enumerate(layer.experts):
                    ye = expert(Tensor(expert_in[e]))
                    outs.append(ye._data)
            return jnp.stack(outs)

        return layer._routed_forward(x, gate_w, expert_run, fused=fused)

    def test_fused_matches_einsum_fwd_and_grads(self):
        layer = _build_moe()
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(24, 16).astype("float32"))
        gw = layer.gate.gate_weight._data

        y_ref, aux_ref = self._routed(layer, x, gw, fused=False)
        y_fused, aux_fused = self._routed(layer, x, gw, fused=True)
        np.testing.assert_allclose(np.asarray(y_fused), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(aux_fused), float(aux_ref),
                                   rtol=1e-6)

        def loss(fused):
            def f(xd, gwd):
                y, aux = self._routed(layer, xd, gwd, fused=fused)
                return (y ** 2).sum() + aux
            return f

        gx_r, gw_r = jax.grad(loss(False), argnums=(0, 1))(x, gw)
        gx_f, gw_f = jax.grad(loss(True), argnums=(0, 1))(x, gw)
        np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_under_jit_one_program(self):
        layer = _build_moe(seed=4)
        rng = np.random.RandomState(5)
        gw = layer.gate.gate_weight._data

        @jax.jit
        def step(xd):
            y, aux = self._routed(layer, xd, gw, fused=True)
            return y.sum() + aux

        for _ in range(3):
            v = step(jnp.asarray(rng.randn(24, 16).astype("float32")))
            assert np.isfinite(float(v))
