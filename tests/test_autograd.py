"""Tape autograd: backward, accumulation, no_grad, paddle.grad, PyLayer.
Gradients checked against analytic results and finite differences (the
reference's OpTest grad-check pattern, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = f(x.copy().reshape(x.shape))
        flat[i] = orig - eps
        lo = f(x.copy().reshape(x.shape))
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * eps)
    return g


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x + 3 * x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_accumulation_over_backwards(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])

    def test_clear_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        x.clear_grad()
        assert x.grad is None

    def test_fanout_accumulates(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        a = x * 2
        b = x * 5
        (a + b).backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_diamond_graph(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x          # 4
        z = y + y * y      # 4 + 16
        z.backward()
        # dz/dy = 1 + 2y = 9; dy/dx = 2x = 4 → 36
        np.testing.assert_allclose(x.grad.numpy(), [36.0])

    def test_matmul_grad(self):
        a_np = np.random.rand(3, 4).astype("float32")
        b_np = np.random.rand(4, 2).astype("float32")
        a = paddle.to_tensor(a_np, stop_gradient=False)
        b = paddle.to_tensor(b_np, stop_gradient=False)
        paddle.matmul(a, b).sum().backward()
        np.testing.assert_allclose(
            a.grad.numpy(), np.ones((3, 2)) @ b_np.T, rtol=1e-5)
        np.testing.assert_allclose(
            b.grad.numpy(), a_np.T @ np.ones((3, 2)), rtol=1e-5)

    def test_broadcast_grad(self):
        x = paddle.to_tensor(np.ones((3, 4), "float32"), stop_gradient=False)
        b = paddle.to_tensor(np.ones((4,), "float32"), stop_gradient=False)
        (x + b).sum().backward()
        np.testing.assert_allclose(b.grad.numpy(), [3, 3, 3, 3])

    def test_nonscalar_needs_grad_tensor(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        with pytest.raises(RuntimeError):
            (x * 2).backward()
        (x * 2).backward(paddle.ones([2]))
        np.testing.assert_allclose(x.grad.numpy(), [2, 2])

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = (x * 2).detach()
        z = y * 3
        assert z.stop_gradient

    def test_finite_difference_softmax(self):
        x_np = np.random.rand(5).astype("float32")
        x = paddle.to_tensor(x_np, stop_gradient=False)
        y = paddle.nn_functional_softmax = x.softmax()
        (y * paddle.to_tensor([1.0, 0, 0, 0, 0])).sum().backward()

        def f(v):
            e = np.exp(v - v.max())
            return (e / e.sum())[0]

        ng = numeric_grad(f, x_np.copy().astype("float64"))
        np.testing.assert_allclose(x.grad.numpy(), ng, atol=1e-3)

    def test_mixed_dtype_no_grad_for_int(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        idx = x.argmax()
        assert idx.stop_gradient

    def test_multi_output_split_grad(self):
        x = paddle.to_tensor(np.arange(6, dtype="float32"),
                             stop_gradient=False)
        a, b = paddle.split(x, 2)
        (a.sum() * 2 + b.sum() * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [2, 2, 2, 3, 3, 3])


class TestNoGrad:
    def test_context(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_decorator(self):
        @paddle.no_grad()
        def f(t):
            return t * 2

        x = paddle.to_tensor([1.0], stop_gradient=False)
        assert f(x).stop_gradient

    def test_enable_grad_nested(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            with paddle.enable_grad():
                y = x * 2
        assert not y.stop_gradient


class TestGradAPI:
    def test_basic(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [4.0])
        assert x.grad is None  # grad() must not touch .grad

    def test_intermediate_input(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * 3
        z = y * y
        (gy,) = paddle.grad(z, y)
        np.testing.assert_allclose(gy.numpy(), [12.0])

    def test_allow_unused(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        u = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        with pytest.raises(RuntimeError):
            paddle.grad(y, [x, u])
        y2 = x * 2
        g = paddle.grad(y2, [x, u], allow_unused=True)
        assert g[1] is None


class TestHooks:
    def test_leaf_hook_scales_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        x.register_hook(lambda g: g * 2)
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_hook_remove(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        h = x.register_hook(lambda g: g * 2)
        h.remove()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0])


class TestPyLayer:
    def test_custom_exp(self):
        from paddle_tpu.autograd import PyLayer

        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = x.exp()
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor
                return dy * y

        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = Exp.apply(x)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), np.exp([1.0]), rtol=1e-5)

    def test_multi_input_output(self):
        from paddle_tpu.autograd import PyLayer

        class MulAdd(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a * b, a + b

            @staticmethod
            def backward(ctx, d_mul, d_add):
                a, b = ctx.saved_tensor
                return d_mul * b + d_add, d_mul * a + d_add

        a = paddle.to_tensor([2.0], stop_gradient=False)
        b = paddle.to_tensor([5.0], stop_gradient=False)
        m, s = MulAdd.apply(a, b)
        (m + s).backward()
        np.testing.assert_allclose(a.grad.numpy(), [6.0])
        np.testing.assert_allclose(b.grad.numpy(), [3.0])
