"""paddle.framework — save/load + glue re-exports.

Ref: python/paddle/framework/ (upstream layout, unverified — mount empty).
"""
from .io import save, load  # noqa: F401
from ..core import get_default_dtype, set_default_dtype  # noqa: F401
from ..core.rng import seed  # noqa: F401
