"""paddle.utils.cpp_extension — JIT-build custom C++ ops (real native path).

Ref: python/paddle/utils/cpp_extension/ (upstream layout, unverified — mount
empty). Paddle compiles user C++/CUDA with pybind into loadable ops. The
TPU-native analog: device math belongs in XLA/Pallas, so custom C++ runs as a
HOST op — `load()` really compiles the sources with g++ into a shared object,
binds the exported C-ABI functions through ctypes, and exposes each as a
callable usable from jitted code via jax.pure_callback (CPU callback island
inside the XLA program).

The C ABI a source must export (one function per op):

    extern "C" void <op>(const float* in, float* out, int64_t n);

elementwise float kernels with identical in/out shape. Richer signatures can
be bound manually from the returned module's `.lib` (a ctypes.CDLL).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from typing import List, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension", "setup",
           "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get(
        "PADDLE_TPU_EXTENSION_DIR",
        os.path.join(os.path.expanduser("~/.cache/paddle_tpu"), "extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name: str, sources: Sequence[str], extra_cflags: Sequence[str],
             build_directory: str = None, verbose: bool = False) -> str:
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    tag = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            tag.update(f.read())
    tag.update(" ".join(extra_cflags).encode())
    so_path = os.path.join(build_dir, f"{name}_{tag.hexdigest()[:12]}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           *extra_cflags, *sources, "-o", so_path]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"cpp_extension build failed (rc={proc.returncode}):\n"
            f"{proc.stderr[-4000:]}")
    return so_path


class _ExtensionModule:
    """load() result: ctypes-backed ops + pure_callback wrappers."""

    def __init__(self, name: str, so_path: str, functions: Sequence[str]):
        self.name = name
        self.so_path = so_path
        self.lib = ctypes.CDLL(so_path)
        for fname in functions:
            cfunc = getattr(self.lib, fname)
            cfunc.restype = None
            cfunc.argtypes = [ctypes.POINTER(ctypes.c_float),
                              ctypes.POINTER(ctypes.c_float),
                              ctypes.c_int64]
            setattr(self, fname, self._wrap(cfunc))

    @staticmethod
    def _wrap(cfunc):
        def host_impl(x: np.ndarray) -> np.ndarray:
            x = np.ascontiguousarray(x, dtype=np.float32)
            out = np.empty_like(x)
            cfunc(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  ctypes.c_int64(x.size))
            return out

        def op(x):
            import jax

            from ..core.tensor import Tensor

            data = x._data if isinstance(x, Tensor) else x
            result = jax.pure_callback(
                host_impl, jax.ShapeDtypeStruct(data.shape, np.float32),
                data, vmap_method="sequential")
            return Tensor(result) if isinstance(x, Tensor) else result

        op.host = host_impl
        return op


def load(name: str, sources: List[str], extra_cxx_flags: List[str] = None,
         extra_cuda_cflags: List[str] = None, functions: List[str] = None,
         build_directory: str = None, verbose: bool = False,
         **kwargs) -> _ExtensionModule:
    """Compile `sources` and return a module exposing `functions`.

    `functions` defaults to [name] (single-op extension)."""
    so_path = _compile(name, sources, extra_cxx_flags or [],
                       build_directory, verbose)
    return _ExtensionModule(name, so_path, functions or [name])


class CppExtension:
    def __init__(self, sources: List[str], *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(sources: List[str], *args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is not supported on the TPU build: device math "
        "belongs in XLA/Pallas kernels (see paddle_tpu.ops.pallas_kernels); "
        "host-side C++ goes through CppExtension/load().")


class BuildExtension:
    """setuptools cmdclass stand-in (no-op shell; load() is the JIT path)."""

    @classmethod
    def with_options(cls, **options):
        return cls


def setup(name: str = None, ext_modules=None, **kwargs):
    """Eagerly build the listed CppExtensions (setup.py analog)."""
    mods = []
    for ext in (ext_modules or []):
        if isinstance(ext, CppExtension):
            mods.append(load(name or "paddle_ext", ext.sources,
                             **ext.kwargs))
    return mods
