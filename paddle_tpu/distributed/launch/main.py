"""Launcher CLI — the fleetrun analog.

Ref: python/paddle/distributed/launch/main.py + controllers/collective.py
(upstream layout, unverified — mount empty). Paddle's controller assigns one
process per GPU; on TPU one controller process per HOST owns all local chips
(jax single-controller), so nproc_per_node defaults to 1 and multi-host jobs
get PADDLE_* env + jax.distributed coordinator wiring. The watch loop keeps
paddle's semantics: abort the job when a rank dies, optional restart budget
(elastic-lite).

Usage:
  python -m paddle_tpu.distributed.launch [--nnodes N] [--node_rank R]
      [--master IP:PORT] [--nproc_per_node M] [--elastic_retries K]
      training_script [script args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["main"]


def _parse():
    p = argparse.ArgumentParser(prog="fleetrun", add_help=True)
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", 1)))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    p.add_argument("--master",
                   default=os.environ.get("PADDLE_MASTER", "127.0.0.1:49170"))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (TPU single-controller: 1)")
    p.add_argument("--elastic_retries", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_RETRIES", 0)),
                   help="restart budget per rank before aborting the job")
    p.add_argument("--log_dir", default=os.environ.get("PADDLE_LOG_DIR"))
    p.add_argument("--elastic_dir",
                   default=os.environ.get("PADDLE_ELASTIC_DIR"),
                   help="heartbeat dir enabling membership/health events")
    p.add_argument("--devices", "--gpus", "--tpus", dest="devices",
                   default=None, help="visible device ids, comma separated")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _rank_env(args, local_rank: int) -> dict:
    world = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    host, port = args.master.rsplit(":", 1)
    endpoints = ",".join(
        f"{host}:{int(port) + i}" for i in range(world))
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ENDPOINTS": endpoints,
        "PADDLE_CURRENT_ENDPOINT": f"{host}:{int(port) + rank}",
        "PADDLE_MASTER": args.master,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(args.nnodes),
    })
    if args.elastic_dir:
        env["PADDLE_ELASTIC_DIR"] = args.elastic_dir
    if args.devices:
        env["FLAGS_selected_tpus"] = args.devices
    return env


def main(argv=None):
    args = _parse()
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs = {}
    retries = {}

    def launch(local_rank: int):
        env = _rank_env(args, local_rank)
        cmd = [sys.executable, args.script] + args.script_args
        stdout = None
        if args.log_dir:
            rank = env["PADDLE_TRAINER_ID"]
            stdout = open(os.path.join(args.log_dir,
                                       f"worker.{rank}.log"), "ab")
        proc = subprocess.Popen(cmd, env=env, stdout=stdout,
                                stderr=subprocess.STDOUT if stdout else None)
        procs[local_rank] = proc
        return proc

    for lr in range(args.nproc_per_node):
        launch(lr)

    def shutdown(signum=None, frame=None):
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in procs.values():
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    # membership/health events (elastic manager) alongside the watch loop
    manager = None
    if args.elastic_dir:
        from ..elastic import ElasticManager

        manager = ElasticManager(
            args.elastic_dir,
            # heartbeats carry GLOBAL ranks; health is about the world size
            np_expected=args.nnodes * args.nproc_per_node)
        for kind in ("join", "dead", "leave", "scale_up", "scale_down"):
            manager.on(kind, lambda ev: print(
                f"[fleetrun][elastic] {ev}", file=sys.stderr))

    # watch loop: paddle's collective controller semantics
    exit_code = 0
    try:
        while procs:
            time.sleep(0.5)
            if manager is not None:
                manager.scan()
            for lr, p in list(procs.items()):
                code = p.poll()
                if code is None:
                    continue
                if code == 0:
                    del procs[lr]
                    continue
                retries[lr] = retries.get(lr, 0) + 1
                if retries[lr] <= args.elastic_retries:
                    print(f"[fleetrun] rank {lr} exited {code}; restart "
                          f"{retries[lr]}/{args.elastic_retries}",
                          file=sys.stderr)
                    launch(lr)
                else:
                    print(f"[fleetrun] rank {lr} failed (exit {code}); "
                          "aborting job", file=sys.stderr)
                    exit_code = code
                    shutdown()
                    return exit_code
    finally:
        if exit_code:
            shutdown()
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
