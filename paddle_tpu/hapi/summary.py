"""paddle.summary + paddle.flops (ref: python/paddle/hapi/model_summary.py,
dynamic_flops.py — upstream layout, unverified — mount empty). Both trace
the net with jax.eval_shape — no FLOPs are spent measuring FLOPs."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit.functional import call_functional, extract_state

__all__ = ["summary", "flops"]


def _trace_with_hooks(net, make_hook, input_size=None, dtypes=None,
                      input=None):
    """Register `make_hook(name)` on every leaf sublayer, run the net once
    abstractly (jax.eval_shape — hooks fire during tracing with exact
    shapes), then remove the hooks."""
    hooks = []
    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name)))
    try:
        if input is not None:
            args = [input] if isinstance(input, Tensor) else list(input)
            datas = [a._data for a in args]
        else:
            if input_size is None:
                raise ValueError("need input_size or input")
            sizes = [input_size] if isinstance(input_size, tuple) else \
                list(input_size)
            dts = dtypes or ["float32"] * len(sizes)
            if isinstance(dts, str):
                dts = [dts] * len(sizes)
            datas = [jnp.zeros([1 if s is None or s == -1 else s
                                for s in size], dtype=dt)
                     for size, dt in zip(sizes, dts)]
        params, buffers = extract_state(net)
        jax.eval_shape(
            lambda p, b, *d: call_functional(net, p, b, d,
                                             training=False)[0],
            params, buffers, *datas)
    finally:
        for h in hooks:
            h.remove()


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []

    def make_hook(name):
        def hook(layer, inputs, outputs):
            outs = outputs if isinstance(outputs, (list, tuple)) else \
                [outputs]
            shapes = [list(o.shape) for o in outs if isinstance(o, Tensor)]
            n_params = sum(
                int(np.prod(p.shape)) for p in layer._parameters.values()
                if p is not None)
            rows.append((name, type(layer).__name__, shapes, n_params))
        return hook

    _trace_with_hooks(net, make_hook, input_size, dtypes, input)

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    w = max([len(r[0]) + len(r[1]) for r in rows] + [30]) + 8
    line = "-" * (w + 40)
    print(line)
    print(f"{'Layer (type)':<{w}}{'Output Shape':<24}{'Param #':>12}")
    print(line)
    for name, typ, shapes, n in rows:
        shape_s = str(shapes[0]) if len(shapes) == 1 else str(shapes)
        print(f"{name + ' (' + typ + ')':<{w}}{shape_s:<24}{n:>12,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False, dtypes=None):
    """Per-layer FLOP estimate (ref: python/paddle/hapi/dynamic_flops.py,
    upstream layout, unverified — mount empty).

    Accounting follows the upstream conventions: a multiply-add counts as
    2 ops for matmul-like layers, normalizations/activations count one op
    per element. `custom_ops` maps a layer class to
    fn(layer, input_shape, output_shape) -> flops and overrides the table.
    """
    from .. import nn

    custom_ops = custom_ops or {}
    rows = []

    def _count(layer, in_shape, out_shape):
        for cls, fn in custom_ops.items():
            if isinstance(layer, cls):
                return int(fn(layer, in_shape, out_shape))
        out_el = int(np.prod(out_shape))
        in_el = int(np.prod(in_shape))
        if isinstance(layer, (nn.Conv1D, nn.Conv2D, nn.Conv3D)):
            w = layer.weight
            kernel_ops = int(np.prod(w.shape[1:]))  # Cin/g * prod(k)
            bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
            return out_el * (2 * kernel_ops - 1 + bias_ops)
        if isinstance(layer, nn.Conv2DTranspose):
            # transpose conv: each INPUT element is scattered through the
            # whole (Cout/g, kh, kw) kernel block — weight is (Cin, Cout/g,
            # kh, kw), so MACs = in_el * prod(w.shape[1:])
            w = layer.weight
            bias_ops = int(np.prod(out_shape[-2:])) if \
                getattr(layer, "bias", None) is not None else 0
            return in_el * 2 * int(np.prod(w.shape[1:])) + bias_ops
        if isinstance(layer, nn.Linear):
            in_f = layer.weight.shape[0]
            bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
            return out_el * (2 * in_f - 1 + bias_ops)
        if isinstance(layer, (nn.BatchNorm, nn.BatchNorm1D, nn.BatchNorm2D,
                              nn.BatchNorm3D, nn.LayerNorm, nn.GroupNorm)):
            return 2 * out_el
        if isinstance(layer, (nn.AvgPool2D, nn.MaxPool2D, nn.AvgPool1D,
                              nn.MaxPool1D, nn.AdaptiveAvgPool2D)):
            return out_el
        if isinstance(layer, (nn.ReLU, nn.ReLU6, nn.GELU, nn.Sigmoid,
                              nn.Tanh, nn.Hardswish, nn.Hardsigmoid,
                              nn.Swish, nn.SiLU, nn.LeakyReLU, nn.Softmax)):
            return out_el
        return 0

    def make_hook(name):
        def hook(layer, inputs, outputs):
            ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            outs = outputs if isinstance(outputs, (list, tuple)) \
                else [outputs]
            in_shape = list(ins[0].shape) if ins and \
                isinstance(ins[0], Tensor) else []
            out_shape = list(outs[0].shape) if outs and \
                isinstance(outs[0], Tensor) else []
            n_params = sum(
                int(np.prod(p.shape)) for p in layer._parameters.values()
                if p is not None)
            rows.append((name, type(layer).__name__, out_shape, n_params,
                         _count(layer, in_shape, out_shape)))
        return hook

    _trace_with_hooks(net, make_hook, input_size, dtypes, inputs)

    total = sum(r[4] for r in rows)
    if print_detail:
        width = max((len(r[0]) for r in rows), default=10) + 2
        print(f"{'Layer':<{width}}{'Type':<18}{'Output':<20}"
              f"{'Params':>12}{'FLOPs':>16}")
        for name, tname, oshape, n_params, fl in rows:
            print(f"{name:<{width}}{tname:<18}{str(oshape):<20}"
                  f"{n_params:>12}{fl:>16}")
        print(f"Total FLOPs: {total}")
    return total
