"""Semi-auto parallel API: ProcessMesh + placements + shard_tensor.

Ref: python/paddle/distributed/auto_parallel/api.py (upstream layout,
unverified — mount empty). Paddle implements sharding propagation, a
partitioner and reshard passes over its IR; on TPU these are XLA GSPMD's job,
so the API is nearly native sugar: ProcessMesh wraps jax.sharding.Mesh,
Shard/Replicate/Partial map to PartitionSpec entries, shard_tensor is
jax.device_put with a NamedSharding, and reshard is device_put to a new one.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "get_mesh", "set_mesh"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    def __init__(self, reduce_type=None):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return "Partial()"


class ProcessMesh:
    """N-D logical process mesh with named dims, backed by jax Mesh."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[List[str]] = None):
        arr = np.asarray(mesh)
        self._shape = list(arr.shape)
        self._process_ids = arr.flatten().tolist()
        self._dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())
        if devs.size < arr.size:
            raise ValueError(
                f"mesh needs {arr.size} devices, have {devs.size}")
        self._jax_mesh = jax.sharding.Mesh(
            devs[arr.flatten()].reshape(arr.shape), tuple(self._dim_names))

    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def mesh(self):
        return np.asarray(self._process_ids).reshape(self._shape)

    def get_dim_size(self, name: str) -> int:
        return self._shape[self._dim_names.index(name)]

    def jax_mesh(self):
        return self._jax_mesh

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"


_GLOBAL_MESH = [None]


def set_mesh(mesh: ProcessMesh):
    _GLOBAL_MESH[0] = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _GLOBAL_MESH[0]


def _to_partition_spec(mesh: ProcessMesh, placements) -> PartitionSpec:
    """placements[i] describes mesh dim i; build the per-tensor-dim spec."""
    if placements is None:
        return PartitionSpec()
    max_dim = -1
    for p in placements:
        if isinstance(p, Shard):
            max_dim = max(max_dim, p.dim)
    entries = [None] * (max_dim + 1)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            name = mesh.dim_names[mesh_dim]
            if entries[p.dim] is None:
                entries[p.dim] = name
            elif isinstance(entries[p.dim], tuple):
                entries[p.dim] = entries[p.dim] + (name,)
            else:
                entries[p.dim] = (entries[p.dim], name)
    return PartitionSpec(*entries)


def shard_tensor(data, mesh: ProcessMesh, placements,
                 dtype=None, place=None, stop_gradient=None):
    """Place a tensor on the mesh with the given placements."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    spec = _to_partition_spec(mesh, placements)
    sharding = NamedSharding(mesh.jax_mesh(), spec)
    t._data = jax.device_put(t._data, sharding)
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements):
    """Re-place onto (possibly different) mesh/placements; XLA moves data."""
    spec = _to_partition_spec(mesh, placements)
    sharding = NamedSharding(mesh.jax_mesh(), spec)
    out = Tensor(jax.device_put(dist_tensor._data, sharding),
                 stop_gradient=dist_tensor.stop_gradient)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard a Layer's params per shard_fn(name, layer, mesh); defaults to
    replicated placement."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for p in sublayer._parameters.values():
                if p is not None:
                    shard_tensor(p, mesh,
                                 [Replicate()] * mesh.ndim)
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    return layer
