"""paddle.linalg namespace (ref: python/paddle/linalg.py — upstream re-
exports tensor.linalg; layout unverified — mount empty). Every function is
a registry op (hand-written jnp or ops.yaml codegen), so eager tape /
static capture / jit all work through the same dispatch; names absent from
the paddle.tensor namespace resolve straight off the registry.
"""
from __future__ import annotations

from .tensor import _make_fn

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "householder_product", "inner", "inv",
    "inverse", "lstsq", "lu", "lu_unpack", "matrix_exp", "matrix_power",
    "matrix_rank", "multi_dot", "norm", "ormqr", "outer",
    "pca_lowrank", "pinv", "qr", "slogdet", "svd_lowrank",
    "solve", "svd", "tensordot", "triangular_solve", "vecdot",
    "vector_norm", "matrix_norm",
]

_OP_NAMES = {name: name for name in __all__
             if name not in ("inv", "vector_norm", "matrix_norm")}
_OP_NAMES["inv"] = "inverse"


_g = globals()
for _name, _opname in _OP_NAMES.items():
    _g[_name] = _make_fn(_opname)
del _g, _name, _opname


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)  # noqa: F821


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)  # noqa: F821

_qr_op = _make_fn("qr")


def qr(x, mode="reduced", name=None):
    """paddle.linalg.qr: (Q, R) for reduced/complete, bare R for 'r'."""
    out = _qr_op(x, mode=mode)
    return out[0] if mode == "r" else out
