"""paddle.onnx — native ONNX exporter (ref: python/paddle/onnx/export.py,
which delegates to the external paddle2onnx package; upstream layout,
unverified — mount empty).

This zero-egress image has no onnx/paddle2onnx toolchain, so the exporter
is self-contained: the layer is traced into a static Program (the same
capture path @to_static uses), each captured op is converted to ONNX
NodeProto by a converter registry, and the ModelProto is serialized with a
minimal protobuf wire-format writer (field numbers from the public
onnx.proto; raw_data little-endian per spec). The artifact is a standard
`.onnx` file loadable by onnxruntime/netron elsewhere.

Covered op set: the MLP/convnet surface (linear, matmul, elementwise,
activations, softmax, reshape/transpose/flatten/concat, conv2d, pooling).
Anything else raises with the op name — no silent partial graphs.
"""
from __future__ import annotations

import struct
from typing import Dict, List

import numpy as np

__all__ = ["export"]


# ------------------------------------------------------- protobuf writer

def _varint(n: int) -> bytes:
    out = b""
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _str(field: int, s: str) -> bytes:
    return _len_delim(field, s.encode())


def _int(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


# ------------------------------------------------------------ onnx protos

_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
          "int64": 7, "bool": 9, "float16": 10, "float64": 11,
          "bfloat16": 16}

# AttributeProto.type enum
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_FLOATS, _AT_INTS = 1, 2, 3, 6, 7


def _attribute(name: str, value) -> bytes:
    body = _str(1, name)
    if isinstance(value, (bool, int)):
        body += _tag(3, 0) + _varint(int(value)) + _int(20, _AT_INT)
    elif isinstance(value, float):
        body += _tag(2, 5) + struct.pack("<f", value) + _int(20, _AT_FLOAT)
    elif isinstance(value, str):
        body += _len_delim(4, value.encode()) + _int(20, _AT_STRING)
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, int) for v in value):
        for v in value:
            body += _tag(8, 0) + _varint(v)
        body += _int(20, _AT_INTS)
    elif isinstance(value, (list, tuple)):
        for v in value:
            body += _tag(7, 5) + struct.pack("<f", float(v))
        body += _int(20, _AT_FLOATS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return body


def _node(op_type: str, inputs: List[str], outputs: List[str],
          name: str = "", attrs: Dict = None) -> bytes:
    body = b""
    for i in inputs:
        body += _str(1, i)
    for o in outputs:
        body += _str(2, o)
    if name:
        body += _str(3, name)
    body += _str(4, op_type)
    for k, v in (attrs or {}).items():
        body += _len_delim(5, _attribute(k, v))
    return body


def _tensor(name: str, arr: np.ndarray) -> bytes:
    dt = _DTYPE.get(str(arr.dtype))
    if dt is None:
        raise TypeError(f"unsupported initializer dtype {arr.dtype}")
    body = b""
    for d in arr.shape:
        body += _tag(1, 0) + _varint(int(d))
    body += _int(2, dt)
    body += _str(8, name)
    little = arr if arr.dtype.byteorder in ("<", "=", "|") else \
        arr.astype(arr.dtype.newbyteorder("<"))
    body += _len_delim(9, np.ascontiguousarray(little).tobytes())
    return body


def _value_info(name: str, shape, dtype: str) -> bytes:
    dims = b""
    for i, d in enumerate(shape):
        if d in (-1, None):
            dims += _len_delim(1, _str(2, f"dyn_{i}"))
        else:
            dims += _len_delim(1, _tag(1, 0) + _varint(int(d)))
    tensor_type = _int(1, _DTYPE[str(dtype)]) + _len_delim(2, dims)
    return _str(1, name) + _len_delim(2, _len_delim(1, tensor_type))


# ---------------------------------------------------------- op converters
#
# each converter: (op, ctx) -> list[bytes NodeProto]; ctx provides fresh
# names and initializer registration for shape constants etc.

class _Ctx:
    def __init__(self, program=None):
        self.program = program
        self.extra_inits: List[bytes] = []
        self._uid = 0

    def var_shape(self, name):
        v = self.program.global_block().vars.get(name)
        return None if v is None else list(v.shape)

    def var_dtype(self, name):
        v = self.program.global_block().vars.get(name)
        return None if v is None else str(v.dtype)

    def fresh(self, base: str) -> str:
        self._uid += 1
        return f"{base}_{self._uid}"

    def add_const(self, arr: np.ndarray, base: str) -> str:
        name = self.fresh(base)
        self.extra_inits.append(_tensor(name, arr))
        return name


def _pos_consts(op):
    """Positional constants from the capture template (e.g. a reshape
    target shape passed positionally rather than as a keyword attr)."""
    return [payload for kind, payload in op.arg_template
            if kind == "const"]


def _attr_or_pos(op, key, idx_from_consts=0, default=None):
    if key in op.attrs:
        return op.attrs[key]
    consts = _pos_consts(op)
    if len(consts) > idx_from_consts:
        return consts[idx_from_consts]
    if default is not None:
        return default
    raise NotImplementedError(
        f"onnx export: op {op.type!r} missing {key!r} (attrs "
        f"{sorted(op.attrs)}, {len(consts)} positional consts)")


def _resolve_args(op, names, defaults):
    """Merge keyword attrs with positional consts: positionals fill the
    first `names` not supplied as keywords, in order (Python call
    semantics — positional-after-keyword is a syntax error upstream)."""
    out = dict(defaults)
    out.update(op.attrs)
    consts = list(_pos_consts(op))
    for n in names:
        if n in op.attrs or not consts:
            continue
        out[n] = consts.pop(0)
    return out


def _op_inputs(op, ctx):
    """Operand names in positional order; scalar/array consts (e.g.
    `x * 2.0`) become float32 initializers so the node stays valid."""
    names = []
    # scalar consts adopt the dtype of the first tensor operand, so mixed
    # int/float graphs stay type-valid ONNX
    var_dt = None
    for kind, payload in op.arg_template:
        if kind == "var":
            var_dt = var_dt or ctx.var_dtype(op.input_names[payload])
    var_dt = var_dt or "float32"
    for kind, payload in op.arg_template:
        if kind == "var":
            names.append(op.input_names[payload])
        elif kind == "const" and isinstance(payload, (int, float, bool,
                                                      np.ndarray)):
            names.append(ctx.add_const(
                np.asarray(payload, np.dtype(var_dt)), "const"))
        else:
            raise NotImplementedError(
                f"onnx export: op {op.type!r} has a non-scalar positional "
                f"constant {payload!r}")
    return names


def _simple(onnx_op, **fixed_attrs):
    def conv(op, ctx):
        return [_node(onnx_op, _op_inputs(op, ctx), op.output_names,
                      attrs=fixed_attrs)]
    return conv


def _cv_linear(op, ctx):
    # y = x @ W (+ b): MatMul then Add
    x, w = op.input_names[0], op.input_names[1]
    bias = op.input_names[2] if len(op.input_names) > 2 else None
    out = op.output_names[0]
    if bias is None:
        return [_node("MatMul", [x, w], [out])]
    mm = ctx.fresh(out + "_mm")
    return [_node("MatMul", [x, w], [mm]),
            _node("Add", [mm, bias], [out])]


def _cv_matmul(op, ctx):
    a = _resolve_args(op, ["transpose_x", "transpose_y"],
                      {"transpose_x": False, "transpose_y": False})
    nodes = []
    x, y = op.input_names[:2]

    def swap_last_two(name):
        shape = ctx.var_shape(name)
        if shape is None:
            raise NotImplementedError(
                f"onnx export: cannot infer rank of {name!r} for matmul "
                "transpose")
        r = len(shape)
        perm = list(range(r - 2)) + [r - 1, r - 2]
        t = ctx.fresh(name + "_t")
        nodes.append(_node("Transpose", [name], [t],
                           attrs={"perm": perm}))
        return t

    if a["transpose_x"]:
        x = swap_last_two(x)
    if a["transpose_y"]:
        y = swap_last_two(y)
    nodes.append(_node("MatMul", [x, y], op.output_names))
    return nodes


def _cv_reshape(op, ctx):
    shape = [int(s) for s in _attr_or_pos(op, "shape")]
    cname = ctx.add_const(np.asarray(shape, np.int64), "reshape_shape")
    return [_node("Reshape", [op.input_names[0], cname], op.output_names)]


def _cv_transpose(op, ctx):
    perm = [int(p) for p in _attr_or_pos(op, "perm")]
    return [_node("Transpose", op.input_names, op.output_names,
                  attrs={"perm": perm})]


def _cv_softmax(op, ctx):
    return [_node("Softmax", op.input_names, op.output_names,
                  attrs={"axis": int(op.attrs.get("axis", -1))})]


def _cv_flatten(op, ctx):
    # paddle flatten(start, stop) merges dims [start..stop] into one;
    # ONNX Flatten is always-2-D, so emit Reshape with the 0-copy/-1
    # target instead (0 = keep dim, single -1 = merged chunk)
    a = _resolve_args(op, ["start_axis", "stop_axis"],
                      {"start_axis": 0, "stop_axis": -1})
    shape = ctx.var_shape(op.input_names[0]) or []
    rank = len(shape)
    start = int(a["start_axis"]) % max(rank, 1)
    stop = int(a["stop_axis"]) % max(rank, 1)
    # 0-copy is positional in the PRE-merge input, so dims AFTER the merged
    # chunk must be written explicitly (their index shifts); dynamic dims
    # there cannot be expressed
    tail = shape[stop + 1:]
    if any(d in (-1, None) for d in tail):
        raise NotImplementedError(
            "onnx export: flatten with dynamic dims after stop_axis")
    target = [0] * start + [-1] + [int(d) for d in tail]
    cname = ctx.add_const(np.asarray(target, np.int64), "flatten_shape")
    return [_node("Reshape", [op.input_names[0], cname], op.output_names)]


def _cv_concat(op, ctx):
    axis = int(_attr_or_pos(op, "axis", 0, default=0))
    return [_node("Concat", op.input_names, op.output_names,
                  attrs={"axis": axis})]


def _pair(v):
    return [int(v), int(v)] if isinstance(v, int) else [int(i) for i in v]


def _onnx_pads(p):
    """paddle [ph, pw] or [top, bottom, left, right] -> ONNX
    [x1_begin, x2_begin, x1_end, x2_end] = [top, left, bottom, right]."""
    p = _pair(p)
    if len(p) == 2:
        return [p[0], p[1], p[0], p[1]]
    if len(p) == 4:
        t, b, l, r = p
        return [t, l, b, r]
    raise NotImplementedError(f"onnx export: padding {p!r}")


def _cv_conv2d(op, ctx):
    a = op.attrs
    if a.get("data_format", "NCHW") != "NCHW":
        raise NotImplementedError("onnx export: conv2d NCHW only")
    s, d = _pair(a.get("stride", 1)), _pair(a.get("dilation", 1))
    p = a.get("padding", 0)
    if isinstance(p, str):
        raise NotImplementedError("onnx export: string conv padding")
    return [_node("Conv", op.input_names, op.output_names,
                  attrs={"strides": s, "dilations": d,
                         "pads": _onnx_pads(p),
                         "group": int(a.get("groups", 1))})]


def _cv_pool(onnx_op):
    def conv(op, ctx):
        a = _resolve_args(
            op, ["kernel_size", "stride", "padding", "ceil_mode"],
            {"stride": None, "padding": 0, "ceil_mode": False})
        k = _pair(a["kernel_size"])
        s = _pair(a["stride"]) if a.get("stride") is not None else k
        attrs = {"kernel_shape": k, "strides": s,
                 "pads": _onnx_pads(a.get("padding", 0)),
                 "ceil_mode": int(bool(a.get("ceil_mode", False)))}
        if a.get("data_format", "NCHW") != "NCHW":
            raise NotImplementedError("onnx export: pooling NCHW only")
        if onnx_op == "AveragePool":
            attrs["count_include_pad"] = int(
                bool(a.get("count_include_pad", True)))
        return [_node(onnx_op, op.input_names[:1], op.output_names[:1],
                      attrs=attrs)]
    return conv


_CONVERTERS = {
    "linear": _cv_linear,
    "matmul": _cv_matmul,
    "add": _simple("Add"), "subtract": _simple("Sub"),
    "multiply": _simple("Mul"), "divide": _simple("Div"),
    "relu": _simple("Relu"), "sigmoid": _simple("Sigmoid"),
    "tanh": _simple("Tanh"), "exp": _simple("Exp"),
    "sqrt": _simple("Sqrt"), "abs": _simple("Abs"),
    "neg": _simple("Neg"), "erf": _simple("Erf"),
    "softmax": _cv_softmax,
    "reshape": _cv_reshape,
    "transpose": _cv_transpose,
    "flatten": _cv_flatten,
    "concat": _cv_concat,
    "conv2d": _cv_conv2d,
    "max_pool2d": _cv_pool("MaxPool"),
    "avg_pool2d": _cv_pool("AveragePool"),
}


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace `layer` and write `path + '.onnx'` (upstream name contract).

    input_spec: list of InputSpec/Tensors defining the feed signature.
    Returns the written file path.
    """
    from . import static
    from .core.tensor import Tensor
    from .jit.api import InputSpec

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")
    if int(opset_version) != 13:
        raise NotImplementedError(
            "paddle.onnx.export emits opset-13 semantics; pass "
            "opset_version=13 (mislabeling the artifact would change "
            "Reshape/Softmax behavior in other runtimes)")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            specs.append(s)
        else:
            arr = np.asarray(s.numpy() if isinstance(s, Tensor) else s)
            specs.append(InputSpec(arr.shape, str(arr.dtype)))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    main = static.Program()
    static.enable_static()
    try:
        with static.program_guard(main, static.Program()):
            feeds = [static.data(s.name or f"input_{i}", list(s.shape),
                                 s.dtype) for i, s in enumerate(specs)]
            result = layer(*feeds)
    finally:
        static.disable_static()
        if was_training and hasattr(layer, "train"):
            layer.train()

    outputs = result if isinstance(result, (list, tuple)) else [result]
    if not outputs:
        raise ValueError("traced layer produced no outputs")

    ctx = _Ctx(main)
    nodes: List[bytes] = []
    for op in main.global_block().ops:
        conv = _CONVERTERS.get(op.type)
        if conv is None:
            raise NotImplementedError(
                f"onnx export: no converter for op {op.type!r}; covered: "
                f"{sorted(_CONVERTERS)}")
        nodes.extend(conv(op, ctx))

    graph = b""
    for n in nodes:
        graph += _len_delim(1, n)
    graph += _str(2, type(layer).__name__)
    for name, t in sorted(main.refs.items()):
        graph += _len_delim(5, _tensor(name, np.asarray(t.numpy())))
    for t in ctx.extra_inits:
        graph += _len_delim(5, t)
    for v, s in zip(main._data_vars, specs):
        graph += _len_delim(11, _value_info(v.name, s.shape, s.dtype))
    for o in outputs:
        graph += _len_delim(12, _value_info(o.name, list(o.shape),
                                            str(o.dtype)))

    model = _int(1, 8)                      # ir_version 8
    model += _str(2, "paddle_tpu")          # producer_name
    model += _len_delim(7, graph)
    model += _len_delim(8, _str(1, "") + _int(2, int(opset_version)))

    out_path = str(path) if str(path).endswith(".onnx") \
        else str(path) + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
