"""ResNet-50 data-parallel throughput (BASELINE.json config #2: Fleet
DP + AMP O2, images/sec/device).

Runs on whatever devices are visible: the real chip(s), or the hermetic
8-fake-device CPU mesh (--cpu; conftest-style XLA_FLAGS forced here).
The train step is the product shape: functional forward + CE + SGD
momentum under amp O2 autocast, batch sharded over the dp mesh axis via
NamedSharding, params replicated — XLA inserts the gradient psum.

Note (verify-skill finding): conv models do not finish compiling through
the axon remote-compile relay; on real hardware run this from a TPU VM.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the hermetic 8-fake-device CPU mesh")
    ap.add_argument("--batch", type=int, default=None,
                    help="global batch (default: 32/device on TPU, "
                    "16 total on CPU)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=None,
                    help="default 224 on TPU, 64 on CPU smoke")
    args = ap.parse_args()

    if args.cpu:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.core import tape as tape_mod
    from paddle_tpu.jit.functional import call_functional, extract_state
    from paddle_tpu.vision import models as V

    devs = jax.devices()
    n_dev = len(devs)
    on_tpu = devs[0].platform == "tpu"
    size = args.image_size if args.image_size is not None else (
        224 if on_tpu else 64)
    batch = args.batch if args.batch is not None else (
        32 * n_dev if on_tpu else 16)
    batch -= batch % n_dev
    if batch <= 0 or size <= 0:
        ap.error(f"batch must be >= device count ({n_dev}) and "
                 "image-size positive")
    print(f"[resnet-dp] devices={n_dev} ({devs[0].platform}), "
          f"global batch={batch}, image={size}", file=sys.stderr)

    paddle.seed(0)
    LR = 0.1
    model = V.resnet50(num_classes=1000)
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=LR, momentum=0.9,
                                    parameters=model.parameters())
    params, buffers = extract_state(model)
    opt_state = opt.functional_state(params)

    mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
    data_sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("dp"))
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    def train_step(params, buffers, opt_state, images, labels):
        def loss_of(p):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                logits, new_buffers = call_functional(
                    model, p, buffers, (images,), training=True)
            with tape_mod.no_grad():
                loss = paddle.nn.functional.cross_entropy(
                    paddle.Tensor(logits), paddle.Tensor(labels))
            return loss._data, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.functional_step(params, grads, opt_state,
                                                  jnp.float32(LR),
                                                  jnp.int32(1))
        return loss, new_params, new_buffers, new_opt

    jitted = jax.jit(train_step, donate_argnums=(0, 1, 2))

    put = lambda t: jax.device_put(t, repl)  # noqa: E731
    params = jax.tree_util.tree_map(put, params)
    buffers = jax.tree_util.tree_map(put, buffers)
    opt_state = jax.tree_util.tree_map(put, opt_state)

    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.randn(batch, 3, size, size), jnp.float32), data_sh)
    labels = jax.device_put(
        jnp.asarray(rng.randint(0, 1000, (batch,))), data_sh)

    t0 = time.perf_counter()
    loss, params, buffers, opt_state = jitted(params, buffers, opt_state,
                                              images, labels)
    float(np.asarray(loss))
    print(f"[resnet-dp] compile+first step {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss, params, buffers, opt_state = jitted(
            params, buffers, opt_state, images, labels)
    final = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    ips = batch * args.steps / dt
    print(f"[resnet-dp] {ips:,.1f} img/s total, {ips/n_dev:,.1f} "
          f"img/s/device, loss {final:.3f}", file=sys.stderr)
    import json

    print(json.dumps({"metric": "resnet50_dp_images_per_sec",
                      "value": round(ips, 1), "unit": "img/s",
                      "devices": n_dev, "batch": batch,
                      "image_size": size,
                      "amp": "O2", "loss": final}))


if __name__ == "__main__":
    main()
