"""paddle.static save/load_inference_model — the static inference I/O seam.

Ref: python/paddle/static/io.py (upstream layout, unverified — mount empty).
Paddle prunes the Program to feed→fetch, serializes the ProgramDesc protobuf
plus persistables. Here the pruned Program is lowered once through jax.export
to a serialized StableHLO module (batch dims symbolic, so any batch size runs)
plus a weights pickle — the same on-disk format as paddle_tpu.jit.save, so one
inference artifact serves both APIs. load_inference_model returns
[program, feed_names, fetch_vars] where `program` is a LoadedInferenceModel
the Executor runs directly (the predictor path: XLA is the whole
analysis+runtime).
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .program import Program, Variable, default_main_program

__all__ = ["save_inference_model", "load_inference_model",
           "serialize_program", "deserialize_program",
           "LoadedInferenceModel", "normalize_program"]

_META = "meta.json"
_HLO = "module.stablehlo"
_WEIGHTS = "weights.pkl"


def normalize_program(program: Program, feed_vars, fetch_vars) -> Program:
    """Prune/validate for inference: training-only state (minimize hooks)
    dropped. The SSA op list is already feed→fetch ordered."""
    for v in list(feed_vars) + list(fetch_vars):
        if not isinstance(v, Variable):
            raise TypeError(
                f"feed_vars/fetch_vars must be static Variables, got "
                f"{type(v).__name__}")
    return program.clone(for_test=True)


def _replay_fn(program: Program, feed_names: List[str],
               fetch_names: List[str]):
    from .executor import _replay

    def pure(param_arrays: Dict[str, jax.Array], *feeds):
        env = dict(param_arrays)
        env.update(dict(zip(feed_names, feeds)))
        _replay(program, env)
        return [env[n] for n in fetch_names]

    return pure


def save_inference_model(path_prefix: str, feed_vars: Sequence[Variable],
                         fetch_vars: Sequence[Variable], executor=None,
                         program: Program = None, **kwargs) -> None:
    """Export the feed→fetch slice of `program` as StableHLO + weights."""
    feed_vars = list(feed_vars) if isinstance(
        feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = list(fetch_vars) if isinstance(
        fetch_vars, (list, tuple)) else [fetch_vars]
    program = normalize_program(program or default_main_program(),
                                feed_vars, fetch_vars)

    feed_names = [v.name for v in feed_vars]
    fetch_names = [v.name for v in fetch_vars]
    param_arrays = {n: t._data for n, t in program.refs.items()}
    pure = _replay_fn(program, feed_names, fetch_names)

    from jax import export as jax_export

    # dynamic (-1) dims become export symbols: the saved module accepts any
    # batch size, matching paddle's feed-dim semantics
    scope = jax_export.SymbolicScope()
    n_sym = 0
    abstract = []
    for v in feed_vars:
        dims = []
        for d in v.shape:
            if d in (-1, None):
                dims.append(jax_export.symbolic_shape(
                    f"b{n_sym}", scope=scope)[0])
                n_sym += 1
            else:
                dims.append(int(d))
        abstract.append(jax.ShapeDtypeStruct(tuple(dims), v.dtype))

    exported = jax_export.export(jax.jit(pure))(param_arrays, *abstract)
    blob = exported.serialize()
    hlo_text = jax.jit(pure).lower(param_arrays, *abstract).as_text()

    # bfloat16 variant: same call signature (f32 params/feeds, cast
    # in-module, outputs cast back) so ONE weights file serves both;
    # inference.Config precision=Bfloat16/Half executes THIS module —
    # the toggle changes the artifact, not just a recorded flag
    def _cast_tree(t, dt):
        return jax.tree_util.tree_map(
            lambda a: a.astype(dt)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, t)

    def pure_bf16(params, *feeds):
        outs = pure(_cast_tree(params, jnp.bfloat16),
                    *[f.astype(jnp.bfloat16)
                      if jnp.issubdtype(f.dtype, jnp.floating) else f
                      for f in feeds])
        return [o.astype(jnp.float32)
                if jnp.issubdtype(o.dtype, jnp.floating) else o
                for o in outs]

    blob_bf16 = jax_export.export(jax.jit(pure_bf16))(
        param_arrays, *abstract).serialize()

    out_dir = str(path_prefix) + ".tpu_model"
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, _HLO), "w") as f:
        f.write(hlo_text)
    with open(os.path.join(out_dir, _HLO + ".bin"), "wb") as f:
        f.write(blob)
    with open(os.path.join(out_dir, _HLO + ".bf16.bin"), "wb") as f:
        f.write(blob_bf16)
    with open(os.path.join(out_dir, _WEIGHTS), "wb") as f:
        pickle.dump({"params": {k: np.asarray(v)
                                for k, v in param_arrays.items()}}, f,
                    protocol=4)
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump({
            "format": "stablehlo+pickle", "version": 1, "kind": "inference",
            "feed": [{"name": v.name, "shape": list(v.shape),
                      "dtype": str(v.dtype)} for v in feed_vars],
            "fetch": [{"name": v.name, "shape": list(v.shape),
                       "dtype": str(v.dtype)} for v in fetch_vars],
        }, f, indent=2)


class LoadedInferenceModel:
    """Stands in for the inference Program after load: executes the
    deserialized StableHLO module. Executor.run dispatches on this type."""

    def __init__(self, out_dir: str, precision: str = "float32"):
        self._dir = out_dir
        self.precision = precision
        with open(os.path.join(out_dir, _META)) as f:
            self.meta = json.load(f)
        with open(os.path.join(out_dir, _WEIGHTS), "rb") as f:
            w = pickle.load(f)
        self._params = {k: jnp.asarray(v) for k, v in w["params"].items()}
        blob_path = os.path.join(out_dir, _HLO + ".bin")
        if precision in ("bfloat16", "float16"):
            # the low-precision module exported next to the f32 one (same
            # signature: casts ride inside the module)
            lp = os.path.join(out_dir, _HLO + ".bf16.bin")
            if os.path.exists(lp):
                blob_path = lp
            else:
                raise FileNotFoundError(
                    f"artifact at {out_dir} predates the bf16 variant; "
                    "re-save with save_inference_model to use "
                    f"precision={precision!r}")
        with open(blob_path, "rb") as f:
            blob = f.read()
        from jax import export as jax_export

        self._exported = jax_export.deserialize(blob)
        self.feed_names = [d["name"] for d in self.meta["feed"]]
        self.fetch_names = [d["name"] for d in self.meta["fetch"]]

    def run(self, feed: Dict) -> List[jax.Array]:
        feeds = []
        for name in self.feed_names:
            if name not in feed:
                raise KeyError(f"inference model needs feed {name!r}; got "
                               f"{sorted(feed)}")
            v = feed[name]
            v = v._data if isinstance(v, Tensor) else jnp.asarray(
                np.asarray(v))
            feeds.append(v)
        return list(self._exported.call(self._params, *feeds))

    def __repr__(self):
        return (f"LoadedInferenceModel(feed={self.feed_names}, "
                f"fetch={self.fetch_names})")


class _FetchTarget:
    """Fetch handle with the saved var's name/shape/dtype (Variable-shaped)."""

    def __init__(self, d):
        self.name = d["name"]
        self.shape = d["shape"]
        self.dtype = np.dtype(d["dtype"])


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] (paddle contract)."""
    out_dir = str(path_prefix) + ".tpu_model"
    if not os.path.isdir(out_dir):
        raise FileNotFoundError(out_dir)
    model = LoadedInferenceModel(out_dir)
    fetch_targets = [_FetchTarget(d) for d in model.meta["fetch"]]
    return [model, model.feed_names, fetch_targets]


def serialize_program(program: Program = None) -> bytes:
    """Pickle the op-list IR (no weights) — ProgramDesc bytes analog."""
    program = program or default_main_program()
    block = program.global_block()
    closures = [op.type for op in block.ops
                if getattr(op, "fn", None) is not None]
    if closures:
        raise ValueError(
            f"program contains closure-captured ops {sorted(set(closures))} "
            "(e.g. Variable slicing) whose functions cannot be serialized; "
            "express them through registered ops (slice/gather) to save "
            "this program")
    return pickle.dumps({
        "ops": [(op.type, op.input_names, op.output_names, op.attrs,
                 op.arg_template) for op in block.ops],
        "vars": {n: (v.shape, str(v.dtype), v.persistable, v.is_data)
                 for n, v in block.vars.items()},
    }, protocol=4)


def deserialize_program(blob: bytes) -> Program:
    from .program import OpDesc

    d = pickle.loads(blob)
    p = Program()
    block = p.global_block()
    for n, (shape, dtype, persistable, is_data) in d["vars"].items():
        block.create_var(name=n, shape=shape, dtype=dtype,
                         persistable=persistable, is_data=is_data)
    for t, ins, outs, attrs, tmpl in d["ops"]:
        block.append_op(OpDesc(t, ins, outs, attrs, tmpl))
    return p
