"""Custom-device plugin seam (ref: paddle/phi/backends/custom/custom_device.cc
+ python/paddle/device/__init__.py CustomPlace plumbing, upstream layout,
unverified — mount empty).

Paddle's CustomDevice loads a vendor runtime .so implementing its C device
API. The TPU-native equivalent of "bring your own accelerator runtime" is a
PJRT plugin: a vendor ships a PJRT C-API library, and the framework
registers it with the jax runtime — every layer above (ops, jit, meshes,
collectives) works unchanged because XLA talks PJRT, not device specifics.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["register_custom_device", "list_custom_devices",
           "is_custom_device_registered"]

_REGISTERED: Dict[str, str] = {}


def register_custom_device(device_type: str,
                           library_path: Optional[str] = None,
                           priority: int = 400,
                           options: Optional[Dict] = None) -> None:
    """Register a PJRT plugin as a paddle custom device.

    `library_path` points at the vendor's PJRT C-API shared library (the
    CustomDevice runtime .so analog). Must run before any jax computation
    initializes the backends; select it with
    ``paddle.device.set_device(device_type)`` /
    ``JAX_PLATFORMS=<device_type>``.
    """
    if not device_type or not device_type.isidentifier():
        raise ValueError(f"invalid custom device name {device_type!r}")
    if device_type in _REGISTERED:
        raise ValueError(
            f"custom device {device_type!r} is already registered "
            f"(library: {_REGISTERED[device_type]})")
    if library_path is None:
        raise ValueError(
            "register_custom_device requires library_path to the vendor's "
            "PJRT C-API shared library")
    if not os.path.exists(library_path):
        raise FileNotFoundError(
            f"PJRT plugin library not found: {library_path}")
    from jax._src import xla_bridge as xb

    xb.register_plugin(device_type, library_path=library_path,
                       priority=priority, options=options)
    _REGISTERED[device_type] = library_path


def list_custom_devices() -> List[str]:
    """Names of custom devices registered through this seam."""
    return sorted(_REGISTERED)


def is_custom_device_registered(device_type: str) -> bool:
    return device_type in _REGISTERED


# ------------------------------------------------------- custom runtime API
#
# The second half of the plugin seam (ref: paddle/phi/capi + the
# test/custom_runtime "custom_cpu" plugin): a vendor RUNTIME .so
# implementing the C `cd_*` surface (init, device memory, h2d/d2h/d2d
# copies, streams/events, stats). Compute on TPU-class devices rides PJRT
# (register_custom_device above); this API covers the runtime half and is
# exercised end-to-end in CI by the in-tree custom_cpu reference plugin.

class CustomDeviceRuntime:
    """ctypes driver over a loaded `cd_*` runtime library."""

    def __init__(self, device_type: str, library_path: str):
        import ctypes

        self.device_type = device_type
        self.library_path = library_path
        lib = ctypes.CDLL(library_path)
        self._lib = lib
        c = ctypes
        lib.cd_init.restype = c.c_int
        lib.cd_device_count.restype = c.c_int
        lib.cd_device_name.restype = c.c_char_p
        lib.cd_malloc.restype = c.c_void_p
        lib.cd_malloc.argtypes = [c.c_size_t]
        lib.cd_free.argtypes = [c.c_void_p]
        for fn in ("cd_memcpy_h2d", "cd_memcpy_d2h", "cd_memcpy_d2d"):
            f = getattr(lib, fn)
            f.restype = c.c_int
            f.argtypes = [c.c_void_p, c.c_void_p, c.c_size_t]
        lib.cd_stream_create.restype = c.c_void_p
        lib.cd_stream_destroy.argtypes = [c.c_void_p]
        lib.cd_stream_synchronize.restype = c.c_int
        lib.cd_stream_synchronize.argtypes = [c.c_void_p]
        lib.cd_event_create.restype = c.c_void_p
        lib.cd_event_destroy.argtypes = [c.c_void_p]
        lib.cd_event_record.restype = c.c_int
        lib.cd_event_record.argtypes = [c.c_void_p, c.c_void_p]
        lib.cd_event_synchronize.restype = c.c_int
        lib.cd_event_synchronize.argtypes = [c.c_void_p]
        lib.cd_allocated_bytes.restype = c.c_int64
        lib.cd_peak_allocated_bytes.restype = c.c_int64
        if lib.cd_init() != 0:
            raise RuntimeError(f"{device_type}: cd_init failed")

    # ------------------------------------------------------------- queries
    def device_count(self) -> int:
        return int(self._lib.cd_device_count())

    def device_name(self) -> str:
        return self._lib.cd_device_name().decode()

    def memory_allocated(self) -> int:
        return int(self._lib.cd_allocated_bytes())

    def max_memory_allocated(self) -> int:
        return int(self._lib.cd_peak_allocated_bytes())

    # ------------------------------------------------------------- buffers
    def to_device(self, array) -> "DeviceBuffer":
        """H2D: allocate on the plugin device and copy the host array in."""
        import ctypes

        import numpy as np

        arr = np.ascontiguousarray(array)
        ptr = self._lib.cd_malloc(arr.nbytes)
        if not ptr and arr.nbytes:
            raise MemoryError(f"{self.device_type}: cd_malloc failed")
        if arr.nbytes:
            rc = self._lib.cd_memcpy_h2d(
                ptr, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)
            if rc != 0:
                self._lib.cd_free(ptr)
                raise RuntimeError(f"{self.device_type}: h2d copy failed")
        return DeviceBuffer(self, ptr, arr.shape, arr.dtype, arr.nbytes)

    def to_host(self, buf: "DeviceBuffer"):
        """D2H: copy a device buffer back into a fresh numpy array."""
        import ctypes

        import numpy as np

        if buf.ptr is None and buf.nbytes:
            raise RuntimeError("to_host on a freed DeviceBuffer")
        out = np.empty(buf.shape, buf.dtype)
        if buf.nbytes:
            rc = self._lib.cd_memcpy_d2h(
                out.ctypes.data_as(ctypes.c_void_p), buf.ptr, buf.nbytes)
            if rc != 0:
                raise RuntimeError(f"{self.device_type}: d2h copy failed")
        return out

    # ------------------------------------------------------- streams/events
    def stream(self):
        return _PluginStream(self)


class DeviceBuffer:
    """A plugin-device allocation; freed through the plugin on GC."""

    def __init__(self, rt: CustomDeviceRuntime, ptr, shape, dtype, nbytes):
        self._rt = rt
        self.ptr = ptr
        self.shape = tuple(shape)
        self.dtype = dtype
        self.nbytes = nbytes

    def copy_(self, other: "DeviceBuffer"):
        if self.ptr is None or other.ptr is None:
            raise RuntimeError("d2d copy on a freed DeviceBuffer")
        if self.nbytes != other.nbytes:
            raise ValueError(
                f"d2d copy size mismatch: {self.nbytes} vs {other.nbytes}")
        rc = self._rt._lib.cd_memcpy_d2d(self.ptr, other.ptr, self.nbytes)
        if rc != 0:
            raise RuntimeError("d2d copy failed")
        return self

    def numpy(self):
        return self._rt.to_host(self)

    def free(self):
        if self.ptr:
            self._rt._lib.cd_free(self.ptr)
            self.ptr = None

    def __del__(self):
        try:
            self.free()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class _PluginStream:
    def __init__(self, rt: CustomDeviceRuntime):
        self._rt = rt
        self._s = rt._lib.cd_stream_create()

    def synchronize(self):
        if self._rt._lib.cd_stream_synchronize(self._s) != 0:
            raise RuntimeError("stream synchronize failed")

    def record_event(self):
        ev = self._rt._lib.cd_event_create()
        if not ev:
            raise RuntimeError("cd_event_create failed")
        if self._rt._lib.cd_event_record(ev, self._s) != 0:
            self._rt._lib.cd_event_destroy(ev)
            raise RuntimeError("cd_event_record failed")
        return _PluginEvent(self._rt, ev)

    def destroy(self):
        if self._s:
            self._rt._lib.cd_stream_destroy(self._s)
            self._s = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:  # noqa: BLE001
            pass


class _PluginEvent:
    def __init__(self, rt, ev):
        self._rt = rt
        self._ev = ev

    def synchronize(self):
        if self._rt._lib.cd_event_synchronize(self._ev) != 0:
            raise RuntimeError("cd_event_synchronize failed")

    def __del__(self):
        try:
            if self._ev:
                self._rt._lib.cd_event_destroy(self._ev)
                self._ev = None
        except Exception:  # noqa: BLE001
            pass


_RUNTIMES: Dict[str, CustomDeviceRuntime] = {}


def load_custom_device_runtime(device_type: str,
                               library_path: Optional[str] = None
                               ) -> CustomDeviceRuntime:
    """Load a vendor runtime .so implementing the `cd_*` C API and register
    it as a custom device runtime. With library_path=None and device_type
    'custom_cpu', the in-tree reference plugin is JIT-compiled — the
    upstream test/custom_runtime custom_cpu analog."""
    if device_type in _RUNTIMES:
        cached = _RUNTIMES[device_type]
        if library_path is not None and library_path != cached.library_path:
            raise ValueError(
                f"{device_type!r} already loaded from "
                f"{cached.library_path}; refusing to silently ignore "
                f"{library_path}")
        return cached
    if library_path is None:
        if device_type != "custom_cpu":
            raise ValueError(
                "library_path is required for non-reference plugins")
        from ..utils.cpp_extension import _compile

        src = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "core", "native",
            "custom_cpu_plugin.cc")
        library_path = _compile("custom_cpu_plugin", [src], [])
    rt = CustomDeviceRuntime(device_type, library_path)
    _RUNTIMES[device_type] = rt
    return rt


def get_custom_device_runtime(device_type: str) -> CustomDeviceRuntime:
    if device_type not in _RUNTIMES:
        raise KeyError(f"no runtime loaded for {device_type!r}; call "
                       "load_custom_device_runtime first")
    return _RUNTIMES[device_type]


__all__ += ["CustomDeviceRuntime", "DeviceBuffer",
            "load_custom_device_runtime", "get_custom_device_runtime"]
