"""paddle.hub — load models from a local hubconf (ref: python/paddle/hub.py,
upstream layout, unverified — mount empty).

This environment has no network egress, so only the `source='local'` path is
functional; github/gitee sources raise with a clear message instead of
hanging on a download.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_entry_module(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} found in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source: str):
    if source not in ("local",):
        raise RuntimeError(
            f"paddle.hub source {source!r} needs network access, which this "
            "environment does not have; clone the repo and use "
            "source='local' with its directory path")


def list(repo_dir: str, source: str = "local", force_reload: bool = False):
    """Entrypoint names exported by repo_dir/hubconf.py."""
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False):
    """Docstring of one hubconf entrypoint."""
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"{model!r} not found in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model).__doc__


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    """Instantiate a hubconf entrypoint: load('path/to/repo', 'resnet18')."""
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"{model!r} not found in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model)(**kwargs)
