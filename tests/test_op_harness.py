"""Op unit tests through the OpTest harness (SURVEY §4 row 1): every op
listed here runs eager + static + jit against a NumPy reference, analytic
grads vs finite differences, and a bf16 forward sweep."""
import numpy as np
import pytest

from op_test import OpTest

R = np.random.RandomState(0)
A = R.randn(3, 4).astype(np.float32)
B = R.randn(3, 4).astype(np.float32) + 2.5   # positive-ish for log/sqrt
C = R.rand(3, 4).astype(np.float32) * 0.8 + 0.1
M1 = R.randn(3, 4).astype(np.float32)
M2 = R.randn(4, 5).astype(np.float32)


def softmax_ref(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


CASES = [
    ("add", lambda x, y: x + y, [A, B], {}),
    ("subtract", lambda x, y: x - y, [A, B], {}),
    ("multiply", lambda x, y: x * y, [A, B], {}),
    ("divide", lambda x, y: x / y, [A, np.abs(B) + 1.0], {}),
    ("maximum", lambda x, y: np.maximum(x, y), [A, B], {}),
    ("minimum", lambda x, y: np.minimum(x, y), [A, B], {}),
    ("exp", np.exp, [A * 0.5], {}),
    ("log", np.log, [np.abs(B) + 0.5], {}),
    ("sqrt", np.sqrt, [np.abs(B) + 0.5], {}),
    ("rsqrt", lambda x: 1 / np.sqrt(x), [np.abs(B) + 0.5], {}),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), [A], {}),
    ("tanh", np.tanh, [A], {}),
    ("abs", np.abs, [A + 0.05], {}),          # keep away from the kink
    ("square", np.square, [A], {}),
    ("reciprocal", lambda x: 1 / x, [np.abs(B) + 1.0], {}),
    ("erf", None, [A], {}),                   # ref filled below (scipy)
    ("sin", np.sin, [A], {}),
    ("cos", np.cos, [A], {}),
    ("atan", np.arctan, [A], {}),
    ("logit", None, [C], {}),
    ("matmul", lambda x, y: x @ y, [M1, M2], {}),
    ("softmax", softmax_ref, [A], {"axis": -1}),
    ("mean", lambda x: np.mean(x), [A], {}),
    ("sum", lambda x, axis: np.sum(x, axis=axis), [A], {"axis": 1}),
    ("logsumexp", None, [A], {}),
    ("clip", lambda x, min, max: np.clip(x, min, max),  # noqa: A002
     [A], {"min": -0.5, "max": 0.5}),
    ("transpose", lambda x, perm: np.transpose(x, perm), [A],
     {"perm": [1, 0]}),
    ("reshape", lambda x, shape: np.reshape(x, shape), [A],
     {"shape": [4, 3]}),
    ("lerp", lambda x, y, weight: x + weight * (y - x), [A, B],
     {"weight": 0.3}),
    ("stanh", None, [A], {}),
]


def _fill_refs():
    import scipy.special as sp

    refs = {
        "erf": lambda x: sp.erf(x),
        "logit": lambda x: np.log(x / (1 - x)),
        "logsumexp": lambda x: sp.logsumexp(x),
        "stanh": lambda x, scale_a=0.67, scale_b=1.7159:
            scale_b * np.tanh(scale_a * x),
    }
    out = []
    for name, ref, inputs, kwargs in CASES:
        out.append((name, ref or refs[name], inputs, kwargs))
    return out


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs(), ids=[c[0] for c in CASES])
def test_op(name, ref, inputs, kwargs):
    grad_free = {"clip"}   # kink at the clip boundary breaks fin-diff rows
    OpTest(name, ref, inputs, kwargs,
           check_grad=name not in grad_free).run()


D = np.abs(R.randn(3, 4)).astype(np.float32) + 0.5


def _softplus(x):
    return np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)


CASES2 = [
    ("elementwise_pow", lambda x, y: x ** y, [D, np.full((3, 4), 2.0,
                                                         np.float32)], {}),
    ("atan2", np.arctan2, [A, B], {}),
    ("hypot", np.hypot, [A, B], {}),
    ("heaviside", np.heaviside, [A, D], {}),
    ("copysign", np.copysign, [D, A], {}),
    ("logaddexp", np.logaddexp, [A, B], {}),
    ("relu", lambda x: np.maximum(x, 0), [A + 0.05], {}),
    ("relu6", lambda x: np.clip(x, 0, 6), [A * 4 + 0.05], {}),
    ("softplus", _softplus, [A], {}),
    ("mish", lambda x: x * np.tanh(_softplus(x)), [A], {}),
    ("hardtanh", lambda x: np.clip(x, -1, 1), [A * 2 + 0.03], {}),
    ("leaky_relu", lambda x, negative_slope=0.01:
        np.where(x > 0, x, negative_slope * x), [A + 0.05], {}),
    ("elu", lambda x, alpha=1.0:
        np.where(x > 0, x, alpha * (np.exp(x) - 1)), [A + 0.05], {}),
    ("selu", None, [A + 0.05], {}),
    ("gelu", None, [A], {}),
    ("silu", lambda x: x / (1 + np.exp(-x)), [A], {}),
    ("log_softmax", None, [A], {"axis": -1}),
    ("max", lambda x, axis: np.max(x, axis=axis), [A], {"axis": 1}),
    ("min", lambda x, axis: np.min(x, axis=axis), [A], {"axis": 1}),
    ("prod", lambda x: np.prod(x), [C], {}),
    ("std", None, [A], {}),
    ("var", None, [A], {}),
    ("amax", lambda x: np.max(x), [A], {}),
    ("amin", lambda x: np.min(x), [A], {}),
    ("cumsum", lambda x, axis: np.cumsum(x, axis=axis), [A], {"axis": 1}),
    ("cumprod", lambda x, dim: np.cumprod(x, axis=dim), [C], {"dim": 1}),
    ("flip", lambda x, axis: np.flip(x, axis), [A], {"axis": [1]}),
    ("roll", lambda x, shifts, axis: np.roll(x, shifts, axis), [A],
     {"shifts": 2, "axis": 1}),
    ("tril", np.tril, [A], {}),
    ("triu", np.triu, [A], {}),
    ("kron", np.kron, [M1[:2, :2], M2[:2, :2]], {}),
    ("outer", np.outer, [A[0], B[0]], {}),
    ("trace_op", lambda x: np.trace(x), [M1[:3, :3]], {}),
    ("logcumsumexp", None, [A], {"axis": 1}),
    ("nan_to_num", lambda x: np.nan_to_num(x), [A], {}),
    ("deg2rad", np.deg2rad, [A * 90], {}),
    ("rad2deg", np.rad2deg, [A], {}),
]


def _fill_refs2():
    import scipy.special as sp

    _SELU_L, _SELU_A = 1.0507009873554805, 1.6732632423543772
    refs = {
        "selu": lambda x: _SELU_L * np.where(
            x > 0, x, _SELU_A * (np.exp(x) - 1)),
        "gelu": lambda x: 0.5 * x * (1 + sp.erf(x / np.sqrt(2))),
        "log_softmax": lambda x, axis=-1:
            x - sp.logsumexp(x, axis=axis, keepdims=True),
        "std": lambda x: np.std(x, ddof=1),
        "var": lambda x: np.var(x, ddof=1),
        "logcumsumexp": lambda x, axis:
            np.log(np.cumsum(np.exp(x), axis=axis)),
    }
    out = []
    for name, ref, inputs, kwargs in CASES2:
        out.append((name, ref or refs[name], inputs, kwargs))
    return out


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs2(), ids=[c[0] for c in CASES2])
def test_op_batch2(name, ref, inputs, kwargs):
    # kinked/selective ops: finite differences cross the non-smooth point
    grad_free = {"heaviside", "max", "min", "amax", "amin", "prod",
                 "nan_to_num", "copysign"}
    OpTest(name, ref, inputs, kwargs,
           check_grad=name not in grad_free).run()


CASES3 = [
    ("equal", lambda x, y: x == y, [A, A.copy()], {}),
    ("not_equal", lambda x, y: x != y, [A, B], {}),
    ("less_than", lambda x, y: x < y, [A, B], {}),
    ("less_equal", lambda x, y: x <= y, [A, B], {}),
    ("greater_than", lambda x, y: x > y, [A, B], {}),
    ("greater_equal", lambda x, y: x >= y, [A, B], {}),
    ("isnan", np.isnan, [A], {}),
    ("isinf", np.isinf, [A], {}),
    ("isfinite", np.isfinite, [A], {}),
    ("logical_and", np.logical_and, [C, D], {}),
    ("logical_or", np.logical_or, [C, np.zeros_like(C)], {}),
    ("logical_not", np.logical_not, [np.zeros_like(C)], {}),
    ("logical_xor", np.logical_xor, [C, np.zeros_like(C)], {}),
    ("sign", np.sign, [A + 0.05], {}),
    ("floor", np.floor, [A * 3 + 0.03], {}),
    ("ceil", np.ceil, [A * 3 + 0.03], {}),
    ("round", None, [A * 3 + 0.03], {}),
    ("trunc", np.trunc, [A * 3 + 0.03], {}),
    ("frac", lambda x: x - np.trunc(x), [A * 3 + 0.03], {}),
    ("expm1", np.expm1, [A], {}),
    ("log1p", np.log1p, [D], {}),
    ("log2", np.log2, [D], {}),
    ("log10", np.log10, [D], {}),
    ("asinh", np.arcsinh, [A], {}),
    ("acosh", np.arccosh, [D + 1.0], {}),
    ("atanh", np.arctanh, [C - 0.5], {}),
    ("sinh", np.sinh, [A], {}),
    ("cosh", np.cosh, [A], {}),
    ("digamma", None, [D + 0.5], {}),
    ("lgamma", None, [D + 0.5], {}),
    ("i0", None, [A], {}),
    ("sinc", None, [A], {}),
    ("diag", np.diag, [A[0]], {}),
    ("diagonal", lambda x: np.diagonal(x), [M1[:3, :3]], {}),
    ("t", lambda x: x.T, [A], {}),
    ("squeeze", lambda x, axis: np.squeeze(x, axis), [A[None]],
     {"axis": 0}),
    ("unsqueeze", lambda x, axis: np.expand_dims(x, axis), [A],
     {"axis": 1}),
    ("expand", None, [A[0:1]], {"shape": [3, 4]}),
    ("tile", lambda x, repeat_times: np.tile(x, repeat_times), [A],
     {"repeat_times": [2, 1]}),
    ("broadcast_to", lambda x, shape: np.broadcast_to(x, shape), [A[0:1]],
     {"shape": [3, 4]}),
]


def _fill_refs3():
    import scipy.special as sp

    refs = {
        "round": lambda x: np.round(x),   # banker's rounding both sides
        "digamma": sp.digamma,
        "lgamma": sp.gammaln,
        "i0": sp.i0,
        "sinc": lambda x: np.sinc(x),
        "expand": lambda x, shape: np.broadcast_to(x, shape),
    }
    out = []
    for name, ref, inputs, kwargs in CASES3:
        out.append((name, ref or refs[name], inputs, kwargs))
    return out


_NO_GRAD3 = {"equal", "not_equal", "less_than", "less_equal",
             "greater_than", "greater_equal", "isnan", "isinf", "isfinite",
             "logical_and", "logical_or", "logical_not", "logical_xor",
             "sign", "floor", "ceil", "round", "trunc", "frac"}


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs3(), ids=[c[0] for c in CASES3])
def test_op_batch3(name, ref, inputs, kwargs):
    OpTest(name, ref, inputs, kwargs, check_grad=name not in _NO_GRAD3,
           bf16=name not in {"digamma", "lgamma", "acosh", "atanh"}).run()


IDX1 = np.array([2, 0, 1], np.int64)
IDX2 = np.array([[0, 2], [1, 3], [2, 0]], np.int64)
MASK = (R.rand(3, 4) > 0.5)


CASES4 = [
    ("gather", lambda x, index: x[index], [A, IDX1], {}),
    ("index_select", lambda x, index, axis:
        np.take(x, index, axis=axis), [A, IDX1], {"axis": 1}),
    ("take_along_axis", lambda x, indices, axis:
        np.take_along_axis(x, indices, axis), [A, IDX2], {"axis": 1}),
    ("where", lambda c, x, y: np.where(c, x, y), [MASK, A, B], {}),
    ("masked_fill", lambda x, mask, value:
        np.where(mask, value, x), [A, MASK], {"value": -1.0}),
    ("index_sample", lambda x, index:
        np.take_along_axis(x, index, 1), [A, IDX2], {}),
    ("one_hot", None, [IDX1], {"num_classes": 4}),
    ("tensor_unfold", None, [np.arange(8, dtype=np.float32)],
     {"axis": 0, "size": 3, "step": 2}),
    ("masked_scatter", None, [A, MASK, B], {}),
    ("select_scatter", lambda x, values, axis, index:
        _select_scatter_ref(x, values, axis, index),
     [A, B[:, 0]], {"axis": 1, "index": 2}),
]


def _select_scatter_ref(x, values, axis, index):
    out = x.copy()
    out[:, index] = values
    return out


def _fill_refs4():
    refs = {
        "one_hot": lambda x, num_classes: np.eye(num_classes)[x],
        "tensor_unfold": lambda x, axis, size, step: np.stack(
            [x[i * step:i * step + size]
             for i in range((x.shape[0] - size) // step + 1)]),
        "masked_scatter": lambda x, mask, value:
            _masked_scatter_ref(x, mask, value),
    }
    out = []
    for name, ref, inputs, kwargs in CASES4:
        out.append((name, ref or refs[name], inputs, kwargs))
    return out


def _masked_scatter_ref(x, mask, value):
    out = x.copy().reshape(-1)
    m = np.broadcast_to(mask, x.shape).reshape(-1)
    out[m] = value.reshape(-1)[:m.sum()]
    return out.reshape(x.shape)


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs4(), ids=[c[0] for c in CASES4])
def test_op_batch4(name, ref, inputs, kwargs):
    # index/selection ops: grads flow through the float operands only;
    # where/masked_fill keep finite-difference checks (smooth in values)
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in {"where", "masked_fill", "gather",
                               "index_select", "take_along_axis"}).run()


# ===================================================================
# batch 5 (r5): yaml elementwise math / special functions / scalar ops
# ===================================================================

E1 = R.randn(3, 4).astype(np.float32)            # generic
POS = np.abs(R.randn(3, 4)).astype(np.float32) + 0.5
UNIT = (R.rand(3, 4).astype(np.float32) * 1.6 - 0.8)   # in (-0.8, 0.8)
I32A = R.randint(1, 20, (3, 4)).astype(np.int32)
I32B = R.randint(1, 20, (3, 4)).astype(np.int32)
BOOLA = R.rand(3, 4) > 0.5
BOOLB = R.rand(3, 4) > 0.5


def _glu_ref(x, axis=-1):
    a, b = np.split(x, 2, axis=axis)
    return a / (1 + np.exp(-b))


CASES5 = [
    ("acos", np.arccos, [UNIT], {}),
    ("asin", np.arcsin, [UNIT], {}),
    ("tan", np.tan, [UNIT], {}),
    ("exp2", np.exp2, [E1], {}),
    ("neg", lambda x: -x, [E1], {}),
    ("negative", lambda x: -x, [E1], {}),
    ("positive", lambda x: +x, [E1], {}),
    ("conj", np.conj, [E1], {}),
    ("real", np.real, [E1], {}),
    ("imag", lambda x: np.zeros_like(x), [E1], {}),
    ("angle", lambda x: np.angle(x).astype(np.float32), [E1], {}),
    ("sgn", np.sign, [E1 + 0.05], {}),
    ("signbit", np.signbit, [E1], {}),
    ("isneginf", np.isneginf, [E1], {}),
    ("isposinf", np.isposinf, [E1], {}),
    ("floor_divide", np.floor_divide, [E1 * 4, POS], {}),
    ("mod", lambda x, y: np.mod(x, y), [E1 * 4 + 0.03, POS], {}),
    ("remainder", lambda x, y: np.mod(x, y), [E1 * 4 + 0.03, POS], {}),
    ("fmax", np.fmax, [E1, POS - 0.5], {}),
    ("fmin", np.fmin, [E1, POS - 0.5], {}),
    ("gcd", np.gcd, [I32A, I32B], {}),
    ("lcm", np.lcm, [I32A, I32B], {}),
    ("ldexp", lambda x, y: np.ldexp(x, y), [E1, I32A % 5], {}),
    ("nextafter", np.nextafter, [E1, POS], {}),
    ("xlogy", None, [POS, POS + 0.5], {}),
    ("logaddexp2", np.logaddexp2, [E1, E1 * 0.5], {}),
    ("erfinv", None, [UNIT], {}),
    ("i0e", None, [E1], {}),
    ("i1", None, [E1], {}),
    ("i1e", None, [E1], {}),
    ("gammaln", None, [POS], {}),
    ("multigammaln", None, [POS + 1.5], {"p": 2}),
    ("polygamma", None, [POS], {"n": 1}),
    ("gammainc", None, [POS, POS + 0.3], {}),
    ("gammaincc", None, [POS, POS + 0.3], {}),
    ("frexp", np.frexp, [E1 * 3 + 0.03], {}),
    ("celu", lambda x, alpha=1.0:
        np.where(x > 0, x, alpha * (np.exp(x / alpha) - 1)),
     [E1 + 0.05], {"alpha": 1.2}),
    ("glu", _glu_ref, [E1], {"axis": -1}),
    ("hardshrink", lambda x, threshold=0.5:
        np.where(np.abs(x) > threshold, x, 0.0), [E1 * 2 + 0.07], {}),
    ("hardsigmoid", lambda x, slope=1 / 6, offset=0.5:
        np.clip(slope * x + offset, 0, 1), [E1 * 4 + 0.07], {}),
    ("hardswish", lambda x:
        x * np.clip(x + 3, 0, 6) / 6, [E1 * 4 + 0.07], {}),
    ("log_sigmoid", lambda x:
        -(np.log1p(np.exp(-np.abs(x))) + np.maximum(-x, 0)), [E1], {}),
    ("softshrink", lambda x, threshold=0.5: np.where(
        x > threshold, x - threshold,
        np.where(x < -threshold, x + threshold, 0.0)),
     [E1 * 2 + 0.07], {}),
    ("softsign", lambda x: x / (1 + np.abs(x)), [E1 + 0.05], {}),
    ("swish", lambda x: x / (1 + np.exp(-x)), [E1], {}),
    ("tanhshrink", lambda x: x - np.tanh(x), [E1], {}),
    ("thresholded_relu", lambda x, threshold=1.0, value=0.0:
        np.where(x > threshold, x, value), [E1 * 2 + 0.07], {}),
    ("square_error_cost", lambda i, l: (i - l) ** 2, [E1, POS], {}),
    ("log_loss", lambda i, l, epsilon=1e-4:
        -l * np.log(i + epsilon) - (1 - l) * np.log(1 - i + epsilon),
     [C, (C > 0.5).astype(np.float32)], {}),
    ("multiply_scalar", lambda x, value: x * value, [E1], {"value": 2.5}),
    ("pow_scalar", lambda x, value: x ** value, [POS], {"value": 1.7}),
    ("rpow_scalar", lambda x, value: value ** x, [E1], {"value": 1.7}),
    ("scale", lambda x, scale=1.0, bias=0.0, bias_after_scale=True:
        x * scale + bias, [E1], {"scale": 3.0, "bias": 0.5}),
    ("clone", lambda x: x.copy(), [E1], {}),
    ("full_like", lambda x, fill_value: np.full_like(x, fill_value),
     [E1], {"fill_value": 2.5}),
    ("cast", lambda x, dtype: x.astype(np.int32), [E1 * 5],
     {"dtype": "int32"}),
    ("allclose", lambda x, y, rtol=1e-5, atol=1e-8:
        np.array(np.allclose(x, y, rtol, atol)), [E1, E1 + 1e-9], {}),
    ("isclose", np.isclose, [E1, E1 + 1e-9], {}),
    ("equal_all", lambda x, y: np.array(np.array_equal(x, y)),
     [E1, E1.copy()], {}),
    ("bitwise_and", np.bitwise_and, [I32A, I32B], {}),
    ("bitwise_or", np.bitwise_or, [I32A, I32B], {}),
    ("bitwise_xor", np.bitwise_xor, [I32A, I32B], {}),
    ("bitwise_not", np.invert, [I32A], {}),
    ("bitwise_left_shift", np.left_shift, [I32A, I32B % 4], {}),
    ("bitwise_right_shift", np.right_shift, [I32A, I32B % 4], {}),
]


def _fill_refs5():
    import scipy.special as sp

    refs = {
        "xlogy": sp.xlogy,
        "erfinv": sp.erfinv,
        "i0e": sp.i0e,
        "i1": sp.i1,
        "i1e": sp.i1e,
        "gammaln": sp.gammaln,
        "multigammaln": lambda x, p: sp.multigammaln(x, p),
        "polygamma": lambda x, n: sp.polygamma(n, x),
        "gammainc": sp.gammainc,
        "gammaincc": sp.gammaincc,
    }
    return [(n, r or refs[n], i, k) for n, r, i, k in CASES5]


_NO_GRAD5 = {"sgn", "signbit", "isneginf", "isposinf", "floor_divide",
             "mod", "remainder", "fmax", "fmin", "frexp", "cast",
             "allclose", "isclose", "equal_all", "full_like", "angle",
             "imag", "nextafter", "hardshrink", "softshrink",
             "thresholded_relu", "log_loss"}
# scipy-special ops whose bf16/fp16 ulp behavior is too coarse to bound
_NO_LOWP5 = {"erfinv", "gammaln", "multigammaln", "polygamma", "gammainc",
             "gammaincc", "i1", "i1e", "i0e", "cast", "frexp", "exp2",
             "rpow_scalar", "nextafter", "log_loss"}


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs5(), ids=[c[0] for c in CASES5])
def test_op_batch5(name, ref, inputs, kwargs):
    OpTest(name, ref, inputs, kwargs,
           check_grad=name not in _NO_GRAD5,
           bf16=name not in _NO_LOWP5,
           fp16=name not in _NO_LOWP5).run()


# ===================================================================
# batch 6 (r5): manipulation / stacking / indexing / scatter
# ===================================================================

X4 = R.randn(2, 3, 4, 5).astype(np.float32)
X3 = R.randn(2, 4, 6).astype(np.float32)
SEQ1 = np.sort(R.randn(6).astype(np.float32))
IDXR = np.array([0, 2], np.int64)
ND_IDX = np.array([[0, 1], [1, 3], [0, 0]], np.int64)   # rows into (3,4)


def _scatter_ref(x, index, updates, overwrite=True):
    out = x.copy()
    if overwrite:
        out[index] = updates[:len(index)]
    else:
        np.add.at(out, index, updates[:len(index)])
    return out


def _scatter_nd_ref(index, updates, shape):
    out = np.zeros(shape, updates.dtype)
    np.add.at(out, tuple(index.T), updates)
    return out


def _scatter_nd_add_ref(x, index, updates):
    out = x.copy()
    np.add.at(out, tuple(index.T), updates)
    return out


def _diag_embed_ref(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    out = np.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = np.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out[..., r, c] = x
    return out


def _diagonal_scatter_ref(x, y, offset=0, axis1=0, axis2=1):
    out = x.copy()
    idx = np.arange(y.shape[-1])
    out[idx + max(-offset, 0), idx + max(offset, 0)] = y
    return out


def _slice_ref(x, axes, starts, ends):
    sl = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        sl[ax] = slice(s, e)
    return x[tuple(sl)]


def _strided_slice_ref(x, axes, starts, ends, strides):
    sl = [slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        sl[ax] = slice(s, e, st)
    return x[tuple(sl)]


def _slice_scatter_ref(x, value, axes=None, starts=None, ends=None,
                       strides=None):
    out = x.copy()
    sl = [slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        sl[ax] = slice(s, e, st)
    out[tuple(sl)] = value
    return out


def _as_strided_ref(x, shape, stride, offset=0):
    it = x.itemsize
    return np.lib.stride_tricks.as_strided(
        x.reshape(-1)[offset:], shape,
        [s * it for s in stride]).copy()


def _put_along_axis_ref(x, indices, values, axis, reduce="assign"):
    out = x.copy()
    np.put_along_axis(out, indices, values, axis)
    return out


def _index_add_ref(x, index, axis, value):
    out = x.copy()
    sl = [slice(None)] * x.ndim
    for j, i in enumerate(index):
        sli = list(sl)
        sli[axis] = i
        slv = list(sl)
        slv[axis] = j
        out[tuple(sli)] += value[tuple(slv)]
    return out


def _index_fill_ref(x, index, axis, value):
    out = x.copy()
    sl = [slice(None)] * x.ndim
    for i in index:
        sli = list(sl)
        sli[axis] = i
        out[tuple(sli)] = value
    return out


def _unique_consecutive_ref(x):
    flat = x.reshape(-1)
    keep = np.concatenate([[True], flat[1:] != flat[:-1]])
    return flat[keep]


def _shard_index_ref(x, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return np.where(in_shard, x % shard_size, ignore_value)


def _combinations_ref(x, r=2, with_replacement=False):
    import itertools
    it = (itertools.combinations_with_replacement(x, r)
          if with_replacement else itertools.combinations(x, r))
    return np.array(list(it), x.dtype)


CASES6 = [
    ("argsort", lambda x, axis=-1: np.argsort(x, axis=axis, kind="stable"),
     [A], {"axis": -1}),
    ("sort", lambda x, axis=-1: np.sort(x, axis=axis), [A], {"axis": -1}),
    ("as_strided", _as_strided_ref, [np.arange(24, dtype=np.float32)],
     {"shape": [3, 4], "stride": [8, 2], "offset": 1}),
    ("atleast_1d", np.atleast_1d, [np.float32(3.5)], {}),
    ("atleast_2d", np.atleast_2d, [np.arange(4, dtype=np.float32)], {}),
    ("atleast_3d", np.atleast_3d, [A], {}),
    ("block_diag", None, [M1[:2, :2], M2[:3, :3]], {}),
    ("bucketize", lambda x, s, right=False: np.searchsorted(
        s, x, side="right" if right else "left"), [A, SEQ1],
     {"right": True}),
    ("combinations", _combinations_ref,
     [np.arange(4, dtype=np.float32)], {"r": 2}),
    ("concat", lambda *xs, axis=0: np.concatenate(xs, axis), [A, B],
     {"axis": 1}),
    ("stack", lambda *xs, axis=0: np.stack(xs, axis), [A, B], {"axis": 1}),
    ("hstack", lambda *xs: np.hstack(xs), [A, B], {}),
    ("vstack", lambda *xs: np.vstack(xs), [A, B], {}),
    ("dstack", lambda *xs: np.dstack(xs), [A, B], {}),
    ("column_stack", lambda *xs: np.column_stack(xs), [A, B], {}),
    ("row_stack", lambda *xs: np.vstack(xs), [A, B], {}),
    ("meshgrid", lambda *xs, indexing="ij": tuple(
        np.meshgrid(*xs, indexing=indexing)),
     [np.arange(3, dtype=np.float32), np.arange(4, dtype=np.float32)], {}),
    ("diag_embed", _diag_embed_ref, [A], {"offset": 1}),
    ("diagonal_scatter", _diagonal_scatter_ref,
     [M2[:4, :4].copy(), np.arange(4, dtype=np.float32)], {}),
    ("diff", lambda x, n=1, axis=-1: np.diff(x, n, axis), [A], {}),
    ("expand_as", lambda x, y: np.broadcast_to(x, y.shape), [A[0:1], A],
     {}),
    ("flatten", lambda x, start_axis=0, stop_axis=-1:
        x.reshape(2, 12, 5), [X4], {"start_axis": 1, "stop_axis": 2}),
    ("gather_nd", lambda x, idx: x[tuple(idx.T)], [A, ND_IDX], {}),
    ("isin", lambda x, t: np.isin(x, t), [I32A, I32B], {}),
    ("moveaxis", np.moveaxis, [X3], {"source": 0, "destination": 2}),
    ("swapaxes", np.swapaxes, [X3], {"axis1": 0, "axis2": 2}),
    ("rot90", lambda x, k=1, axes=(0, 1): np.rot90(x, k, axes), [A],
     {"k": 3}),
    ("pad", None, [X4], {"pad": [1, 2, 0, 1], "value": 1.5}),
    ("put_along_axis", _put_along_axis_ref, [A, IDX2, B[:, :2]],
     {"axis": 1}),
    ("index_add", lambda x, index, axis, value: _index_add_ref(
        x, index, axis, value), [A, IDXR],
     {"axis": 1, "value": np.ones((3, 2), np.float32)}),
    ("index_fill", _index_fill_ref, [A, IDXR], {"axis": 1, "value": -2.0}),
    ("masked_select", lambda x, mask: x[np.broadcast_to(mask, x.shape)],
     [A, A > 0.0], {}),
    ("igamma", lambda x, a: __import__("scipy.special",
                                       fromlist=["x"]).gammaincc(x, a),
     [np.asarray([0.5, 2.0, 4.0], np.float32),
      np.asarray([1.0, 3.0, 2.0], np.float32)], {}),
    ("igammac", lambda x, a: __import__("scipy.special",
                                        fromlist=["x"]).gammainc(x, a),
     [np.asarray([0.5, 2.0, 4.0], np.float32),
      np.asarray([1.0, 3.0, 2.0], np.float32)], {}),
    ("repeat_interleave", lambda x, repeats, axis=None:
        np.repeat(x, repeats, axis), [A], {"repeats": 3, "axis": 1}),
    ("scatter", _scatter_ref, [A, IDX1, B], {}),
    ("scatter_nd", _scatter_nd_ref, [ND_IDX, np.ones(3, np.float32)],
     {"shape": [3, 4]}),
    ("scatter_nd_add", _scatter_nd_add_ref,
     [A, ND_IDX, np.ones(3, np.float32)], {}),
    ("searchsorted", lambda s, v: np.searchsorted(s, v), [SEQ1, A], {}),
    ("slice_op", _slice_ref, [X3],
     {"axes": [0, 2], "starts": [0, 1], "ends": [2, 5]}),
    ("strided_slice", _strided_slice_ref, [X3],
     {"axes": [1, 2], "starts": [0, 1], "ends": [4, 6], "strides": [2, 2]}),
    ("slice_scatter", _slice_scatter_ref, [X3, np.zeros((2, 2, 6),
                                                        np.float32)],
     {"axes": [1], "starts": [0], "ends": [4], "strides": [2]}),
    ("split", lambda x, num_or_sections, axis=0: tuple(
        np.split(x, num_or_sections, axis)), [X3],
     {"num_or_sections": 2, "axis": 1}),
    ("tensor_split", lambda x, num_or_indices, axis=0: tuple(
        np.array_split(x, num_or_indices, axis)), [X3],
     {"num_or_indices": 3, "axis": 2}),
    ("hsplit", lambda x, num_or_indices: tuple(
        np.hsplit(x, num_or_indices)), [A], {"num_or_indices": 2}),
    ("vsplit", lambda x, num_or_indices: tuple(
        np.vsplit(x, num_or_indices)), [M2[:4]], {"num_or_indices": 2}),
    ("dsplit", lambda x, num_or_indices: tuple(
        np.dsplit(x, num_or_indices)), [X3], {"num_or_indices": 3}),
    ("take", lambda x, index: np.take(x, index), [A, IDX2 % 12], {}),
    ("topk_indices", None, [A], {"k": 2, "axis": -1}),
    ("unbind", lambda x, axis=0: tuple(np.moveaxis(x, axis, 0)), [X3],
     {"axis": 1}),
    ("unflatten", lambda x, axis, shape: x.reshape(2, 2, 2, 6), [X3],
     {"axis": 1, "shape": [2, 2]}),
    ("vander", lambda x, n=None, increasing=False:
        np.vander(x, n, increasing), [np.arange(1, 5, dtype=np.float32)],
     {"n": 3}),
    ("is_empty", lambda x: np.array(x.size == 0), [A], {}),
    ("shard_index", _shard_index_ref, [np.arange(8).astype(np.int64)],
     {"index_num": 8, "nshards": 2, "shard_id": 1}),
    ("reduce_as", lambda x, target: x.sum(0, keepdims=True), [A, A[0:1]],
     {}),
]


def _fill_refs6():
    import scipy.linalg as sl

    def _pad_ref(x, pad, mode="constant", value=0.0, data_format="NCHW"):
        wl, wr, ht, hb = pad
        return np.pad(x, ((0, 0), (0, 0), (ht, hb), (wl, wr)),
                      constant_values=value)

    def _topk_indices_ref(x, k, axis=-1, largest=True):
        order = np.argsort(-x if largest else x, axis=axis, kind="stable")
        return np.take(order, np.arange(k), axis=axis)

    refs = {
        "block_diag": lambda *xs: sl.block_diag(*xs),
        "pad": _pad_ref,
        "topk_indices": _topk_indices_ref,
    }
    return [(n, r or refs[n], i, k) for n, r, i, k in CASES6]


_LIST6 = {"concat", "stack", "hstack", "vstack", "dstack", "column_stack",
          "row_stack", "meshgrid", "block_diag"}
_GRAD6 = {"concat", "stack", "hstack", "vstack", "dstack", "column_stack",
          "row_stack", "pad", "flatten", "moveaxis", "swapaxes", "diff",
          "diag_embed", "expand_as", "repeat_interleave", "unflatten",
          "slice_op", "split", "unbind", "rot90", "gather_nd", "take"}
_NO_LOWP6 = {"argsort", "sort", "bucketize", "searchsorted",
             "topk_indices", "isin", "as_strided", "combinations",
             "vander",
             # kwargs carry f32 constants the sweep can't re-dtype
             "index_add", "index_fill", "slice_scatter"}


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs6(), ids=[c[0] for c in CASES6])
def test_op_batch6(name, ref, inputs, kwargs):
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in _GRAD6,
           bf16=name not in _NO_LOWP6,
           fp16=name not in _NO_LOWP6,
           list_input=name in _LIST6).run()


# ===================================================================
# batch 7 (r5): linalg — products, factorizations, solvers
# ===================================================================

S3 = (M1[:3, :3] @ M1[:3, :3].T + 3 * np.eye(3)).astype(np.float32)  # SPD
G3 = (M2[:3, :3] + 0.1 * np.eye(3)).astype(np.float32)   # general, invertible
BMA = R.randn(2, 3, 4).astype(np.float32)
BMB = R.randn(2, 4, 5).astype(np.float32)
V4 = R.randn(4).astype(np.float32)
OVR = R.randn(5, 3).astype(np.float32)    # overdetermined lstsq
OVRY = R.randn(5, 2).astype(np.float32)


def _cummax_ref(x, axis=None):
    vals = np.maximum.accumulate(x, axis=axis)
    n = x.shape[axis]
    idx = np.zeros(x.shape, np.int64)
    run = np.zeros(np.delete(x.shape, axis), np.int64)
    best = np.take(x, 0, axis=axis).copy()
    for i in range(n):
        cur = np.take(x, i, axis=axis)
        upd = cur >= best
        best = np.where(upd, cur, best)
        run = np.where(upd, i, run)
        sl = [slice(None)] * x.ndim
        sl[axis] = i
        idx[tuple(sl)] = run
    return vals, idx


def _cummin_ref(x, axis=None):
    vals, idx = _cummax_ref(-x, axis=axis)
    return -vals, idx


def _hh_q(x, tau):
    """Accumulate the householder reflectors (LAPACK orgqr semantics)."""
    m, k = x.shape[0], len(tau)
    q = np.eye(m, dtype=np.float64)
    for i in range(k):
        v = np.zeros(m, np.float64)
        v[i] = 1.0
        v[i + 1:] = x[i + 1:, i]
        q = q @ (np.eye(m) - tau[i] * np.outer(v, v))
    return q


def _lu_ref(x, pivot=True):
    import scipy.linalg as sl
    lu, piv = sl.lu_factor(x)
    # LAPACK ipiv is a sequence of row swaps, 1-based in paddle's contract
    return lu.astype(np.float32), (piv + 1).astype(np.int32)


def _lu_unpack_ref(lu_data, pivots, unpack_ludata=True,
                   unpack_pivots=True):
    n = lu_data.shape[0]
    lo = np.tril(lu_data, -1) + np.eye(n, dtype=lu_data.dtype)
    up = np.triu(lu_data)
    perm = np.arange(n)
    for i, p in enumerate(pivots):   # 1-based swap sequence -> permutation
        perm[i], perm[p - 1] = perm[p - 1], perm[i].copy()
    pm = np.zeros((n, n), lu_data.dtype)
    pm[perm, np.arange(n)] = 1.0
    return pm, lo, up


CASES7 = [
    ("addmm", lambda inp, x, y, beta=1.0, alpha=1.0:
        beta * inp + alpha * (x @ y), [M1[:3, :3], M1[:3, :4], M2[:4, :3]],
     {"beta": 0.5, "alpha": 2.0}),
    ("bmm", lambda x, y: x @ y, [BMA, BMB], {}),
    ("mm", lambda x, y: x @ y, [M1, M2], {}),
    ("mv", lambda x, vec: x @ vec, [M1, V4], {}),
    ("dot", np.dot, [V4, V4 + 1], {}),
    ("inner", np.inner, [A, B], {}),
    ("vecdot", lambda x, y, axis=-1: (x * y).sum(axis), [A, B], {}),
    ("tensordot", lambda x, y, axes=2: np.tensordot(x, y, axes),
     [BMA, R.randn(3, 4, 5).astype(np.float32)], {"axes": 2}),
    ("multi_dot", lambda *xs: np.linalg.multi_dot(xs),
     [M1, M2, M2.T[:5, :3]], {}),
    ("einsum", lambda *xs, equation="": np.einsum(equation, *xs),
     [M1, M2], {"equation": "ij,jk->ik"}),
    ("cross", lambda x, y, axis=-1:
        np.cross(x, y, axisa=axis, axisb=axis, axisc=axis),
     [R.randn(2, 3).astype(np.float32), R.randn(2, 3).astype(np.float32)],
     {}),
    ("cdist", None, [A, B[:2]], {"p": 2.0}),
    ("pdist", None, [A], {"p": 2.0}),
    ("dist", lambda x, y, p=2.0: np.array(
        np.linalg.norm((x - y).ravel(), p), np.float32), [A, B], {}),
    ("norm", lambda x, p=2, axis=None, keepdim=False:
        np.linalg.norm(x, p, axis, keepdim), [A], {"p": 2, "axis": 1}),
    ("det", np.linalg.det, [S3], {}),
    ("slogdet", np.linalg.slogdet, [G3], {}),
    ("inverse", np.linalg.inv, [S3], {}),
    ("pinv", lambda x, rcond=1e-15: np.linalg.pinv(x, rcond), [M1], {}),
    ("solve", np.linalg.solve, [S3, M1[:3, :2]], {}),
    ("cholesky", lambda x, upper=False: np.linalg.cholesky(x), [S3], {}),
    ("cholesky_solve", lambda x, y, upper=False:
        np.linalg.solve(y @ y.T, x),
     [M1[:3, :2], np.linalg.cholesky(S3).astype(np.float32)], {}),
    ("triangular_solve", None,
     [np.triu(S3).astype(np.float32), M1[:3, :2]], {"upper": True}),
    ("matrix_exp", None, [G3 * 0.3], {}),
    ("matrix_power", np.linalg.matrix_power, [G3], {"n": 3}),
    ("matrix_rank", lambda x, tol=None:
        np.asarray(np.linalg.matrix_rank(x), np.int64), [S3], {}),
    ("cond", lambda x, p=None: np.asarray(np.linalg.cond(x), np.float32),
     [S3], {}),
    ("lstsq", None, [OVR, OVRY], {}),
    ("qr", lambda x, mode="reduced": np.linalg.qr(x, mode), [M1], {}),
    ("lu", _lu_ref, [G3], {}),
    ("lu_unpack", _lu_unpack_ref, [_lu_ref(G3)[0], _lu_ref(G3)[1]], {}),
    ("svd", None, [M1], {"full_matrices": False}),
    ("eigh", lambda x, UPLO="L": np.linalg.eigh(x), [S3], {}),
    ("eigvalsh", lambda x, UPLO="L": np.linalg.eigvalsh(x), [S3], {}),
    ("eigvals", np.linalg.eigvals, [G3], {}),
    ("householder_product", None, [np.linalg.qr(OVR)[0] * 0 + OVR,
                                   np.array([1.2, 0.8, 1.5], np.float32)],
     {}),
    ("ormqr", None, [OVR, np.array([1.2, 0.8, 1.5], np.float32),
                     R.randn(5, 2).astype(np.float32)], {}),
    ("cov", lambda x, rowvar=True, ddof=True, fweights=None,
        aweights=None: np.cov(x, rowvar=rowvar, ddof=1 if ddof else 0),
     [A], {}),
    ("corrcoef", lambda x, rowvar=True: np.corrcoef(x, rowvar=rowvar),
     [A], {}),
    ("trapezoid", lambda y, x=None, dx=None, axis=-1:
        np.trapz(y, x, dx if dx is not None else 1.0, axis), [A],
     {"dx": 0.5}),
    ("cumulative_trapezoid", None, [A], {"dx": 0.5}),
    ("cummax", _cummax_ref, [A], {"axis": 1}),
    ("cummin", _cummin_ref, [A], {"axis": 1}),
]


def _fill_refs7():
    import scipy.integrate as si
    import scipy.linalg as sl
    import scipy.spatial.distance as sd

    def _svd_ref(x, full_matrices=False):
        u, s, vh = np.linalg.svd(x, full_matrices=full_matrices)
        return u, s, vh

    def _hhprod_ref(x, tau):
        return _hh_q(x, tau)[:, :x.shape[1]].astype(np.float32)

    def _ormqr_ref(x, tau, y, left=True, transpose=False):
        q = _hh_q(x, tau)[:, :x.shape[0]]
        if transpose:
            q = q.T
        return (q @ y if left else y @ q).astype(np.float32)

    refs = {
        "cdist": lambda x, y, p=2.0: sd.cdist(x, y, "minkowski", p=p),
        "pdist": lambda x, p=2.0: sd.pdist(x, "minkowski", p=p),
        "triangular_solve": lambda x, y, upper=True, transpose=False,
        unitriangular=False: sl.solve_triangular(
            x, y, lower=not upper, trans="T" if transpose else "N",
            unit_diagonal=unitriangular),
        "matrix_exp": sl.expm,
        "lstsq": lambda x, y, rcond=None: np.linalg.lstsq(x, y,
                                                          rcond=rcond),
        "svd": _svd_ref,
        "householder_product": _hhprod_ref,
        "ormqr": _ormqr_ref,
        "cumulative_trapezoid": lambda y, x=None, dx=None, axis=-1:
            si.cumulative_trapezoid(y, x, dx if dx is not None else 1.0,
                                    axis),
    }
    return [(n, r or refs[n], i, k) for n, r, i, k in CASES7]


_LIST7 = {"multi_dot", "einsum"}
_GRAD7 = {"addmm", "bmm", "mm", "mv", "dot", "inner", "vecdot",
          "tensordot", "multi_dot", "einsum", "cross", "cdist", "dist",
          "norm", "det", "inverse", "solve", "trapezoid",
          "cumulative_trapezoid", "matrix_power"}
# factorizations/solvers hit f64-less lax.linalg paths; keep lowp to the
# MXU product ops where a tolerance is meaningful
_LOWP7 = {"addmm", "bmm", "mm", "mv", "dot", "inner", "vecdot",
          "tensordot", "multi_dot", "einsum", "cross", "trapezoid"}
# gauge freedom: Q/R, U/Vh, eigenvectors are sign-ambiguous columns
_ABS7 = {"qr", "svd", "eigh", "householder_product"}
# eigvals order is backend-defined: compare as sorted complex spectra
_POST7 = dict.fromkeys(_ABS7, np.abs)
_POST7["eigvals"] = np.sort_complex


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs7(), ids=[c[0] for c in CASES7])
def test_op_batch7(name, ref, inputs, kwargs):
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in _GRAD7,
           bf16=name in _LOWP7, fp16=name in _LOWP7,
           list_input=name in _LIST7,
           post=_POST7.get(name),
           rtol=1e-4, atol=1e-4).run()


# ===================================================================
# batch 8 (r5): FFT family (paddle.fft — SURVEY §2.2 Tensor-API row)
# ===================================================================

FR = R.randn(4, 8).astype(np.float32)
FC = (R.randn(4, 8) + 1j * R.randn(4, 8)).astype(np.complex64)
# hermitian-symmetric spectrum input for hfft: irfft's natural domain
FH = (R.randn(4, 5) + 1j * R.randn(4, 5)).astype(np.complex64)

CASES8 = [
    ("fft", lambda x, n=None, axis=-1, norm="backward":
        np.fft.fft(x, n, axis, norm), [FR], {}),
    ("ifft", lambda x, n=None, axis=-1, norm="backward":
        np.fft.ifft(x, n, axis, norm), [FC], {}),
    ("fft2", lambda x, s=None, axes=(-2, -1), norm="backward":
        np.fft.fft2(x, s, axes, norm), [FR], {}),
    ("ifft2", lambda x, s=None, axes=(-2, -1), norm="backward":
        np.fft.ifft2(x, s, axes, norm), [FC], {}),
    ("fftn", lambda x, s=None, axes=None, norm="backward":
        np.fft.fftn(x, s, axes, norm), [FR], {}),
    ("ifftn", lambda x, s=None, axes=None, norm="backward":
        np.fft.ifftn(x, s, axes, norm), [FC], {}),
    ("rfft", lambda x, n=None, axis=-1, norm="backward":
        np.fft.rfft(x, n, axis, norm), [FR], {}),
    ("irfft", lambda x, n=None, axis=-1, norm="backward":
        np.fft.irfft(x, n, axis, norm), [FH], {}),
    ("rfft2", lambda x, s=None, axes=(-2, -1), norm="backward":
        np.fft.rfft2(x, s, axes, norm), [FR], {}),
    ("irfft2", lambda x, s=None, axes=(-2, -1), norm="backward":
        np.fft.irfft2(x, s, axes, norm), [FH], {}),
    ("rfftn", lambda x, s=None, axes=None, norm="backward":
        np.fft.rfftn(x, s, axes, norm), [FR], {}),
    ("irfftn", lambda x, s=None, axes=None, norm="backward":
        np.fft.irfftn(x, s, axes, norm), [FH], {}),
    ("hfft", lambda x, n=None, axis=-1, norm="backward":
        np.fft.hfft(x, n, axis, norm), [FH], {}),
    ("ihfft", lambda x, n=None, axis=-1, norm="backward":
        np.fft.ihfft(x, n, axis, norm), [FR], {}),
    ("hfft2", lambda x, s=None, axes=(-2, -1), norm="backward":
        np.fft.hfft(np.fft.fft(x, axis=-2), axis=-1), [FH], {}),
    ("ihfft2", lambda x, s=None, axes=(-2, -1), norm="backward":
        np.fft.ifft(np.fft.ihfft(x, axis=-1), axis=-2), [FR], {}),
    ("hfftn", lambda x, s=None, axes=None, norm="backward":
        np.fft.hfft(np.fft.fftn(x, axes=(0,)), axis=-1), [FH], {}),
    ("ihfftn", lambda x, s=None, axes=None, norm="backward":
        np.fft.ifftn(np.fft.ihfft(x, axis=-1), axes=(0,)), [FR], {}),
    ("fftshift", np.fft.fftshift, [FR], {}),
    ("ifftshift", np.fft.ifftshift, [FR], {}),
]


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    CASES8, ids=[c[0] for c in CASES8])
def test_op_batch8(name, ref, inputs, kwargs):
    # FFT kernels compute in f32/c64 regardless of input precision; the
    # low-precision sweeps would only measure the input cast
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in {"fftshift", "ifftshift"},
           bf16=False, fp16=False, rtol=1e-3, atol=1e-3).run()


# ===================================================================
# batch 9 (r5): reductions, order statistics, histograms
# ===================================================================

NANX = A.copy()
NANX[0, 1] = np.nan
NANX[2, 3] = np.nan
MODEX = np.array([[1., 2., 2., 3.], [4., 4., 1., 1.]], np.float32)
HDD = R.rand(20, 2).astype(np.float32)


def _mode_ref(x, axis=-1, keepdim=False):
    # paddle contract: smallest most-frequent value, LAST occurrence index
    vals = np.zeros(x.shape[:-1], x.dtype)
    idxs = np.zeros(x.shape[:-1], np.int64)
    it = np.ndindex(*x.shape[:-1])
    for i in it:
        row = x[i]
        uniq, counts = np.unique(row, return_counts=True)
        v = uniq[np.argmax(counts)]          # np.unique sorts: smallest wins
        vals[i] = v
        idxs[i] = np.max(np.nonzero(row == v)[0])
    return vals, idxs


def _kthvalue_ref(x, k, axis=-1, keepdim=False):
    order = np.argsort(x, axis=axis, kind="stable")
    idx = np.take(order, k - 1, axis=axis)
    vals = np.take_along_axis(x, np.expand_dims(idx, axis),
                              axis).squeeze(axis)
    return vals, idx


CASES9 = [
    ("all", lambda x, axis=None, keepdim=False:
        np.all(x, axis=axis, keepdims=keepdim), [C > 0.05], {"axis": 1}),
    ("any", lambda x, axis=None, keepdim=False:
        np.any(x, axis=axis, keepdims=keepdim), [C > 0.5], {"axis": 1}),
    ("argmax", lambda x, axis=None, keepdim=False, dtype="int64":
        np.argmax(x, axis=axis), [A], {"axis": 1}),
    ("argmin", lambda x, axis=None, keepdim=False, dtype="int64":
        np.argmin(x, axis=axis), [A], {"axis": 1}),
    ("count_nonzero", lambda x, axis=None, keepdim=False:
        np.count_nonzero(x, axis=axis), [MASK.astype(np.float32)],
     {"axis": 1}),
    ("median", lambda x, axis=None, keepdim=False:
        np.median(x, axis=axis, keepdims=keepdim), [A], {"axis": 1}),
    ("nanmean", lambda x, axis=None, keepdim=False:
        np.nanmean(x, axis=axis, keepdims=keepdim), [NANX], {"axis": 1}),
    ("nansum", lambda x, axis=None, keepdim=False:
        np.nansum(x, axis=axis, keepdims=keepdim), [NANX], {"axis": 1}),
    ("nanmedian", lambda x, axis=None, keepdim=False:
        np.nanmedian(x, axis=axis, keepdims=keepdim), [NANX], {"axis": 1}),
    ("quantile", lambda x, q, axis=None, keepdim=False,
        interpolation="linear": np.quantile(
            x, q, axis=axis, keepdims=keepdim, method=interpolation),
     [A], {"q": 0.3, "axis": 1}),
    ("nanquantile", lambda x, q, axis=None, keepdim=False,
        interpolation="linear": np.nanquantile(
            x, q, axis=axis, keepdims=keepdim, method=interpolation),
     [NANX], {"q": 0.3, "axis": 1}),
    ("kthvalue", _kthvalue_ref, [MODEX], {"k": 2, "axis": -1}),
    ("mode", _mode_ref, [MODEX], {"axis": -1}),
    ("histogram", lambda x, bins=100, min=0.0, max=0.0:  # noqa: A002
        np.histogram(x, bins, (min, max))[0], [C], {
            "bins": 5, "min": 0.0, "max": 1.0}),
    ("bincount", lambda x, weights=None, minlength=0:
        np.bincount(x, weights, minlength), [I32A.reshape(-1) % 6],
     {"minlength": 8}),
    ("histogramdd", None, [HDD],
     {"bins": 4, "ranges": [[0.0, 1.0], [0.0, 1.0]]}),
]


def _fill_refs9():
    def _hdd_ref(x, bins=10, ranges=None, density=False, weights=None):
        h, edges = np.histogramdd(x, bins, ranges, density=density,
                                  weights=weights)
        return (h,) + tuple(e.astype(np.float32) for e in edges)

    refs = {"histogramdd": _hdd_ref}
    return [(n, r or refs[n], i, k) for n, r, i, k in CASES9]


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs9(), ids=[c[0] for c in CASES9])
def test_op_batch9(name, ref, inputs, kwargs):
    # order statistics are selection ops (FD crosses ties); NaN inputs
    # break FD entirely — grads for these live with the smooth reductions
    # already covered in batches 1-2
    OpTest(name, ref, inputs, kwargs, check_grad=False,
           bf16=name in {"nansum", "nanmean"},
           fp16=name in {"nansum", "nanmean"}).run()


# unique_consecutive is eager-only (data-dependent output shape); the
# harness asserts the static capture refuses cleanly and skips jit
def test_op_unique_consecutive():
    OpTest("unique_consecutive", _unique_consecutive_ref,
           [np.array([1., 1., 2., 2., 3., 1.], np.float32)], {},
           check_grad=False, bf16=False, fp16=False).run()


# ===================================================================
# batch 10 (r5): nn structural ops — convs, pools, norms, vision shapes
# ===================================================================

NCHW = R.randn(2, 4, 6, 6).astype(np.float32)
NCL = R.randn(2, 3, 8).astype(np.float32)
NCDHW = R.randn(1, 2, 4, 4, 4).astype(np.float32)
W2D = R.randn(5, 4, 3, 3).astype(np.float32) * 0.3   # (out, in, kh, kw)
W1D = R.randn(4, 3, 3).astype(np.float32) * 0.3
W3D = R.randn(3, 2, 2, 2, 2).astype(np.float32) * 0.3
WT2D = R.randn(4, 5, 3, 3).astype(np.float32) * 0.3  # (in, out, kh, kw)
WT1D = R.randn(3, 4, 3).astype(np.float32) * 0.3
WT3D = R.randn(2, 3, 2, 2, 2).astype(np.float32) * 0.3


def _win_starts(size, k, st):
    return range(0, size - k + 1, st)


def _pool2d_ref(x, k, st, pad, mode, count_include_pad=True):
    n, c, h, w = x.shape
    fill = 0.0 if mode != "max" else -np.inf
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)),
                constant_values=fill)
    hp, wp = h + 2 * pad, w + 2 * pad
    oh = (hp - k) // st + 1
    ow = (wp - k) // st + 1
    out = np.zeros((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * st:i * st + k, j * st:j * st + k]
            if mode == "max":
                out[:, :, i, j] = win.max((-1, -2))
            elif mode == "avg":
                if count_include_pad:
                    out[:, :, i, j] = win.mean((-1, -2))
                else:
                    cnt = np.ones((hp, wp))
                    cnt[:pad] = cnt[hp - pad:] = 0
                    cnt[:, :pad] = cnt[:, wp - pad:] = 0
                    c_ij = cnt[i * st:i * st + k, j * st:j * st + k].sum()
                    out[:, :, i, j] = win.sum((-1, -2)) / c_ij
            else:   # lp
                out[:, :, i, j] = (win ** mode).sum((-1, -2)) ** (1 / mode)
    return out


def _pool1d_ref(x, k, st, pad, mode):
    out = _pool2d_ref(x[:, :, None, :], 1 if mode == "max" else 1, 1, 0,
                      "max") if False else None
    n, c, l = x.shape
    fill = -np.inf if mode == "max" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad)), constant_values=fill)
    lp = l + 2 * pad
    ol = (lp - k) // st + 1
    out = np.zeros((n, c, ol), np.float32)
    for i in range(ol):
        win = xp[:, :, i * st:i * st + k]
        if mode == "max":
            out[:, :, i] = win.max(-1)
        elif mode == "avg":
            out[:, :, i] = win.mean(-1)
        else:
            out[:, :, i] = (win ** mode).sum(-1) ** (1 / mode)
    return out


def _adaptive_starts(in_size, out_size):
    return [(int(np.floor(i * in_size / out_size)),
             int(np.ceil((i + 1) * in_size / out_size)))
            for i in range(out_size)]


def _adaptive_pool_ref(x, output_size, mode, ndim):
    spatial = x.shape[2:]
    if np.isscalar(output_size):
        output_size = (output_size,) * ndim
    out_shape = x.shape[:2] + tuple(output_size)
    out = np.zeros(out_shape, np.float32)
    bounds = [_adaptive_starts(s, o) for s, o in zip(spatial, output_size)]
    for idx in np.ndindex(*output_size):
        sl = (slice(None), slice(None)) + tuple(
            slice(bounds[d][idx[d]][0], bounds[d][idx[d]][1])
            for d in range(ndim))
        axes = tuple(range(2, 2 + ndim))
        red = x[sl].max(axes) if mode == "max" else x[sl].mean(axes)
        out[(slice(None), slice(None)) + idx] = red
    return out


def _conv2d_ref(x, w, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NCHW"):
    n, cin, h, ww = x.shape
    cout, cing, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (padding, padding),
                    (padding, padding)))
    hp, wp = h + 2 * padding, ww + 2 * padding
    ekh = (kh - 1) * dilation + 1
    ekw = (kw - 1) * dilation + 1
    oh = (hp - ekh) // stride + 1
    ow = (wp - ekw) // stride + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    cpg_out = cout // groups
    for g in range(groups):
        for oc in range(g * cpg_out, (g + 1) * cpg_out):
            for i in range(oh):
                for j in range(ow):
                    acc = 0.0
                    for ic in range(cing):
                        for a in range(kh):
                            for b in range(kw):
                                acc += (xp[:, g * cing + ic,
                                           i * stride + a * dilation,
                                           j * stride + b * dilation]
                                        * w[oc, ic, a, b])
                    out[:, oc, i, j] = acc
    if bias is not None:
        out += bias[None, :, None, None]
    return out


def _conv1d_ref(x, w, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NCL"):
    out = _conv2d_ref(x[:, :, None, :], w[:, :, None, :], bias, stride,
                      0, dilation, groups)
    if padding:
        xp = np.pad(x, ((0, 0), (0, 0), (padding, padding)))
        return _conv1d_ref(xp, w, bias, stride, 0, dilation, groups)
    return out[:, :, 0, :]


def _conv3d_ref(x, w, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NCDHW"):
    n, cin, d, h, ww = x.shape
    cout, cing, kd, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0)) + ((padding, padding),) * 3)
    od = (d + 2 * padding - kd) // stride + 1
    oh = (h + 2 * padding - kh) // stride + 1
    ow = (ww + 2 * padding - kw) // stride + 1
    out = np.zeros((n, cout, od, oh, ow), np.float32)
    for oc in range(cout):
        for zi in range(od):
            for i in range(oh):
                for j in range(ow):
                    win = xp[:, :, zi * stride:zi * stride + kd,
                             i * stride:i * stride + kh,
                             j * stride:j * stride + kw]
                    out[:, oc, zi, i, j] = (win * w[oc]).sum((1, 2, 3, 4))
    if bias is not None:
        out += bias[None, :, None, None, None]
    return out


def _conv_transpose2d_ref(x, w, bias=None, stride=1, padding=0,
                          output_padding=0, dilation=1, groups=1,
                          data_format="NCHW"):
    n, cin, h, ww = x.shape
    cing, coutg, kh, kw = w.shape
    cout = coutg * groups
    oh = (h - 1) * stride - 2 * padding + (kh - 1) * dilation + 1 \
        + output_padding
    ow = (ww - 1) * stride - 2 * padding + (kw - 1) * dilation + 1 \
        + output_padding
    full = np.zeros((n, cout, oh + 2 * padding, ow + 2 * padding),
                    np.float32)
    cpg_in = cin // groups
    for g in range(groups):
        for ic in range(g * cpg_in, (g + 1) * cpg_in):
            for oc in range(coutg):
                for i in range(h):
                    for j in range(ww):
                        for a in range(kh):
                            for b in range(kw):
                                full[:, g * coutg + oc,
                                     i * stride + a * dilation,
                                     j * stride + b * dilation] += (
                                    x[:, ic, i, j] * w[ic, oc, a, b])
    out = full[:, :, padding:padding + oh, padding:padding + ow]
    if bias is not None:
        out += bias[None, :, None, None]
    return out


def _conv_transpose1d_ref(x, w, bias=None, stride=1, padding=0,
                          output_padding=0, groups=1, dilation=1,
                          data_format="NCL"):
    out = _conv_transpose2d_ref(x[:, :, None, :], w[:, :, None, :], bias,
                                stride, padding, output_padding, dilation,
                                groups)
    return out[:, :, 0, :] if padding == 0 else out[:, :, 0, :]


def _conv_transpose3d_ref(x, w, bias=None, stride=1, padding=0,
                          output_padding=0, groups=1, dilation=1,
                          data_format="NCDHW"):
    n, cin, d, h, ww = x.shape
    cing, coutg, kd, kh, kw = w.shape
    cout = coutg * groups
    od = (d - 1) * stride - 2 * padding + kd + output_padding
    oh = (h - 1) * stride - 2 * padding + kh + output_padding
    ow = (ww - 1) * stride - 2 * padding + kw + output_padding
    full = np.zeros((n, cout, od + 2 * padding, oh + 2 * padding,
                     ow + 2 * padding), np.float32)
    for ic in range(cin):
        for oc in range(coutg):
            for zi in range(d):
                for i in range(h):
                    for j in range(ww):
                        full[:, oc, zi * stride:zi * stride + kd,
                             i * stride:i * stride + kh,
                             j * stride:j * stride + kw] += (
                            x[:, ic, zi, i, j, None, None, None]
                            * w[ic, oc])
    out = full[:, :, padding:padding + od, padding:padding + oh,
               padding:padding + ow]
    if bias is not None:
        out += bias[None, :, None, None, None]
    return out


def _group_norm_ref(x, num_groups, weight=None, bias=None, epsilon=1e-5,
                    data_format="NCHW"):
    n, c = x.shape[:2]
    xg = x.reshape(n, num_groups, -1)
    mu = xg.mean(-1, keepdims=True)
    var = xg.var(-1, keepdims=True)
    out = ((xg - mu) / np.sqrt(var + epsilon)).reshape(x.shape)
    if weight is not None:
        out = out * weight.reshape((1, c) + (1,) * (x.ndim - 2))
    if bias is not None:
        out = out + bias.reshape((1, c) + (1,) * (x.ndim - 2))
    return out


def _instance_norm_ref(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mu = x.mean(axes, keepdims=True)
    var = x.var(axes, keepdims=True)
    out = (x - mu) / np.sqrt(var + epsilon)
    c = x.shape[1]
    if weight is not None:
        out = out * weight.reshape((1, c) + (1,) * (x.ndim - 2))
    if bias is not None:
        out = out + bias.reshape((1, c) + (1,) * (x.ndim - 2))
    return out


def _batch_norm_train_ref(x, weight=None, bias=None, epsilon=1e-5,
                          data_format="NCHW"):
    axes = (0,) + tuple(range(2, x.ndim))
    mu = x.mean(axes)
    var = x.var(axes)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    out = (x - mu.reshape(shape)) / np.sqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mu, var


def _batch_norm_infer_ref(x, running_mean, running_var, weight=None,
                          bias=None, epsilon=1e-5, data_format="NCHW"):
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    out = (x - running_mean.reshape(shape)) / np.sqrt(
        running_var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def _lrn_ref(x, size, alpha=1e-4, beta=0.75, k=1.0):
    n, c, h, w = x.shape
    sq = x ** 2
    out = np.zeros_like(x)
    half = size // 2
    for ci in range(c):
        lo, hi = max(0, ci - half), min(c, ci + half + (size - 2 * half))
        s = sq[:, lo:hi].sum(1)
        out[:, ci] = x[:, ci] / (k + alpha * s) ** beta
    return out


def _unfold_ref(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    n, c, h, w = x.shape
    kh = kw = kernel_sizes
    xp = np.pad(x, ((0, 0), (0, 0), (paddings, paddings),
                    (paddings, paddings)))
    oh = (h + 2 * paddings - kh) // strides + 1
    ow = (w + 2 * paddings - kw) // strides + 1
    cols = np.zeros((n, c * kh * kw, oh * ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * strides:i * strides + kh,
                       j * strides:j * strides + kw]
            cols[:, :, i * ow + j] = patch.reshape(n, -1)
    return cols


def _fold_ref(x, output_sizes, kernel_sizes, strides=1, paddings=0,
              dilations=1):
    n, ckk, loc = x.shape
    oh_img, ow_img = output_sizes
    kh = kw = kernel_sizes
    c = ckk // (kh * kw)
    oh = (oh_img + 2 * paddings - kh) // strides + 1
    ow = (ow_img + 2 * paddings - kw) // strides + 1
    full = np.zeros((n, c, oh_img + 2 * paddings, ow_img + 2 * paddings),
                    np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * ow + j].reshape(n, c, kh, kw)
            full[:, :, i * strides:i * strides + kh,
                 j * strides:j * strides + kw] += patch
    return full[:, :, paddings:paddings + oh_img,
                paddings:paddings + ow_img]


def _max_pool2d_with_index_ref(x, kernel_size, stride=None, padding=0,
                               ceil_mode=False, data_format="NCHW"):
    k = kernel_size
    st = stride if stride is not None else k
    n, c, h, w = x.shape
    vals = _pool2d_ref(x, k, st, padding, "max")
    oh, ow = vals.shape[2:]
    idxs = np.zeros((n, c, oh, ow), np.int64)
    xp = np.pad(x, ((0, 0), (0, 0), (padding, padding),
                    (padding, padding)), constant_values=-np.inf)
    for ni in range(n):
        for ci in range(c):
            for i in range(oh):
                for j in range(ow):
                    win = xp[ni, ci, i * st:i * st + k, j * st:j * st + k]
                    a, b = np.unravel_index(np.argmax(win), win.shape)
                    # flat index in the UNPADDED h*w plane
                    idxs[ni, ci, i, j] = ((i * st + a - padding) * w
                                          + (j * st + b - padding))
    return vals, idxs


def _max_unpool2d_ref(x, indices, kernel_size, stride=None, padding=0,
                      output_size=None, data_format="NCHW"):
    st = stride if stride is not None else kernel_size
    n, c, oh, ow = x.shape
    if output_size is None:
        h = (oh - 1) * st - 2 * padding + kernel_size
        w = (ow - 1) * st - 2 * padding + kernel_size
    else:
        h, w = output_size
    out = np.zeros((n, c, h * w), np.float32)
    for ni in range(n):
        for ci in range(c):
            out[ni, ci, indices[ni, ci].reshape(-1)] = \
                x[ni, ci].reshape(-1)
    return out.reshape(n, c, h, w)


_MPI = _max_pool2d_with_index_ref(NCHW, 2)

CASES10 = [
    ("avg_pool2d", lambda x, kernel_size, stride=None, padding=0,
        ceil_mode=False, count_include_pad=True, data_format="NCHW":
        _pool2d_ref(x, kernel_size,
                    stride if stride is not None else kernel_size,
                    padding, "avg", count_include_pad),
     [NCHW], {"kernel_size": 2, "stride": 2, "padding": 1}),
    ("max_pool2d", lambda x, kernel_size, stride=None, padding=0,
        ceil_mode=False, data_format="NCHW":
        _pool2d_ref(x, kernel_size,
                    stride if stride is not None else kernel_size,
                    padding, "max"),
     [NCHW], {"kernel_size": 2}),
    ("lp_pool2d", lambda x, norm_type, kernel_size, stride=None,
        padding=0, ceil_mode=False, data_format="NCHW":
        _pool2d_ref(x, kernel_size,
                    stride if stride is not None else kernel_size,
                    padding, norm_type),
     [np.abs(NCHW) + 0.1], {"norm_type": 2.0, "kernel_size": 2}),
    ("avg_pool1d", lambda x, kernel_size, stride=None, padding=0,
        ceil_mode=False: _pool1d_ref(
            x, kernel_size, stride if stride is not None else kernel_size,
            padding, "avg"),
     [NCL], {"kernel_size": 2}),
    ("max_pool1d", lambda x, kernel_size, stride=None, padding=0,
        ceil_mode=False: _pool1d_ref(
            x, kernel_size, stride if stride is not None else kernel_size,
            padding, "max"),
     [NCL], {"kernel_size": 2}),
    ("lp_pool1d", lambda x, norm_type, kernel_size, stride=None,
        padding=0, ceil_mode=False, data_format="NCL": _pool1d_ref(
            np.abs(NCL) + 0.1, kernel_size,
            stride if stride is not None else kernel_size, padding,
            norm_type),
     [np.abs(NCL) + 0.1], {"norm_type": 2.0, "kernel_size": 2}),
    ("avg_pool3d", None, [NCDHW], {"kernel_size": 2}),
    ("max_pool3d", None, [NCDHW], {"kernel_size": 2}),
    ("adaptive_avg_pool2d", lambda x, output_size, data_format="NCHW":
        _adaptive_pool_ref(x, output_size, "avg", 2),
     [NCHW], {"output_size": 3}),
    ("adaptive_max_pool2d", lambda x, output_size, data_format="NCHW":
        _adaptive_pool_ref(x, output_size, "max", 2),
     [NCHW], {"output_size": 3}),
    ("adaptive_avg_pool1d", lambda x, output_size:
        _adaptive_pool_ref(x, output_size, "avg", 1),
     [NCL], {"output_size": 3}),
    ("adaptive_max_pool1d", lambda x, output_size:
        _adaptive_pool_ref(x, output_size, "max", 1),
     [NCL], {"output_size": 3}),
    ("adaptive_avg_pool3d", lambda x, output_size, data_format="NCDHW":
        _adaptive_pool_ref(x, output_size, "avg", 3),
     [NCDHW], {"output_size": 2}),
    ("adaptive_max_pool3d", lambda x, output_size:
        _adaptive_pool_ref(x, output_size, "max", 3),
     [NCDHW], {"output_size": 2}),
    ("max_pool2d_with_index", _max_pool2d_with_index_ref, [NCHW],
     {"kernel_size": 2}),
    ("max_unpool2d", _max_unpool2d_ref, [_MPI[0], _MPI[1]],
     {"kernel_size": 2}),
    ("conv2d", _conv2d_ref, [NCHW[:, :4], W2D], {"stride": 1,
                                                 "padding": 1}),
    ("conv1d", _conv1d_ref, [NCL, W1D], {"padding": 1}),
    ("conv3d", _conv3d_ref, [NCDHW, W3D], {}),
    ("conv2d_transpose", _conv_transpose2d_ref, [NCHW[:, :4], WT2D],
     {"stride": 2, "padding": 1}),
    ("conv1d_transpose", _conv_transpose1d_ref, [NCL, WT1D],
     {"stride": 2}),
    ("conv3d_transpose", _conv_transpose3d_ref, [NCDHW, WT3D], {}),
    ("group_norm", lambda x, num_groups, weight=None, bias=None:
        _group_norm_ref(x, num_groups, weight, bias), [NCHW],
     {"num_groups": 2, "weight": np.ones(4, np.float32) * 1.3,
      "bias": np.zeros(4, np.float32) + 0.1}),
    ("instance_norm", _instance_norm_ref,
     [NCHW, np.ones(4, np.float32) * 1.3, np.zeros(4, np.float32) + 0.1],
     {}),
    ("batch_norm_train", _batch_norm_train_ref,
     [NCHW, np.ones(4, np.float32) * 1.3, np.zeros(4, np.float32) + 0.1],
     {}),
    ("batch_norm_infer", _batch_norm_infer_ref,
     [NCHW, R.rand(4).astype(np.float32), np.abs(R.rand(4)).astype(
         np.float32) + 0.5, np.ones(4, np.float32),
      np.zeros(4, np.float32)], {}),
    ("local_response_norm", _lrn_ref, [NCHW], {"size": 3}),
    ("layer_norm", None,
     [A, np.ones(4, np.float32) * 1.2, np.zeros(4, np.float32) + 0.1],
     {}),
    ("rms_norm", None, [A, np.ones(4, np.float32) * 1.2], {}),
    ("unfold", _unfold_ref, [NCHW], {"kernel_sizes": 2, "strides": 2}),
    ("fold", _fold_ref, [_unfold_ref(NCHW, 2, 2)],
     {"output_sizes": [6, 6], "kernel_sizes": 2, "strides": 2}),
    ("channel_shuffle", None, [NCHW], {"groups": 2}),
    ("pixel_unshuffle", None, [NCHW], {"downscale_factor": 2}),
    ("temporal_shift", None, [NCHW], {"seg_num": 2}),
    ("maxout", None, [NCHW], {"groups": 2}),
    ("interpolate", None, [NCHW], {"scale_factor": 2, "mode": "nearest"}),
]


def _fill_refs10():
    def _layer_norm_ref(x, weight=None, bias=None, epsilon=1e-5,
                        begin_norm_axis=-1):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        out = (x - mu) / np.sqrt(var + epsilon)
        if weight is not None:
            out = out * weight
        if bias is not None:
            out = out + bias
        return out

    def _rms_norm_ref(x, weight=None, epsilon=1e-6):
        ms = (x ** 2).mean(-1, keepdims=True)
        out = x / np.sqrt(ms + epsilon)
        return out * weight if weight is not None else out

    def _channel_shuffle_ref(x, groups, data_format="NCHW"):
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w).transpose(
            0, 2, 1, 3, 4).reshape(n, c, h, w)

    def _pixel_unshuffle_ref(x, downscale_factor, data_format="NCHW"):
        r = downscale_factor
        n, c, h, w = x.shape
        out = x.reshape(n, c, h // r, r, w // r, r)
        return out.transpose(0, 1, 3, 5, 2, 4).reshape(
            n, c * r * r, h // r, w // r)

    def _temporal_shift_ref(x, seg_num, shift_ratio=0.25,
                            data_format="NCHW"):
        nt, c, h, w = x.shape
        n = nt // seg_num
        x5 = x.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        out = np.zeros_like(x5)
        out[:, :-1, :fold] = x5[:, 1:, :fold]
        out[:, 1:, fold:2 * fold] = x5[:, :-1, fold:2 * fold]
        out[:, :, 2 * fold:] = x5[:, :, 2 * fold:]
        return out.reshape(nt, c, h, w)

    def _maxout_ref(x, groups, axis=1):
        n, c, h, w = x.shape
        return x.reshape(n, c // groups, groups, h, w).max(2)

    def _interp_nearest_ref(x, size=None, scale_factor=None,
                            mode="nearest", align_corners=False,
                            data_format="NCHW"):
        n, c, h, w = x.shape
        oh, ow = int(h * scale_factor), int(w * scale_factor)
        ih = (np.arange(oh) * (h / oh)).astype(np.int64)
        iw = (np.arange(ow) * (w / ow)).astype(np.int64)
        return x[:, :, ih][:, :, :, iw]

    refs = {
        "avg_pool3d": lambda x, kernel_size, stride=None, padding=0,
        ceil_mode=False, count_include_pad=True, data_format="NCDHW":
            _adaptive_pool_ref(x, x.shape[2] // kernel_size, "avg", 3),
        "max_pool3d": lambda x, kernel_size, stride=None, padding=0,
        ceil_mode=False, data_format="NCDHW":
            _adaptive_pool_ref(x, x.shape[2] // kernel_size, "max", 3),
        "layer_norm": _layer_norm_ref,
        "rms_norm": _rms_norm_ref,
        "channel_shuffle": _channel_shuffle_ref,
        "pixel_unshuffle": _pixel_unshuffle_ref,
        "temporal_shift": _temporal_shift_ref,
        "maxout": _maxout_ref,
        "interpolate": _interp_nearest_ref,
    }
    return [(n, r or refs[n], i, k) for n, r, i, k in CASES10]


# FD on maxes crosses selection ties; convs/norms keep full grad checks
_GRAD10 = {"avg_pool2d", "avg_pool1d", "conv2d", "conv1d", "conv3d",
           "conv2d_transpose", "conv1d_transpose", "conv3d_transpose",
           "group_norm", "instance_norm", "layer_norm", "rms_norm",
           "unfold", "fold", "channel_shuffle", "pixel_unshuffle",
           "temporal_shift", "local_response_norm"}
_NO_LOWP10 = {"max_pool2d_with_index", "max_unpool2d", "batch_norm_train",
              "batch_norm_infer", "local_response_norm", "interpolate"}


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs10(), ids=[c[0] for c in CASES10])
def test_op_batch10(name, ref, inputs, kwargs):
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in _GRAD10,
           bf16=name not in _NO_LOWP10, fp16=name not in _NO_LOWP10,
           rtol=2e-4, atol=2e-4).run()


# ===================================================================
# batch 11 (r5): losses, attention, embedding, sampling grids
# ===================================================================

LOGITS = R.randn(4, 5).astype(np.float32)
LBL_I = R.randint(0, 5, (4,)).astype(np.int64)
PROB01 = (R.rand(4, 5) * 0.8 + 0.1).astype(np.float32)
LBL01 = (R.rand(4, 5) > 0.5).astype(np.float32)
PM1 = np.where(R.rand(4) > 0.5, 1.0, -1.0).astype(np.float32)
EMB_W = R.randn(7, 5).astype(np.float32)
EMB_I = R.randint(0, 7, (2, 3)).astype(np.int64)
QKV = R.randn(2, 6, 2, 4).astype(np.float32) * 0.5


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _reduce_np(loss, reduction):
    if reduction == "none":
        return loss
    return loss.sum() if reduction == "sum" else loss.mean()


def _cross_entropy_ref(logits, label, weight=None, soft_label=False,
                       axis=-1, ignore_index=-100, reduction="mean",
                       label_smoothing=0.0):
    p = _softmax_np(logits, axis)
    logp = np.log(p)
    nll = -logp[np.arange(len(label)), label]
    return _reduce_np(nll, reduction)


def _nll_loss_ref(logp, label, weight=None, ignore_index=-100,
                  reduction="mean"):
    nll = -logp[np.arange(len(label)), label]
    return _reduce_np(nll, reduction)


def _ctc_ref(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    from scipy.special import logsumexp
    T, B, C = log_probs.shape
    nlls = np.zeros(B, np.float64)
    for b in range(B):
        Tl, L = int(input_lengths[b]), int(label_lengths[b])
        ext = [blank]
        for y in labels[b][:L]:
            ext += [int(y), blank]
        S = len(ext)
        alpha = np.full(S, -np.inf)
        alpha[0] = log_probs[0, b, blank]
        if S > 1:
            alpha[1] = log_probs[0, b, ext[1]]
        for t in range(1, Tl):
            new = np.full(S, -np.inf)
            for s in range(S):
                cands = [alpha[s]]
                if s >= 1:
                    cands.append(alpha[s - 1])
                if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                    cands.append(alpha[s - 2])
                new[s] = logsumexp(cands) + log_probs[t, b, ext[s]]
            alpha = new
        nlls[b] = -logsumexp([alpha[S - 1], alpha[S - 2]])
    if reduction == "mean":     # warpctc: nll/label_len, then batch mean
        return np.float32(np.mean(nlls / np.maximum(label_lengths, 1)))
    return np.float32(_reduce_np(nlls, reduction))


def _rnnt_ref(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean"):
    from scipy.special import log_softmax, logsumexp
    logp = log_softmax(input.astype(np.float64), axis=-1)
    B, T, U1, V = logp.shape
    nlls = np.zeros(B, np.float64)
    for b in range(B):
        Tl, U = int(input_lengths[b]), int(label_lengths[b])
        alpha = np.full((Tl, U + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(Tl):
            for u in range(U + 1):
                cands = [alpha[t, u]] if t == 0 and u == 0 else []
                if t > 0:
                    cands.append(alpha[t - 1, u]
                                 + logp[b, t - 1, u, blank])
                if u > 0:
                    cands.append(alpha[t, u - 1]
                                 + logp[b, t, u - 1, label[b, u - 1]])
                if cands:
                    alpha[t, u] = logsumexp(cands)
        nlls[b] = -(alpha[Tl - 1, U] + logp[b, Tl - 1, U, blank])
    return np.float32(_reduce_np(nlls, reduction))


def _affine_grid_ref(theta, out_shape, align_corners=True):
    n, _, h, w = out_shape
    if align_corners:
        xs = np.linspace(-1, 1, w)
        ys = np.linspace(-1, 1, h)
    else:
        xs = (np.arange(w) + 0.5) * 2 / w - 1
        ys = (np.arange(h) + 0.5) * 2 / h - 1
    gx, gy = np.meshgrid(xs, ys)
    base = np.stack([gx, gy, np.ones_like(gx)], -1)       # (h, w, 3)
    return np.einsum("nij,hwj->nhwi", theta, base).astype(np.float32)


def _grid_sample_ref(x, grid, mode="bilinear", padding_mode="zeros",
                     align_corners=True):
    n, c, h, w = x.shape
    _, oh, ow, _ = grid.shape
    out = np.zeros((n, c, oh, ow), np.float32)
    for ni in range(n):
        for i in range(oh):
            for j in range(ow):
                gx, gy = grid[ni, i, j]
                if align_corners:
                    fx = (gx + 1) / 2 * (w - 1)
                    fy = (gy + 1) / 2 * (h - 1)
                else:
                    fx = ((gx + 1) * w - 1) / 2
                    fy = ((gy + 1) * h - 1) / 2
                x0, y0 = int(np.floor(fx)), int(np.floor(fy))
                for dy in (0, 1):
                    for dx in (0, 1):
                        xx, yy = x0 + dx, y0 + dy
                        wgt = ((1 - abs(fx - xx)) * (1 - abs(fy - yy)))
                        if 0 <= xx < w and 0 <= yy < h and wgt > 0:
                            out[ni, :, i, j] += wgt * x[ni, :, yy, xx]
    return out


def _rope_ref(q, k, theta=10000.0, position_offset=0):
    b, s, h, d = q.shape
    half = d // 2
    freqs = 1.0 / (theta ** (np.arange(half) * 2.0 / d))
    ang = (np.arange(s) + position_offset)[:, None] * freqs[None, :]
    cos = np.cos(ang)[None, :, None, :]
    sin = np.sin(ang)[None, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        return np.concatenate([x1 * cos - x2 * sin,
                               x2 * cos + x1 * sin], -1).astype(np.float32)
    return rot(q), rot(k)


def _sdpa_ref(q, k, v, attn_mask=None, rng_key=None, dropout_p=0.0,
              is_causal=False, scale=None):
    b, sq, h, d = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    logits = np.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if is_causal:
        mask = np.tril(np.ones((sq, sq), bool))
        logits = np.where(mask, logits, -np.inf)
    p = _softmax_np(logits, -1)
    out = np.einsum("bhqk,bhkd->bhqd", p, vh)
    return out.transpose(0, 2, 1, 3)


CASES11 = [
    ("binary_cross_entropy", lambda i, l, weight=None, reduction="mean":
        _reduce_np(-(l * np.log(i) + (1 - l) * np.log(1 - i)), reduction),
     [PROB01, LBL01], {}),
    ("binary_cross_entropy_with_logits",
     lambda x, l, weight=None, reduction="mean", pos_weight=None:
        _reduce_np(np.maximum(x, 0) - x * l + np.log1p(np.exp(-np.abs(x))),
                   reduction), [LOGITS, LBL01], {}),
    ("cross_entropy", _cross_entropy_ref, [LOGITS, LBL_I], {}),
    ("fused_linear_cross_entropy",
     lambda x, w, bias=None, label=None, ignore_index=-100,
            transpose_y=False, reduction="mean", chunk_size=2048:
        _cross_entropy_ref(x @ w + bias, label, reduction=reduction),
     # private RNG: drawing from the shared R here would shift every
     # later case's inputs (grid_sample's FD check broke exactly so)
     [np.random.RandomState(77).randn(4, 3).astype(np.float32),
      np.random.RandomState(78).randn(3, 5).astype(np.float32),
      np.random.RandomState(79).randn(5).astype(np.float32), LBL_I],
     {"chunk_size": 3}),
    ("nll_loss", _nll_loss_ref,
     [np.log(_softmax_np(LOGITS)), LBL_I], {}),
    ("kl_div", lambda i, l, reduction="mean", log_target=False:
        _reduce_np(l * (np.log(l) - i), reduction),
     [np.log(PROB01), PROB01[::-1].copy()], {"reduction": "sum"}),
    ("l1_loss", lambda i, l, reduction="mean":
        _reduce_np(np.abs(i - l), reduction), [A, B], {}),
    ("mse_loss", lambda i, l, reduction="mean":
        _reduce_np((i - l) ** 2, reduction), [A, B], {}),
    ("smooth_l1_loss", lambda i, l, reduction="mean", delta=1.0:
        _reduce_np(np.where(np.abs(i - l) < delta,
                            0.5 * (i - l) ** 2 / delta,
                            np.abs(i - l) - 0.5 * delta), reduction),
     [A, B], {}),
    ("huber_loss", lambda i, l, delta=1.0, reduction="mean":
        _reduce_np(np.where(np.abs(i - l) <= delta, 0.5 * (i - l) ** 2,
                            delta * (np.abs(i - l) - 0.5 * delta)),
                   reduction), [A, B], {}),
    ("soft_margin_loss", lambda i, l, reduction="mean":
        _reduce_np(np.log1p(np.exp(-l * i)), reduction),
     [LOGITS, np.where(LBL01[:, :5] > 0, 1., -1.).astype(np.float32)],
     {}),
    ("hinge_embedding_loss", lambda i, l, margin=1.0, reduction="mean":
        _reduce_np(np.where(l == 1, i, np.maximum(0, margin - i)),
                   reduction),
     [np.abs(LOGITS), np.where(LBL01[:, :5] > 0, 1., -1.).astype(
         np.float32)], {}),
    ("margin_ranking_loss",
     lambda i, o, l, margin=0.0, reduction="mean":
        _reduce_np(np.maximum(0, -l * (i - o) + margin), reduction),
     [A[0], B[0], PM1], {"margin": 0.1}),
    ("cosine_embedding_loss",
     lambda x1, x2, l, margin=0.0, reduction="mean": _reduce_np(
         np.where(l == 1,
                  1 - (x1 * x2).sum(-1)
                  / (np.linalg.norm(x1, axis=-1)
                     * np.linalg.norm(x2, axis=-1)),
                  np.maximum(0, (x1 * x2).sum(-1)
                             / (np.linalg.norm(x1, axis=-1)
                                * np.linalg.norm(x2, axis=-1)) - margin)),
         reduction), [LOGITS, LOGITS[::-1].copy(), PM1], {}),
    ("triplet_margin_loss",
     lambda a, p, n, margin=1.0, p_=2.0, epsilon=1e-6, swap=False,
     reduction="mean", **kw: _reduce_np(
         np.maximum(0, np.linalg.norm(a - p, axis=-1)
                    - np.linalg.norm(a - n, axis=-1) + margin),
         reduction), [LOGITS, LOGITS * 0.5, LOGITS[::-1].copy()], {}),
    ("multi_label_soft_margin_loss",
     lambda i, l, weight=None, reduction="mean": _reduce_np(
         -(l * np.log(1 / (1 + np.exp(-i)))
           + (1 - l) * np.log(1 - 1 / (1 + np.exp(-i)))).mean(-1),
         reduction), [LOGITS, LBL01[:, :5]], {}),
    ("gaussian_nll_loss",
     lambda i, l, var, full=False, epsilon=1e-6, reduction="mean":
        _reduce_np(0.5 * (np.log(np.maximum(var, epsilon))
                          + (i - l) ** 2 / np.maximum(var, epsilon)),
                   reduction), [A, B, np.abs(C) + 0.5], {}),
    ("poisson_nll_loss",
     lambda i, l, log_input=True, full=False, epsilon=1e-8,
     reduction="mean": _reduce_np(np.exp(i) - l * i, reduction),
     [A, np.abs(B)], {}),
    ("dice_loss", lambda i, l, epsilon=1e-5: np.mean(
        1 - (2 * np.take_along_axis(i, l, -1)[:, 0] + epsilon)
        / (i.sum(-1) + 1 + epsilon)),
     [PROB01, LBL_I[:, None]], {}),
    ("sigmoid_focal_loss",
     lambda logit, l, normalizer=None, alpha=0.25, gamma=2.0,
     reduction="sum": _reduce_np(
         -(alpha * l * ((1 - 1 / (1 + np.exp(-logit))) ** gamma)
           * np.log(1 / (1 + np.exp(-logit)))
           + (1 - alpha) * (1 - l) * ((1 / (1 + np.exp(-logit))) ** gamma)
           * np.log(1 - 1 / (1 + np.exp(-logit)))), reduction),
     [LOGITS, LBL01[:, :5]], {}),
    ("npair_loss", None, [LOGITS, LOGITS * 0.8 + 0.1, LBL_I], {}),
    ("ctc_loss", _ctc_ref,
     [np.log(_softmax_np(R.randn(6, 2, 4).astype(np.float32))),
      np.array([[1, 2, 1], [2, 3, 0]], np.int64),
      np.array([6, 5], np.int64), np.array([3, 2], np.int64)], {}),
    ("rnnt_loss", _rnnt_ref,
     [R.randn(2, 5, 4, 4).astype(np.float32) * 0.5,
      np.array([[1, 2, 1], [2, 3, 0]], np.int64),
      np.array([5, 4], np.int64), np.array([3, 2], np.int64)], {}),
    ("margin_cross_entropy", None, [LOGITS * 0.05, LBL_I],
     {"margin1": 1.0, "margin2": 0.0, "margin3": 0.0, "scale": 2.0}),
    ("embedding", lambda ids, w, padding_idx=None, sparse=False: w[ids],
     [EMB_I, EMB_W], {}),
    ("linear", lambda x, w, b=None: x @ w + (0 if b is None else b),
     [A, M2, R.randn(5).astype(np.float32)], {}),
    ("prelu", lambda x, w: np.where(x > 0, x, x * w.reshape(1, -1, 1, 1)),
     [NCHW, np.full(4, 0.25, np.float32)], {}),
    ("cosine_similarity", lambda x1, x2, axis=1, eps=1e-8:
        (x1 * x2).sum(axis) / np.maximum(
            np.linalg.norm(x1, axis=axis) * np.linalg.norm(x2, axis=axis),
            eps), [A, B], {}),
    ("pairwise_distance", lambda x, y, p=2.0, epsilon=1e-6, keepdim=False:
        np.linalg.norm(x - y + epsilon, ord=p, axis=-1), [A, B], {}),
    ("rrelu", lambda x, lower=0.125, upper=1 / 3, training=False:
        np.where(x >= 0, x, (lower + upper) / 2 * x), [A], {}),
    ("affine_grid", _affine_grid_ref,
     [np.array([[[1.0, 0.2, 0.1], [-0.1, 0.9, -0.2]],
                [[0.8, 0.0, 0.3], [0.1, 1.1, 0.0]]], np.float32)],
     {"out_shape": [2, 3, 4, 5]}),
    ("grid_sample", _grid_sample_ref,
     [NCHW, (R.rand(2, 3, 3, 2).astype(np.float32) * 1.6 - 0.8)], {}),
    ("rotary_position_embedding", _rope_ref, [QKV, QKV * 0.5], {}),
    ("scaled_dot_product_attention", _sdpa_ref,
     [QKV, QKV * 0.8, QKV * 0.6], {"is_causal": True}),
]


def _fill_refs11():
    def _npair_ref(anchor, positive, labels, l2_reg=0.002):
        logits = anchor @ positive.T
        same = labels[:, None] == labels[None, :]
        target = same / same.sum(1, keepdims=True)
        ce = (-target * np.log(_softmax_np(logits, -1))).sum(-1).mean()
        l2 = l2_reg * ((anchor ** 2).sum(-1).mean()
                       + (positive ** 2).sum(-1).mean()) * 0.25
        return ce + l2

    def _margin_ce_ref(logits, label, margin1=1.0, margin2=0.5,
                       margin3=0.0, scale=64.0, return_softmax=False,
                       reduction="mean"):
        # arcface margins on UNIT-NORM cosine logits: cos(m1*t + m2) - m3
        theta = np.arccos(np.clip(logits, -1, 1))
        tgt = np.cos(margin1 * theta + margin2) - margin3
        out = logits.copy()
        out[np.arange(len(label)), label] = \
            tgt[np.arange(len(label)), label]
        return _cross_entropy_ref(out * scale, label,
                                  reduction=reduction)

    refs = {"npair_loss": _npair_ref,
            "margin_cross_entropy": _margin_ce_ref}
    return [(n, r or refs[n], i, k) for n, r, i, k in CASES11]


_GRAD11 = {"binary_cross_entropy", "binary_cross_entropy_with_logits",
           "cross_entropy", "nll_loss", "kl_div", "l1_loss", "mse_loss",
           "soft_margin_loss", "gaussian_nll_loss", "poisson_nll_loss",
           "dice_loss", "sigmoid_focal_loss", "npair_loss", "ctc_loss",
           "rnnt_loss", "embedding", "linear", "cosine_similarity",
           "pairwise_distance", "affine_grid", "grid_sample",
           "rotary_position_embedding", "scaled_dot_product_attention"}
_NO_LOWP11 = {"ctc_loss", "rnnt_loss", "margin_cross_entropy",
              "grid_sample", "binary_cross_entropy", "kl_div",
              "sigmoid_focal_loss", "multi_label_soft_margin_loss",
              "poisson_nll_loss", "npair_loss"}


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs11(), ids=[c[0] for c in CASES11])
def test_op_batch11(name, ref, inputs, kwargs):
    # 0/1 float labels are semantically discrete: only the prediction
    # operand gets a finite-difference grad check
    label_ops = {"sigmoid_focal_loss", "binary_cross_entropy",
                 "binary_cross_entropy_with_logits", "dice_loss",
                 "soft_margin_loss", "poisson_nll_loss"}
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in _GRAD11,
           bf16=name not in _NO_LOWP11, fp16=name not in _NO_LOWP11,
           rtol=2e-4, atol=2e-4,
           grad_inputs={0} if name in label_ops else None).run()


# ===================================================================
# batch 12 (r5): final cases + the registry coverage gate
# ===================================================================

CASES12 = [
    ("label_smooth", lambda label, epsilon=0.1, prior_dist=None:
        (1 - epsilon) * label + epsilon / label.shape[-1], [LBL01], {}),
    ("pixel_shuffle", lambda x, upscale_factor, data_format="NCHW":
        x.reshape(x.shape[0], x.shape[1] // upscale_factor ** 2,
                  upscale_factor, upscale_factor, x.shape[2], x.shape[3])
        .transpose(0, 1, 4, 2, 5, 3)
        .reshape(x.shape[0], x.shape[1] // upscale_factor ** 2,
                 x.shape[2] * upscale_factor, x.shape[3] * upscale_factor),
     [NCHW], {"upscale_factor": 2}),
    ("polar", lambda ab, an: (ab * np.exp(1j * an)).astype(np.complex64),
     [np.abs(A) + 0.1, B], {}),
    ("renorm", None, [NCHW], {"p": 2.0, "axis": 1, "max_norm": 1.5}),
]


def _fill_refs12():
    def _renorm_ref(x, p, axis, max_norm):
        moved = np.moveaxis(x, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = (np.abs(flat) ** p).sum(1) ** (1 / p)
        factor = np.where(norms > max_norm,
                          max_norm / np.maximum(norms, 1e-12), 1.0)
        return np.moveaxis((flat * factor[:, None]).reshape(moved.shape),
                           0, axis)

    return [(n, r or {"renorm": _renorm_ref}[n], i, k)
            for n, r, i, k in CASES12]


@pytest.mark.parametrize(
    "name,ref,inputs,kwargs",
    _fill_refs12(), ids=[c[0] for c in CASES12])
def test_op_batch12(name, ref, inputs, kwargs):
    OpTest(name, ref, inputs, kwargs,
           check_grad=name in {"label_smooth", "pixel_shuffle"},
           bf16=name not in {"polar", "renorm"},
           fp16=name not in {"polar", "renorm"}).run()


# ------------------------------------------------------- coverage gate
#
# Every op in the registry must either run through the OpTest harness in
# this file or appear below with a justification. A newly registered op
# that does neither FAILS CI (VERDICT r4 next #2).

HARNESS_EXCLUDED = {
    "dropout": "random output; determinism/ratio/eval-mode contracts "
               "tested in test_nn.py",
    "eig": "eigenvector gauge + eigenvalue-order freedom; "
           "reconstruction-property tested in test_linalg_fft.py "
           "(A @ v == v * w) and eigvals IS harnessed with sorted "
           "spectra",
    "pca_lowrank": "randomized algorithm; reconstruction property "
                   "tested below (test_lowrank_properties)",
    "svd_lowrank": "randomized algorithm; reconstruction property "
                   "tested below (test_lowrank_properties)",
    "set_value_by_index": "internal Tensor.__setitem__ carrier op "
                          "(takes a private index tree); exercised by "
                          "the __setitem__ suites in test_tensor.py",
    "index_put": "takes a tuple-of-index-tensors argument the positional "
                 "harness cannot express; dedicated test below "
                 "(test_index_put_semantics)",
}


def test_index_put_semantics():
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.zeros((3, 2), np.float32))
    rows = paddle.to_tensor(np.asarray([0, 2, 0]))
    cols = paddle.to_tensor(np.asarray([1, 0, 1]))
    v = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
    got = paddle.index_put(x, (rows, cols), v).numpy()
    np.testing.assert_allclose(got, [[0, 3], [0, 0], [2, 0]])
    acc = paddle.index_put(x, (rows, cols), v, accumulate=True).numpy()
    np.testing.assert_allclose(acc, [[0, 4], [0, 0], [2, 0]])
    # gradient flows into x (untouched slots) and value
    xg = paddle.to_tensor(np.ones((3, 2), np.float32),
                          stop_gradient=False)
    vg = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32),
                          stop_gradient=False)
    paddle.index_put(xg, (rows, cols), vg).sum().backward()
    assert vg.grad is not None
    np.testing.assert_allclose(vg.grad.numpy(), [0.0, 1.0, 1.0])


def test_registry_fully_harnessed():
    import re

    from paddle_tpu.ops.registry import OPS

    src = open(__file__).read()
    covered = set(re.findall(r'^\s*\("([a-z0-9_]+)",', src, re.M))
    covered |= {"unique_consecutive"}      # dedicated test above
    missing = set(OPS) - covered - set(HARNESS_EXCLUDED)
    assert not missing, (
        f"{len(missing)} registered ops have no OpTest harness entry and "
        f"no documented exclusion: {sorted(missing)}")
    stale = set(HARNESS_EXCLUDED) - set(OPS)
    assert not stale, f"exclusions for unregistered ops: {sorted(stale)}"


def test_lowrank_properties():
    """pca/svd_lowrank are randomized — check reconstruction instead of
    bitwise parity (their harness exclusion above)."""
    import paddle_tpu as paddle

    x = R.randn(20, 8).astype(np.float32) @ np.diag(
        [8, 4, 2, 1, .01, .01, .01, .01]).astype(np.float32)
    u, s, v = (t.numpy() for t in paddle.linalg.svd_lowrank(
        paddle.to_tensor(x), q=6))
    recon = u @ np.diag(np.asarray(s)) @ np.asarray(v).T
    assert np.linalg.norm(recon - x) / np.linalg.norm(x) < 0.05
    u2, s2, v2 = (t.numpy() for t in paddle.linalg.pca_lowrank(
        paddle.to_tensor(x), q=6))
    xc = x - x.mean(0)
    recon2 = np.asarray(u2) @ np.diag(np.asarray(s2)) @ np.asarray(v2).T
    assert np.linalg.norm(recon2 - xc) / np.linalg.norm(xc) < 0.05
