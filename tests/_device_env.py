"""One place for the fake-device XLA environment every test process
hand-rolled before (conftest, the multiproc workers): set
`--xla_force_host_platform_device_count=N` BEFORE jax is first imported,
then pin the platform via jax.config (env vars alone cannot undo a
sitecustomize that already pinned jax_platforms).

Import-order contract: call `ensure_fake_devices` before the first
`import jax` of the process — it imports jax itself only for the config
update, which is safe exactly because the XLA_FLAGS write happened
first.
"""
import os
from typing import Optional


def ensure_fake_devices(count: Optional[int], *, force: bool = False,
                        platform: Optional[str] = "cpu") -> None:
    """Arrange for `count` fake host devices (`count=None` leaves
    XLA_FLAGS alone — real-hardware runs emulate nothing).

    `force=False` (the conftest pattern) appends the flag only if no
    device-count flag is present, preserving an operator's explicit
    XLA_FLAGS; `force=True` (the multiproc-worker pattern) REPLACES
    XLA_FLAGS wholesale — a spawned worker must not inherit the parent
    pytest process's 8-device setup. `platform=None` skips the backend
    pin (the conftest's "axon" escape hatch).
    """
    if count is not None:
        if force:
            os.environ["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={count}")
        else:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={count}"
                ).strip()
    if platform is not None:
        import jax

        jax.config.update("jax_platforms", platform)
