"""RNG state management.

Paddle has a global generator (`paddle.seed`) plus Fleet's RNGStatesTracker for
parallel-consistent dropout (ref: fleet/meta_parallel/parallel_layers/random.py,
upstream layout, unverified — mount empty).

TPU-native design: threefry counter keys. Two modes:
  * eager: a global mutable key, split on every draw;
  * traced (inside jit): a `rng_guard(key)` context supplies a base key that is
    split deterministically per draw, so the same program always consumes keys
    functionally — no hidden state inside compiled code.
"""
from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax
import numpy as np


def _key_impl() -> Optional[str]:
    """RNG implementation for framework keys. Default: threefry (jax's
    default — reproducible across backends). PADDLE_TPU_RNG_IMPL=rbg swaps
    in XLA's RngBitGenerator, which lowers to the TPU's hardware PRNG —
    ~10x cheaper per dropout mask than threefry's 20 u32 rounds (PERF_NOTES
    r5 trace: threefry bits dominate the per-layer residual fusions). Masks
    are then not bit-reproducible across backends, which Paddle's dropout
    contract does not promise."""
    return os.environ.get("PADDLE_TPU_RNG_IMPL") or None


class Generator:
    """Mutable RNG stream over a threefry key."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        # LAZY key creation: jax.random.key() is a computation that would
        # initialize the XLA backend at `import paddle_tpu` time — which
        # breaks jax.distributed.initialize (must run before backend init)
        # in real multi-process jobs
        self._key = None
        # trace-mode stack: (base_key, counter_list)
        self._trace_stack = []

    def _ensure_key(self):
        if self._key is None:
            impl = _key_impl()
            self._key = (jax.random.key(self._seed, impl=impl) if impl
                         else jax.random.key(self._seed))
        return self._key

    def manual_seed(self, seed: int):
        # stays lazy like __init__: paddle.seed() before fleet.init() must
        # not initialize the XLA backend (breaks jax.distributed.initialize)
        self._seed = int(seed)
        self._key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        if self._trace_stack:
            base, counter = self._trace_stack[-1]
            counter[0] += 1
            return jax.random.fold_in(base, counter[0])
        self._key, sub = jax.random.split(self._ensure_key())
        return sub

    def get_state(self):
        return jax.random.key_data(self._ensure_key())

    def set_state(self, state):
        data = np.asarray(state, dtype=np.uint32)
        # the impl is recoverable from the data shape (threefry2x32 keys
        # are (2,) u32, rbg/unsafe_rbg (4,)), so state saved under one
        # PADDLE_TPU_RNG_IMPL setting restores under any other
        if data.shape and data.shape[-1] == 4:
            impl = _key_impl()
            if impl not in ("rbg", "unsafe_rbg"):
                impl = "rbg"
            self._key = jax.random.wrap_key_data(data, impl=impl)
        else:
            self._key = jax.random.wrap_key_data(data)

    @contextlib.contextmanager
    def trace_mode(self, base_key):
        """Within jit tracing: draw keys functionally from `base_key`."""
        self._trace_stack.append((base_key, [0]))
        try:
            yield
        finally:
            self._trace_stack.pop()


_DEFAULT_GENERATOR = Generator(0)


def default_generator() -> Generator:
    return _DEFAULT_GENERATOR


def seed(s: int) -> Generator:
    """paddle.seed"""
    _DEFAULT_GENERATOR.manual_seed(s)
    return _DEFAULT_GENERATOR


def next_key():
    return _DEFAULT_GENERATOR.next_key()


@contextlib.contextmanager
def rng_guard(base_key):
    """Supply the base key for a traced region (used by jitted train steps)."""
    with _DEFAULT_GENERATOR.trace_mode(base_key):
        yield


def get_rng_state():
    return _DEFAULT_GENERATOR.get_state()


def set_rng_state(state):
    _DEFAULT_GENERATOR.set_state(state)


class RNGStatesTracker:
    """Named RNG streams — Fleet's tracker for TP-consistent dropout.

    Model-parallel regions register a stream whose seed is offset by the mp
    rank so dropout masks differ across tensor-parallel shards while the
    default stream stays identical (Megatron semantics).
    """

    def __init__(self):
        self._states = {}

    def reset(self):
        self._states.clear()

    def add(self, name: str, seed_: int):
        if name in self._states:
            raise ValueError(f"rng state {name!r} already added")
        self._states[name] = Generator(seed_)

    def get_generator(self, name: str) -> Generator:
        if name not in self._states:
            raise KeyError(f"rng state {name!r} not found")
        return self._states[name]

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        """Temporarily make the named stream the default generator."""
        global _DEFAULT_GENERATOR
        if name not in self._states:
            raise KeyError(f"rng state {name!r} not found; call add() first")
        prev = _DEFAULT_GENERATOR
        _DEFAULT_GENERATOR = self._states[name]
        try:
            yield
        finally:
            _DEFAULT_GENERATOR = prev


_MODEL_PARALLEL_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _MODEL_PARALLEL_TRACKER


def model_parallel_random_seed(seed_: int, mp_rank: int = 0):
    """Fleet parity: distinct 'local_seed' per mp rank, shared 'global_seed'."""
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", seed_)
    tracker.add("local_seed", seed_ + 1024 + mp_rank)
