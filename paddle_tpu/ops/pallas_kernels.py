"""Pallas TPU kernels — the PHI `fusion/` + flash-attention analog (ref:
paddle/phi/kernels/gpu/flash_attn_kernel.cu over the external flashattn lib,
upstream layout, unverified — mount empty).

Selection policy: the functional layer calls *_available() first; on
non-TPU backends or awkward shapes we fall back to the jnp reference op and
let XLA fuse. The kernels themselves follow the pallas_guide.md playbook:
block over (seq_q,) grid, keep K/V tiles in VMEM, online-softmax accumulation
in fp32.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_BLOCK_Q = 512
_BLOCK_K = 512


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except RuntimeError:
        return False


def flash_attention_available(q, k, v, attn_mask=None) -> bool:
    if attn_mask is not None:
        return False
    if not _on_tpu():
        return False
    qd = q._data if hasattr(q, "_data") else q
    kd = k._data if hasattr(k, "_data") else k
    b, sq, h, d = qd.shape
    sk = kd.shape[1]
    # MXU-friendly shapes only; otherwise the XLA reference path is fine.
    return d % 128 == 0 and sq % _BLOCK_Q == 0 and sk % _BLOCK_K == 0


@functools.partial(jax.jit, static_argnames=("is_causal",))
def _flash_attention_data(q, k, v, is_causal=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # layout: (b, h, s, d) for blocking
    qt = jnp.einsum("bshd->bhsd", q)
    kt = jnp.einsum("bshd->bhsd", k)
    vt = jnp.einsum("bshd->bhsd", v)

    block_q = min(_BLOCK_Q, sq)
    block_k = min(_BLOCK_K, sk)
    n_q = sq // block_q
    n_k = sk // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)

        qblk = q_ref[0, 0].astype(jnp.float32) * scale
        kblk = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if is_causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, -jnp.inf)
        m_prev = m_ref[...]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_cur)
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_cur
        vblk = v_ref[0, 0].astype(jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(ki == n_k - 1)
        def _done():
            o_ref[0, 0] = (acc_ref[...] /
                           jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)

    grid = (b, h, n_q, n_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
    )(qt, kt, vt)
    return jnp.einsum("bhsd->bshd", out)


def flash_attention(q, k, v, is_causal=False):
    """Tensor-level wrapper used by nn.functional."""
    from ..core.dispatch import apply_callable

    def fn(qd, kd, vd):
        return _flash_attention_data(qd, kd, vd, is_causal=is_causal)

    return apply_callable("flash_attention", fn, q, k, v)
