"""ZeRO-sharded data-parallel training (ISSUE 16 tentpole, layer 2).

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336): instead of every dp replica holding the
full optimizer state and redundantly applying the identical weight
update, shard the update itself —

    reduce-scatter grads -> shard-local optimizer update on the 1/dp
    parameter slice -> all-gather updated params

`ZeroTrainStep` / `zero_train_step` builds that step jit/shard_map-
native on the unified (dp x tp) mesh from `parallel/mesh.py`:

- **stage 0** (the baseline the parity claim is against): fixed-order
  dp all-reduce of every grad, full replicated elementwise update.
- **stage 1** (ZeRO-1, paddle level "os"): same all-reduced grad, but
  the optimizer update runs on this shard's 1/dp flat slice only —
  optimizer-state bytes/chip drop to 1/dp.
- **stage 2** (ZeRO-2, "os_g"): the grad is reduce-SCATTERED (fixed
  shard order), so the full summed gradient never materializes in the
  update path.

**Bit-parity (fp32), by construction**: all stages sum grads with the
same fixed-shard-order `ordered_psum` (and `ordered_psum_scatter`,
whose shard i output is bit-identical to slicing the ordered sum —
the sum is elementwise); the optimizer update is the optimizer's OWN
elementwise `functional_step`, so updating a slice and concatenating
equals slicing the full update. Hence ZeRO-1/2 == replicated dp,
bit-for-bit, at every dp degree (pinned by tests/test_zero.py).
Cross-DEGREE bit-parity is NOT claimed: changing dp changes the batch
summation order, which fp addition does not forgive.

**Optimizer-state layout + degree-blind checkpoints**: each slot is
stored as a (dp, tp, chunk) array placed P("dp", "tp"), where chunk =
ceil(tp_local_flat_size / dp). `save_optimizer_state` reassembles full
logical arrays (host-side, numpy), `load_optimizer_state` re-slices
them for ANY (dp, tp) — save at dp=2, restore at dp=4, keep training:
the same degree-blind contract the serving journal honors for tp.

**tp composition**: params may carry Megatron PartitionSpecs over the
tp axis; the dp machinery slices each shard's TP-LOCAL flat view, so
dp x tp composes on one mesh with no special cases. Loss functions
crossing tp regions must use `mesh.copy_to_tp_region` /
`mesh.reduce_from_tp_region` (differentiating raw collectives under
`shard_map(check_rep=False)` is undefined on jax 0.4.x).

**Limits** (validated loudly at construction): elementwise optimizers
only (Lamb's trust ratio and LBFGS's history are whole-tensor
operations — a 1/dp slice changes them); `grad_clip` is rejected (the
global-norm clip over a slice is wrong — use the GSPMD GroupSharded
surface with `HybridParallelClipGrad` instead).

**Bucketing + ring-pipelined overlap (ISSUE 20 tentpole)**: with
`bucket_bytes` set, the per-leaf grads are packed into fixed-byte flat
buckets in a SHARD-MAJOR layout — each leaf's padded flat grad is
shaped (dp, chunk) and the bucket concatenates those along the chunk
axis, so one `ordered_psum_scatter` of the packed bucket hands shard i
exactly the concatenation of each leaf's shard-i slice, with every
per-element sum in the identical fixed shard order as the per-leaf
scatter (bit-identical by construction; pinned across the bucket-size
sweep in tests/test_zero_bucket.py). With `overlap=True` the buckets
additionally ride the fixed-order ppermute ring
(`mesh.ring_collect` / `mesh.ring_pipeline` — the same scheduler
serving TP decode overlap uses): bucket j+1's transport is emitted
before bucket j's reduce + shard-local optimizer update, and the
updated-slice all-gather of bucket j rides as ring hops ahead of
bucket j+1's update math — transport changes, arithmetic does not, so
fp32 overlapped stays bit-identical to the serial step at every
(dp, stage, tp, grad_accum).

**Mixed precision** (`param_dtype="bf16"`): params are placed in
bfloat16 (backward FLOPs + bytes on the wire halve; floating batch
leaves are cast to bf16 inside the step), the optimizer state carries
fp32 MASTER weights (the optimizer's own `multi_precision` slot,
riding the (dp, tp, chunk) layout — degree-blind save/restore for
free) and the shard-local update runs in fp32 against them. Dynamic
loss scaling guards the bf16 backward: the loss is scaled by a
power-of-two scale (exact — no mantissa change), grads travel scaled
in bf16, the update unscales in fp32, and a traced nonfinite check
over the local grads skips the update (params AND state where-
reverted) and backs the scale off; `scale_growth_interval` good steps
double it again. bf16 is a BOUNDED-ERROR mode: the dp grad sums run
in bf16, so cross-stage/overlap bit-parity is NOT claimed — the
contract is a loss trajectory within documented tolerance of fp32
(pinned on the pretrain bench) with nonfinite/loss-scale events
visible in telemetry.

The paddle-compat GroupSharded/`group_sharded_parallel` surface
(GSPMD sharding-annotation flavor, stages 1-3) lives at the bottom of
this module — `fleet.meta_parallel.sharding` and
`distributed.sharding` are re-export shims onto it — and bridges to
the explicit engine via `_ShardedBase.zero_train_step()`.
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:                                   # newer jax exports it at top level
    from jax import shard_map as _shard_map  # type: ignore
except ImportError:                    # jax 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

from ..nn import Layer
from .mesh import (
    DP_AXIS, TP_AXIS, build_mesh, collected_shard_sum, device_order,
    local_shape, ordered_psum, ordered_psum_scatter, ring_collect,
    ring_pipeline, shard_leaf, tp_dim_spec,
)

__all__ = [
    "ZeroTrainStep", "zero_train_step", "model_loss",
    "build_bucket_layout",
    "save_optimizer_state", "load_optimizer_state",
    "GroupShardedStage2", "GroupShardedStage3",
    "GroupShardedOptimizerStage2", "group_sharded_parallel",
    "save_group_sharded_model", "shard_leaf",
]

# whole-tensor update rules: slicing changes the math, so the sharded
# engine refuses them instead of silently diverging from the replica
_NON_ELEMENTWISE = ("Lamb", "LBFGS")

# reserved opt-state entry holding the dynamic loss scaler's replicated
# scalars under param_dtype="bf16" (never a param name — params come
# from named_parameters, which cannot produce dunder keys)
_SCALER_KEY = "__scaler__"
# paddle GradScaler-shaped constants: halve on a nonfinite step, double
# after `scale_growth_interval` consecutive good ones, clamped so the
# scale can neither vanish nor overflow f32
_SCALE_BACKOFF = 0.5
_SCALE_GROWTH = 2.0
_SCALE_MIN = 1.0
_SCALE_MAX = 2.0 ** 24


def model_loss(model, criterion=None):
    """Build a `loss_fn(params, x, y) -> scalar` over a Layer via the
    functional forward (`call_functional`), defaulting to mean squared
    error. The mean must be over the LOCAL batch rows — the engine's
    fixed-order dp reduction averages the shard losses."""
    from ..core.tensor import Tensor
    from ..jit.functional import call_functional

    def loss_fn(params, x, y):
        out, _ = call_functional(model, params, {}, (x,), training=True)
        if criterion is None:
            return jnp.mean((out - y) ** 2)
        loss = criterion(Tensor(out), Tensor(y))
        return getattr(loss, "_data", loss)

    return loss_fn


def _pad_flat(x, n: int):
    """Flatten and zero-pad to length n (n >= x.size). Zero padding is
    update-neutral for every elementwise rule: pad params and grads are
    both 0, so the padded slots never feed back into real elements."""
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, n - flat.shape[0]))


# ------------------------------------------------------ bucket layout
def build_bucket_layout(names: Sequence[str], chunks: Dict[str, int],
                        itemsize: int, dp: int,
                        bucket_bytes: Optional[int]) -> List[Dict]:
    """Greedy fixed-byte bucketing of the padded per-leaf flats,
    computed ONCE at build time (pure host function — unit-tested
    directly in tests/test_zero_bucket.py).

    Leaves are taken in param order; a leaf's padded footprint is
    dp * chunk * itemsize bytes. A new bucket starts when adding the
    next leaf would exceed `bucket_bytes`; a leaf larger than the cap
    by itself gets its own bucket (leaves are never split — the
    shard-major packing needs whole (dp, chunk) blocks).
    `bucket_bytes=None` yields one bucket per leaf (the overlap
    pipeline's finest granularity when no byte cap is set).

    Returns one dict per bucket: `names` (leaf order inside the
    bucket), `offs` (each leaf's offset inside the bucket's per-shard
    slice) and `width` (the per-shard slice length, sum of the member
    chunks)."""
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    cap = None
    if bucket_bytes is not None:
        cap = int(bucket_bytes)
        if cap <= 0:
            raise ValueError(
                f"bucket_bytes must be > 0 (or None), got {bucket_bytes}")
    groups: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for k in names:
        nbytes = dp * int(chunks[k]) * int(itemsize)
        if cur and (cap is None or cur_bytes + nbytes > cap):
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(k)
        cur_bytes += nbytes
    if cur:
        groups.append(cur)
    out = []
    for member_names in groups:
        offs: Dict[str, int] = {}
        width = 0
        for k in member_names:
            offs[k] = width
            width += int(chunks[k])
        out.append({"names": tuple(member_names), "offs": offs,
                    "width": width})
    return out


def _pack_bucket(ctx, bucket, grads):
    """Pack one bucket's leaves into the SHARD-MAJOR flat the fixed-
    order scatter consumes: each leaf's flat grad is zero-padded to
    dp * chunk and shaped (dp, chunk); the bucket concatenates those
    along the chunk axis into (dp, width) and flattens. Row d of the
    packed layout is then exactly the concatenation of every leaf's
    shard-d slice, so `ordered_psum_scatter` of the packed flat sums
    each element in the identical fixed shard order as the per-leaf
    scatter — the bucketed shard slice is bit-identical to
    concatenating the per-leaf slices."""
    rows = [_pad_flat(grads[k], ctx.dp * ctx._chunks[k])
            .reshape(ctx.dp, ctx._chunks[k]) for k in bucket["names"]]
    packed = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=1)
    return packed.reshape(-1)


# ------------------------------------------------------------- step bodies
# module-level on purpose: these ARE the hot per-step path (traced into
# the one train executable), and graftlint's HOST-SYNC rule audits them
# by name — nested closures would dodge the audit.

def _accumulated_grads(ctx, params, batch, scale=None):
    """Local (this dp shard's) loss and grads, averaged over
    `ctx.grad_accum` micro-batches split from the local rows (static
    unroll — one executable, no host loop).

    With `scale` (the traced loss-scale scalar, bf16 mode only) the
    loss is multiplied by it before differentiation, so the bf16
    cotangents travel scaled; the returned loss is unscaled (exact —
    the scale is a power of two), while the returned grads stay
    SCALED and UNAVERAGED: the shard-local update folds 1/(dp *
    grad_accum * scale) into one fp32 multiply (`_unscale_shard`),
    instead of averaging in bf16 here."""
    loss_fn = ctx.loss_fn
    if scale is None:
        vg = jax.value_and_grad(loss_fn)
    else:
        def scaled_loss(p, *args):
            return loss_fn(p, *args) * scale

        vg = jax.value_and_grad(scaled_loss)
    k = ctx.grad_accum
    if k == 1:
        loss, grads = vg(params, *batch)
        if scale is not None:
            loss = loss / scale
        return loss, grads
    per = batch[0].shape[0] // k
    loss = None
    gsum = None
    for j in range(k):
        micro = tuple(jax.lax.dynamic_slice_in_dim(b, j * per, per, axis=0)
                      for b in batch)
        step_loss, g = vg(params, *micro)
        loss = step_loss if loss is None else loss + step_loss
        gsum = g if gsum is None else jax.tree_util.tree_map(
            lambda a, b: a + b, gsum, g)
    inv = jnp.float32(1.0 / k)
    if scale is not None:
        return loss * inv / scale, gsum
    return loss * inv, jax.tree_util.tree_map(lambda g: g * inv, gsum)


def _unscale_shard(ctx, shard, scale):
    """Finish one reduced grad shard: fp32 mode multiplies by 1/dp
    (the dp-mean — bit-identical to the legacy per-leaf constant);
    scaled bf16 mode casts to fp32 FIRST, then applies the folded
    1/(dp * grad_accum) mean and the loss-scale inverse in one fp32
    multiply — the grads travelled scaled/unaveraged in bf16, and the
    unscale is the entry into the fp32 master-weight update."""
    if scale is None:
        return shard * jnp.float32(1.0 / ctx.dp)
    inv = jnp.float32(1.0 / (ctx.dp * ctx.grad_accum))
    return shard.astype(jnp.float32) * (inv / scale)


def _grad_nonfinite(ctx, grads):
    """Traced scalar count of nonfinite elements over the LOCAL
    (scaled, pre-reduction) grads, combined across dp (and tp when
    composed) with the same fixed-order psum as the update — the
    loss scaler's skip signal. Pre-reduction on purpose: a backward
    overflow is caught on the shard that produced it, before the bf16
    sums can fold it into every shard's slice."""
    total = jnp.float32(0.0)
    for k in grads:
        total = total + jnp.sum(
            (~jnp.isfinite(grads[k])).astype(jnp.float32))
    total = ordered_psum(total, DP_AXIS)
    if ctx.tp > 1:
        total = ordered_psum(total, TP_AXIS)
    return total


def _scaler_next(ctx, scaler, finite):
    """One dynamic-loss-scale transition (traced, replicated scalars):
    a nonfinite step halves the scale (clamped at `_SCALE_MIN`) and
    resets the good-step counter; `scale_growth_interval` consecutive
    good steps double it (clamped at `_SCALE_MAX`). All transitions
    are power-of-two multiplies — scaling never costs mantissa."""
    scale, good = scaler["scale"], scaler["good_steps"]
    good1 = good + jnp.float32(1.0)
    grown = jnp.logical_and(
        finite, good1 >= jnp.float32(ctx.scale_growth_interval))
    up = jnp.minimum(scale * jnp.float32(_SCALE_GROWTH),
                     jnp.float32(_SCALE_MAX))
    down = jnp.maximum(scale * jnp.float32(_SCALE_BACKOFF),
                       jnp.float32(_SCALE_MIN))
    new_scale = jnp.where(finite, jnp.where(grown, up, scale), down)
    new_good = jnp.where(finite, jnp.where(grown, jnp.float32(0.0), good1),
                         jnp.float32(0.0))
    return {"scale": new_scale, "good_steps": new_good}


def _replicated_update(ctx, params, grads, state, lr, t, scale=None):
    """Stage 0: fixed-order dp all-reduce of every grad, full
    elementwise update everywhere — the reference the sharded stages
    are bit-identical to. Returns `(new_params, new_state, grad_aux)`
    where grad_aux is the telemetry (grad_sumsq, nonfinite) pair over
    the MEAN grad (None when telemetry is off — the telemetry-off
    trace is unchanged). Under bf16 (`scale` set) the all-reduced
    scaled grad is unscaled into fp32 before the master-weight
    update."""
    if scale is None:
        inv = jnp.float32(1.0 / ctx.dp)
        g = {k: ordered_psum(grads[k], DP_AXIS) * inv for k in grads}
    else:
        g = {k: _unscale_shard(ctx, ordered_psum(grads[k], DP_AXIS), scale)
             for k in grads}
    # functional_step indexes state by param name, so the reserved
    # scaler entry (when present) is naturally out of its reach
    new_p, new_s = ctx.optimizer.functional_step(params, g, state, lr, t)
    aux = None
    if ctx._telemetry is not None:
        # g is replicated across dp (already all-reduced): no dp
        # combine, tp-sharded leaves combined inside grad_leaf_stats
        aux = ctx._trmod.grad_leaf_stats(ctx, g, dp_reduce=False)
    return new_p, new_s, aux


def _sharded_update(ctx, params, grads, state, lr, t, scale=None):
    """ZeRO-1/2: slice params + grads to this shard's 1/dp flat chunk,
    run the optimizer's own elementwise update on the slice against the
    (dp, tp, chunk)-laid-out state, then all-gather the updated slices
    back into the tp-local param. Stage 1 all-reduces the full grad
    first; stage 2 reduce-scatters so the full summed gradient never
    materializes in the update path.

    Telemetry keeps that property: the grad health stats are taken
    over each shard's SLICE of the mean grad (the slices partition the
    padded flat grad; zero padding contributes 0 to both sumsq and the
    nonfinite count), then dp-combined as per-leaf scalars inside
    `grad_leaf_stats` — the full summed gradient still never
    materializes. Returns `(new_params, new_state, grad_aux)`;
    grad_aux is None when telemetry is off."""
    inv = jnp.float32(1.0 / ctx.dp)
    names = list(params)
    i = jax.lax.axis_index(DP_AXIS)
    sliced_p, sliced_g, local_state = {}, {}, {}
    for k in names:
        chunk = ctx._chunks[k]
        padded = ctx.dp * chunk
        if ctx.stage >= 2:
            gs = ordered_psum_scatter(_pad_flat(grads[k], padded), DP_AXIS)
            gs = gs * inv if scale is None else _unscale_shard(
                ctx, gs, scale)
        elif scale is None:
            gfull = ordered_psum(grads[k], DP_AXIS) * inv
            gs = jax.lax.dynamic_slice(_pad_flat(gfull, padded),
                                       (i * chunk,), (chunk,))
        else:
            gfull = ordered_psum(grads[k], DP_AXIS)
            gs = _unscale_shard(
                ctx, jax.lax.dynamic_slice(_pad_flat(gfull, padded),
                                           (i * chunk,), (chunk,)), scale)
        sliced_p[k] = jax.lax.dynamic_slice(_pad_flat(params[k], padded),
                                            (i * chunk,), (chunk,))
        sliced_g[k] = gs
        # state leaves arrive as this shard's (1, 1, chunk) block
        local_state[k] = {slot: v.reshape(-1)
                          for slot, v in state[k].items()}
    new_slices, new_state = ctx.optimizer.functional_step(
        sliced_p, sliced_g, local_state, lr, t)
    new_params = {}
    for k in names:
        full = jax.lax.all_gather(new_slices[k], DP_AXIS).reshape(-1)
        new_params[k] = full[:ctx._loc_sizes[k]].reshape(ctx._loc_shapes[k])
    aux = None
    if ctx._telemetry is not None:
        aux = ctx._trmod.grad_leaf_stats(ctx, sliced_g, dp_reduce=True)
    return new_params, {k: {slot: v.reshape(1, 1, -1)
                            for slot, v in new_state[k].items()}
                        for k in names}, aux


def _slice_local(ctx, params, state, bucket, i, sliced_p, sliced_g,
                 local_state, shard):
    """Split one bucket's reduced shard slice back into per-leaf
    (chunk,) grads at the layout's static offsets, and slice this
    shard's param chunk + (1,1,chunk) state block for each member
    leaf — the shard-local inputs of the bucket's optimizer update."""
    for k in bucket["names"]:
        off = bucket["offs"][k]
        chunk = ctx._chunks[k]
        sliced_g[k] = jax.lax.slice_in_dim(shard, off, off + chunk)
        sliced_p[k] = jax.lax.dynamic_slice(
            _pad_flat(params[k], ctx.dp * chunk), (i * chunk,), (chunk,))
        local_state[k] = {slot: v.reshape(-1)
                          for slot, v in state[k].items()}


def _unpack_gathered(ctx, bucket, gathered, new_params):
    """(dp, width) gathered bucket -> per-leaf tp-local params: column
    block [off, off+chunk) of the gathered buffer is leaf k's
    (dp, chunk) padded layout — flatten, trim the dp padding, reshape.
    Pure data movement (same values the per-leaf all_gather lays out),
    so the gather tail adds no arithmetic to the parity surface."""
    for k in bucket["names"]:
        off = bucket["offs"][k]
        chunk = ctx._chunks[k]
        full = gathered[:, off:off + chunk].reshape(-1)
        new_params[k] = full[:ctx._loc_sizes[k]].reshape(
            ctx._loc_shapes[k])


def _bucketed_update(ctx, params, grads, state, lr, t, scale=None):
    """ZeRO-1/2 with bucketed collectives, serial schedule
    (`bucket_bytes` set, `overlap=False`): one fixed-order
    reduce-scatter (stage 2) or all-reduce + slice (stage 1) per
    BUCKET instead of per leaf, over the shard-major packed flat
    (`_pack_bucket` — bit-identical sums by construction), one
    whole-tree optimizer update, then one all-gather per bucket on
    the tail. Fewer, larger collectives; same arithmetic."""
    inv = jnp.float32(1.0 / ctx.dp)
    names = list(params)
    i = jax.lax.axis_index(DP_AXIS)
    sliced_p, sliced_g, local_state = {}, {}, {}
    for bucket in ctx._buckets:
        width = bucket["width"]
        flat = _pack_bucket(ctx, bucket, grads)
        if ctx.stage >= 2:
            shard = ordered_psum_scatter(flat, DP_AXIS)
        else:
            full = ordered_psum(flat, DP_AXIS)
            shard = jax.lax.dynamic_slice(full, (i * width,), (width,))
        shard = shard * inv if scale is None else _unscale_shard(
            ctx, shard, scale)
        _slice_local(ctx, params, state, bucket, i, sliced_p, sliced_g,
                     local_state, shard)
    new_slices, new_state = ctx.optimizer.functional_step(
        sliced_p, sliced_g, local_state, lr, t)
    new_params = {}
    for bucket in ctx._buckets:
        cat = jnp.concatenate([new_slices[k] for k in bucket["names"]]) \
            if len(bucket["names"]) > 1 else new_slices[bucket["names"][0]]
        gathered = jax.lax.all_gather(cat, DP_AXIS)        # (dp, width)
        _unpack_gathered(ctx, bucket, gathered, new_params)
    aux = None
    if ctx._telemetry is not None:
        aux = ctx._trmod.grad_leaf_stats(
            ctx, {k: sliced_g[k] for k in names}, dp_reduce=True)
    return new_params, {k: {slot: v.reshape(1, 1, -1)
                            for slot, v in new_state[k].items()}
                        for k in names}, aux


def _overlapped_update(ctx, params, grads, state, lr, t, scale=None):
    """ZeRO-1/2 with the bucketed collectives ring-pipelined against
    the shard-local optimizer compute (`overlap=True`): each bucket's
    packed flat rides the fixed-order ppermute ring
    (`mesh.ring_collect`) and the shared `mesh.ring_pipeline`
    double-buffers — bucket j+1's grad transport is emitted before
    bucket j's reduce + optimizer update, and bucket j's updated-slice
    all-gather is itself ring transport emitted BEFORE bucket j+1's
    update math (the mirrored tail). The collected buffer has the
    all_gather layout and the reduce is the identical static
    shard-order sum (`collected_shard_sum`), so fp32 results stay
    bit-identical to the serial step — the schedule moves bytes
    earlier, it never reorders a sum. The optimizer update runs once
    per bucket (`functional_step` is per-leaf elementwise, so
    per-bucket calls equal the whole-tree call bitwise)."""
    names = list(params)
    i = jax.lax.axis_index(DP_AXIS)
    n = ctx.dp
    buckets = ctx._buckets
    gathered: List = [None] * len(buckets)
    new_state: Dict = {}
    stat_slices: Dict = {}

    def transport(bucket):
        return ring_collect(_pack_bucket(ctx, bucket, grads), DP_AXIS, n)

    def reduce(moved):
        if ctx.stage >= 2:
            return collected_shard_sum(moved, DP_AXIS)
        full = moved[0]
        for s in range(1, n):
            full = full + moved[s]
        width = moved.shape[1] // n
        return jax.lax.dynamic_slice(full, (i * width,), (width,))

    def consume(j, shard):
        bucket = buckets[j]
        shard = shard * jnp.float32(1.0 / n) if scale is None \
            else _unscale_shard(ctx, shard, scale)
        sliced_p, sliced_g, local_state = {}, {}, {}
        _slice_local(ctx, params, state, bucket, i, sliced_p, sliced_g,
                     local_state, shard)
        new_sl, new_st = ctx.optimizer.functional_step(
            sliced_p, sliced_g, local_state, lr, t)
        for k in bucket["names"]:
            new_state[k] = {slot: v.reshape(1, 1, -1)
                            for slot, v in new_st[k].items()}
            stat_slices[k] = sliced_g[k]
        cat = jnp.concatenate([new_sl[k] for k in bucket["names"]]) \
            if len(bucket["names"]) > 1 else new_sl[bucket["names"][0]]
        # the mirrored tail: bucket j's updated-slice gather goes into
        # flight here, ahead of bucket j+1's reduce + update in the
        # pipeline's next iteration
        gathered[j] = ring_collect(cat, DP_AXIS, n)        # (dp, width)

    ring_pipeline(buckets, transport, reduce, consume)
    new_params: Dict = {}
    for j, bucket in enumerate(buckets):
        _unpack_gathered(ctx, bucket, gathered[j], new_params)
    aux = None
    if ctx._telemetry is not None:
        aux = ctx._trmod.grad_leaf_stats(
            ctx, {k: stat_slices[k] for k in names}, dp_reduce=True)
    return new_params, new_state, aux


# ------------------------------------------- degree-blind state layout
def _to_zero_layout(full, spec_dim: Optional[int], dp: int, tp: int,
                    chunk: int) -> np.ndarray:
    """Full logical array -> (dp, tp, chunk) sharded layout (host-side
    numpy; the inverse of `_from_zero_layout` at ANY dp)."""
    full = np.asarray(full)
    parts = (np.split(full, tp, axis=spec_dim) if spec_dim is not None
             else [full] * tp)
    blocks = []
    for part in parts:
        flat = np.ravel(part)
        flat = np.pad(flat, (0, dp * chunk - flat.size))
        blocks.append(flat.reshape(dp, chunk))
    return np.stack(blocks, axis=1)


def _from_zero_layout(arr, shape: Tuple[int, ...],
                      spec_dim: Optional[int], tp: int) -> np.ndarray:
    """(dp, tp, chunk) sharded layout -> full logical array. Degree
    blind: only the layout's own leading dim says what dp it was saved
    at; nothing else depends on it."""
    arr = np.asarray(arr)
    if spec_dim is None:
        flat = np.ravel(arr[:, 0])
        return flat[:int(np.prod(shape))].reshape(shape)
    loc_shape = list(shape)
    loc_shape[spec_dim] //= tp
    loc = int(np.prod(loc_shape))
    parts = [np.ravel(arr[:, j])[:loc].reshape(loc_shape)
             for j in range(tp)]
    return np.concatenate(parts, axis=spec_dim)


class ZeroTrainStep:
    """One jitted shard_map train step
    `(params, opt_state, batch, lr, t) -> (loss, params, opt_state)`
    over the unified (dp x tp) mesh, with the optimizer update sharded
    across dp per `stage` (see module docstring). Build once per
    (model, optimizer, degree); `init_state` places params/state, the
    instance is the step callable."""

    def __init__(self, model, optimizer, loss_fn=None, *, criterion=None,
                 dp: Optional[int] = None, tp: int = 1, stage: int = 1,
                 param_specs: Optional[Dict[str, P]] = None,
                 batch_specs: Optional[Sequence[P]] = None,
                 grad_accum: int = 1, devices=None,
                 bucket_bytes: Optional[int] = None,
                 overlap: bool = False,
                 param_dtype: Optional[str] = None,
                 loss_scale: float = 2.0 ** 15,
                 scale_growth_interval: int = 200,
                 telemetry=None, enable_telemetry: bool = False):
        if stage not in (0, 1, 2):
            raise ValueError(
                f"stage must be 0 (replicated baseline), 1 (ZeRO-1) or 2 "
                f"(ZeRO-2); got {stage} — stage 3 (param sharding) is the "
                "GSPMD GroupSharded surface (level='p_g_os')")
        opt_name = type(optimizer).__name__
        if opt_name in _NON_ELEMENTWISE:
            raise NotImplementedError(
                f"{opt_name} applies whole-tensor update rules; the "
                "dp-sliced update would change its math. Use an "
                "elementwise optimizer (SGD/Momentum/Adam/AdamW/...)")
        if getattr(optimizer, "_grad_clip", None) is not None:
            raise NotImplementedError(
                "grad_clip inside the sharded update would clip by the "
                "SLICE norm, not the global norm; clip before the step or "
                "use the GSPMD GroupSharded surface with "
                "HybridParallelClipGrad")
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = (loss_fn if loss_fn is not None
                        else model_loss(model, criterion))
        self.tp = int(tp)
        devs = device_order(devices)
        self.dp = int(dp) if dp is not None else max(
            len(devs) // self.tp, 1)
        if self.dp < 1:
            raise ValueError(f"dp must be >= 1, got {self.dp}")
        self.stage = int(stage)
        self.grad_accum = int(grad_accum)
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.param_specs = dict(param_specs or {})
        self.batch_specs = (tuple(batch_specs) if batch_specs is not None
                            else None)
        if self.grad_accum > 1 and self.batch_specs is not None and any(
                tuple(s) != (DP_AXIS,) for s in self.batch_specs):
            raise ValueError(
                "grad_accum > 1 splits every batch leaf along its local "
                "rows, so all batch_specs must be P('dp')")
        self.mesh = build_mesh(((DP_AXIS, self.dp), (TP_AXIS, self.tp)),
                               devices)
        self.devices = tuple(self.mesh.devices.reshape(-1))
        # per-param geometry, discovered at init_state/load time
        # dp=1 "sharding" is an identity: the 1/dp slice IS the whole
        # param, so the engine runs the stage-0 program outright — same
        # math, and literally the same executable, so bit-parity with
        # the replicated baseline is definitional rather than lucky
        # (even boundary reshapes steer XLA's FMA selection enough to
        # drift low bits otherwise)
        self._sharded = self.stage >= 1 and self.dp > 1
        # ---- bucketing / overlap knobs (ISSUE 20). Both describe HOW
        # the sharded collectives run, so stage 0 (no sharded
        # collectives) rejects them outright; at dp=1 the engine runs
        # the literal stage-0 executable (see above) and the knobs are
        # inert by the same identity.
        if bucket_bytes is not None and int(bucket_bytes) <= 0:
            raise ValueError(
                f"bucket_bytes must be > 0 (or None), got {bucket_bytes}")
        if self.stage == 0 and (overlap or bucket_bytes is not None):
            raise ValueError(
                "bucket_bytes/overlap schedule the SHARDED collectives; "
                "stage 0 has none — use stage 1 or 2")
        self.bucket_bytes = (int(bucket_bytes) if bucket_bytes is not None
                             else None)
        self.overlap = bool(overlap)
        self._bucketed = self._sharded and (self.bucket_bytes is not None
                                            or self.overlap)
        self._overlap = self._sharded and self.overlap
        # ---- mixed precision (ISSUE 20): bf16 working weights + wire
        # format, fp32 master weights in the sharded optimizer state
        if param_dtype in (None, "float32", "fp32", "f32"):
            self._param_dtype = None
        elif param_dtype in ("bf16", "bfloat16"):
            self._param_dtype = jnp.bfloat16
        else:
            raise ValueError(
                f"param_dtype must be None/'float32' or 'bf16', "
                f"got {param_dtype!r}")
        self.loss_scale = float(loss_scale)
        self.scale_growth_interval = int(scale_growth_interval)
        if self._param_dtype is not None:
            if self.loss_scale < 1.0:
                raise ValueError(
                    f"loss_scale must be >= 1, got {loss_scale}")
            if self.scale_growth_interval < 1:
                raise ValueError(
                    "scale_growth_interval must be >= 1, got "
                    f"{scale_growth_interval}")
            # the optimizer's own multi-precision machinery IS the
            # master-weight store: force it on so functional_state
            # allocates the fp32 "master_weight" slot for bf16 params
            # (documented in the class docstring — the engine owns this
            # decision, a bf16 step without masters is never correct)
            self.optimizer._multi_precision = True
        self._buckets: List[Dict] = []
        self._overlap_fraction: Optional[float] = None
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        self._spec: Dict[str, P] = {}
        self._spec_dim: Dict[str, Optional[int]] = {}
        self._loc_shapes: Dict[str, Tuple[int, ...]] = {}
        self._loc_sizes: Dict[str, int] = {}
        self._chunks: Dict[str, int] = {}
        self._state_spec: Dict[str, Dict[str, P]] = {}
        self._step = None
        self._probes: Dict[int, object] = {}
        # ---- training observability (ISSUE 19), opt-in. The import is
        # lazy AND conditional: a telemetry-off trainer never imports
        # observability/training.py at all (poisoned-module pinned in
        # tests/test_training_obs.py — zero cost when off means zero
        # code, not just zero work).
        self._telemetry = None
        self._trmod = None
        if telemetry is not None or enable_telemetry:
            from ..observability import training as _trmod

            self._trmod = _trmod
            self._telemetry = (telemetry if telemetry is not None
                               else _trmod.TrainingTelemetry())
            self._telemetry.bind(
                dp=self.dp, tp=self.tp, stage=self.stage,
                device_ids=[d.id for d in self.devices])

    # ------------------------------------------------------------ geometry
    def _record_geometry(self, params: Dict[str, jnp.ndarray]) -> None:
        sizes = {DP_AXIS: self.dp, TP_AXIS: self.tp}
        for name, arr in params.items():
            shape = tuple(int(d) for d in arr.shape)
            spec = self.param_specs.get(name, P())
            self._shapes[name] = shape
            self._spec[name] = spec
            self._spec_dim[name] = tp_dim_spec(spec)
            loc = local_shape(shape, spec, sizes)
            self._loc_shapes[name] = loc
            self._loc_sizes[name] = int(np.prod(loc)) if loc else 1
            self._chunks[name] = max(
                math.ceil(self._loc_sizes[name] / self.dp), 1)
        if self._bucketed:
            # layout computed once per geometry; itemsize is the WIRE
            # dtype (the packed grads travel in the compute dtype)
            itemsize = 2 if self._param_dtype is not None else 4
            self._buckets = build_bucket_layout(
                list(params), self._chunks, itemsize, self.dp,
                self.bucket_bytes)

    def _slot_spec(self, name: str, slot_arr) -> P:
        """Stage-0 placement of one state slot: follow the param's tp
        spec when shaped like the param, else replicate (scalars)."""
        if tuple(slot_arr.shape) == self._shapes[name]:
            return self._spec[name]
        return P()

    # ------------------------------------------------------------ placement
    def init_state(self, params: Optional[Dict[str, jnp.ndarray]] = None):
        """Place full logical params on the mesh and build the sharded
        optimizer state; returns `(params, opt_state)` ready for the
        step callable."""
        if params is None:
            from ..jit.functional import extract_state

            params, _ = extract_state(self.model)
        params = {k: jnp.asarray(v) for k, v in params.items()}
        self._record_geometry(params)
        work = params
        if self._param_dtype is not None:
            # working weights live (and travel) in bf16; the fp32
            # originals become the master_weight slots below, so the
            # cast here loses nothing — masters round-trip exact
            work = {k: (v.astype(self._param_dtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in params.items()}
        placed = {k: jax.device_put(
            v, NamedSharding(self.mesh, self._spec[k]))
            for k, v in work.items()}
        host_state = self.optimizer.functional_state(work)
        host_np = {k: {s: np.asarray(v) for s, v in acc.items()}
                   for k, acc in host_state.items()}
        if self._param_dtype is not None:
            for k, v in params.items():
                if "master_weight" in host_np.get(k, {}):
                    # seed the master from the ORIGINAL fp32 param, not
                    # the bf16 round-trip functional_state produced
                    host_np[k]["master_weight"] = np.asarray(
                        v, dtype=np.float32)
            host_np[_SCALER_KEY] = {
                "scale": np.float32(self.loss_scale),
                "good_steps": np.float32(0.0)}
        return placed, self.load_optimizer_state(host_np)

    def load_optimizer_state(self, host_state):
        """Full-logical host state -> placed sharded state for THIS
        (dp, tp, stage). Degree-blind restore: the host form carries no
        dp imprint, so state saved at any degree loads at any other."""
        if not self._shapes:
            raise RuntimeError(
                "call init_state() (or pass params to it) before "
                "load_optimizer_state — the engine needs param geometry")
        out = {}
        for name, acc in host_state.items():
            if name == _SCALER_KEY:
                # replicated f32 scalars — no dp/tp imprint, so the
                # scaler restores degree-blind for free
                slots = {}
                for slot, arr in acc.items():
                    slots[slot] = jax.device_put(
                        jnp.asarray(arr, jnp.float32),
                        NamedSharding(self.mesh, P()))
                    self._state_spec.setdefault(name, {})[slot] = P()
                out[name] = slots
                continue
            slots = {}
            for slot, arr in acc.items():
                arr = np.asarray(arr)
                if not self._sharded:
                    spec = self._slot_spec(name, arr)
                    slots[slot] = jax.device_put(
                        jnp.asarray(arr), NamedSharding(self.mesh, spec))
                    self._state_spec.setdefault(name, {})[slot] = spec
                else:
                    laid = _to_zero_layout(arr, self._spec_dim[name],
                                           self.dp, self.tp,
                                           self._chunks[name])
                    slots[slot] = jax.device_put(
                        jnp.asarray(laid),
                        NamedSharding(self.mesh, P(DP_AXIS, TP_AXIS)))
                    self._state_spec.setdefault(name, {})[slot] = \
                        P(DP_AXIS, TP_AXIS)
            out[name] = slots
        return out

    def save_optimizer_state(self, opt_state):
        """Placed sharded state -> full-logical host arrays (numpy),
        restorable at ANY dp via `load_optimizer_state`."""
        out = {}
        for name, acc in opt_state.items():
            if name == _SCALER_KEY:
                out[name] = {slot: np.asarray(arr)
                             for slot, arr in acc.items()}
                continue
            slots = {}
            for slot, arr in acc.items():
                if not self._sharded:
                    slots[slot] = np.asarray(arr)
                else:
                    slots[slot] = _from_zero_layout(
                        arr, self._shapes[name], self._spec_dim[name],
                        self.tp)
            out[name] = slots
        return out

    # ----------------------------------------------------------- step build
    def _build(self, batch_len: int):
        pspec = {k: self._spec[k] for k in self._shapes}
        sspec = {k: dict(v) for k, v in self._state_spec.items()}
        bspec = (self.batch_specs if self.batch_specs is not None
                 else tuple(P(DP_AXIS) for _ in range(batch_len)))
        if len(bspec) != batch_len:
            raise ValueError(
                f"batch has {batch_len} leaves but batch_specs has "
                f"{len(bspec)}")
        ctx = self
        inv_dp = jnp.float32(1.0 / self.dp)
        # static dispatch: the schedule is a build-time property, the
        # jaxpr contains exactly one update path
        if not self._sharded:
            update_fn = _replicated_update
        elif self._overlap:
            update_fn = _overlapped_update
        elif self._bucketed:
            update_fn = _bucketed_update
        else:
            update_fn = _sharded_update
        scaled = self._param_dtype is not None

        def body(params, state, batch, lr, t):
            scale = None
            if scaled:
                scaler = state[_SCALER_KEY]
                scale = scaler["scale"]
                # floating batch leaves enter the bf16 compute dtype
                # here — part of the documented bounded-error contract
                batch = tuple(
                    b.astype(ctx._param_dtype)
                    if jnp.issubdtype(b.dtype, jnp.floating) else b
                    for b in batch)
            loss, grads = _accumulated_grads(ctx, params, batch, scale)
            # pin the backward: without the barrier XLA fuses the grad
            # computation with its CONSUMERS, and the stage-0 (full
            # update) vs stage-1/2 (slice/gather) consumers steer it to
            # differently-ordered reductions — observed bit drift at
            # dp=1. The barrier makes the grads a sealed subprogram, so
            # every stage (and every bucket/overlap schedule) compiles
            # the identical backward.
            loss, grads = jax.lax.optimization_barrier((loss, grads))
            loss = ordered_psum(loss, DP_AXIS) * inv_dp
            finite = None
            if scaled:
                # skip signal BEFORE any reduction mixes shards
                finite = _grad_nonfinite(ctx, grads) == jnp.float32(0.0)
            new_p, new_s, aux = update_fn(ctx, params, grads, state,
                                          lr, t, scale=scale)
            extras = None
            if scaled:
                # nonfinite step: revert params AND state wholesale (the
                # update ran on garbage), then let the scaler back off
                new_p = {k: jnp.where(finite, v, params[k])
                         for k, v in new_p.items()}
                new_s = {k: {slot: jnp.where(finite, v, state[k][slot])
                             for slot, v in acc.items()}
                         for k, acc in new_s.items()}
                new_scaler = _scaler_next(ctx, scaler, finite)
                new_s[_SCALER_KEY] = new_scaler
                extras = (new_scaler["scale"],
                          jnp.float32(1.0)
                          - finite.astype(jnp.float32))
            if ctx._telemetry is None:
                return loss, new_p, new_s
            # seal the update the same way the backward is sealed: the
            # health packing only CONSUMES barriered copies, so it
            # cannot steer how XLA compiles the update itself — the
            # telemetry-on step stays bit-identical to telemetry-off
            # (pinned across the whole (dp, stage) matrix in
            # tests/test_training_obs.py)
            if extras is None:
                (loss, new_p, new_s, params,
                 aux) = jax.lax.optimization_barrier(
                    (loss, new_p, new_s, params, aux))
            else:
                (loss, new_p, new_s, params, aux,
                 extras) = jax.lax.optimization_barrier(
                    (loss, new_p, new_s, params, aux, extras))
            health = ctx._trmod.pack_health(ctx, loss, params, new_p, aux,
                                            extras=extras)
            return loss, new_p, new_s, health

        out_specs = ((P(), pspec, sspec) if self._telemetry is None
                     else (P(), pspec, sspec, P()))
        self._step = jax.jit(_shard_map(
            body, mesh=self.mesh,
            in_specs=(pspec, sspec, bspec, P(), P()),
            out_specs=out_specs,
            check_rep=False,  # noqa: COLLECTIVE-MESH — the ordered fixed-shard-order collectives and the (dp,tp,chunk) state outputs are per-shard by design; 0.4.x rep tracking can't see through custom_vjp boundaries
            ))

    def __call__(self, params, opt_state, batch, lr, t):
        """One training step. `batch` is a tuple of GLOBAL arrays
        (row-sharded over dp per batch_specs); `lr` scalar; `t` the
        1-based step count (drives Adam bias correction).

        With telemetry enabled the returned loss is the HOST float the
        telemetry plane drained (same value, already synced) — the one
        per-step host sync covers the caller's loss read too — and the
        call may raise `TrainingDiverged` when the sentinel trips."""
        tele = self._telemetry
        if tele is None:
            batch = tuple(batch)
            if self._step is None:
                self._build(len(batch))
            return self._step(params, opt_state, batch,
                              jnp.asarray(lr, jnp.float32),
                              jnp.asarray(t, jnp.int32))
        t_in = tele.clock()
        batch = tuple(batch)
        if self._step is None:
            self._build(len(batch))
        lr_ = jnp.asarray(lr, jnp.float32)
        t_ = jnp.asarray(t, jnp.int32)
        # tokens from batch SHAPE metadata — never a device read
        rows = batch[0].shape[0]
        tokens = (tele.tokens_per_step if tele.tokens_per_step is not None
                  else int(rows))
        t0 = tele.clock()
        loss, new_p, new_s, health = self._step(params, opt_state, batch,
                                                lr_, t_)
        t1 = tele.clock()
        host_loss = tele.record_step(
            health, step=int(t), tokens=tokens,
            batch_build_s=t0 - t_in, dispatch_s=t1 - t0)
        return host_loss, new_p, new_s

    # -------------------------------------------------------- observability
    @staticmethod
    def bytes_per_chip(tree) -> int:
        """Max-over-devices resident bytes of a placed pytree — THE
        1/dp measurement for the optimizer-state claim."""
        total = 0
        for arr in jax.tree_util.tree_leaves(tree):
            total += max(s.data.size * s.data.dtype.itemsize
                         for s in arr.addressable_shards)
        return total

    def optimizer_state_bytes_per_chip(self, opt_state) -> int:
        return self.bytes_per_chip(opt_state)

    def collective_seconds(self, samples: int = 3, rows: int = 1,
                           width: int = 1024) -> List[float]:
        """Measured wall seconds per fixed-order dp all-reduce of a
        replicated (rows, width) f32 buffer — the training twin of
        `TPContext.collective_seconds`. Feeds the
        `parallel_dp_collective_seconds` bench probe. On CPU meshes one
        dispatch's host overhead dominates — which is the honest
        number."""
        fn = self._probes.get((rows, width))
        if fn is None:
            mesh = self.mesh

            def reduce_one(y):
                return ordered_psum(y, DP_AXIS)

            def allreduce(x):
                return _shard_map(
                    reduce_one, mesh=mesh, in_specs=P(), out_specs=P(),
                    check_rep=False,  # noqa: COLLECTIVE-MESH — probe psum of a replicated buffer; rep tracking adds latency to the very overhead being measured
                    )(x)
            fn = jax.jit(allreduce)
            self._probes[(rows, width)] = fn
        x = jax.device_put(jnp.zeros((rows, width), jnp.float32),
                           NamedSharding(self.mesh, P()))
        fn(x).block_until_ready()              # compile + warm
        out = []
        for _ in range(max(int(samples), 1)):
            t0 = time.perf_counter()
            fn(x).block_until_ready()
            out.append(time.perf_counter() - t0)
        # the training twin of serving_tp_collective_seconds: same
        # registry, same construction-time-probe discipline (per-step
        # timing would measure dispatch queueing, not the collective)
        from ..observability import global_registry

        hist = global_registry().histogram(
            "parallel_dp_collective_seconds",
            "fixed-order dp all-reduce probe (ZeroTrainStep)")
        for s in out:
            hist.observe(s)
        return out

    def shard_step_seconds(self, samples: int = 3, rows: int = 128,
                           width: int = 128,
                           best_of: int = 3) -> Dict[str, float]:
        """Per-dp-shard straggler probe: a warmed best-of-N single-
        device micro-step (matmul-shaped) timed on EACH dp row's lead
        device, published as `training_shard_step_seconds{shard=}`.
        Same discipline as `collective_seconds`/`TPContext.
        collective_seconds`: two warm-up dispatches, then best-of-N per
        sample (`observability.training.probe_best_of` = min, monotone
        as trials are added) — so a shard whose BEST case is slow is a
        real straggler, not scheduler noise, and it shows up before it
        stalls the whole mesh at the next collective."""
        from ..observability import training as trmod

        fn = self._probes.get(("shard", rows, width))
        if fn is None:
            fn = jax.jit(lambda a: (a @ a.T).sum())
            self._probes[("shard", rows, width)] = fn
        out: Dict[str, float] = {}
        # enumerate over the mesh's (dp, tp) device grid rows — the
        # shard label cardinality is the dp degree, bounded by the mesh
        for shard, dev_row in enumerate(self.mesh.devices):
            dev = dev_row.reshape(-1)[0]
            x = jax.device_put(jnp.ones((rows, width), jnp.float32), dev)
            fn(x).block_until_ready()          # compile + warm
            fn(x).block_until_ready()
            best = []
            for _ in range(max(int(samples), 1)):
                trials = []
                for _ in range(max(int(best_of), 1)):
                    t0 = time.perf_counter()
                    fn(x).block_until_ready()
                    trials.append(time.perf_counter() - t0)
                best.append(trmod.probe_best_of(trials))
            if self._telemetry is not None:
                for s in best:
                    self._telemetry.observe_shard_step(str(shard), s)
            else:
                from ..observability import global_registry

                hist = global_registry().histogram(
                    "training_shard_step_seconds",
                    "warmed best-of-N per-dp-shard step-time probe",
                    labels={"shard": str(shard)})
                for s in best:
                    hist.observe(s)
            out[str(shard)] = trmod.probe_best_of(best)
        return out

    def comm_seconds(self, samples: int = 3, elems: int = 65536,
                     best_of: int = 3) -> Dict[str, float]:
        """Warmed best-of-N wall seconds for the two ZeRO wire
        primitives at this dp degree — the fixed-order reduce-scatter
        of a replicated (dp * elems,) f32 flat and the matching
        updated-shard all-gather — published as
        `training_comm_seconds{collective=reduce_scatter|all_gather}`.
        Same construction-time-probe discipline as
        `collective_seconds`: per-step timing would measure dispatch
        queueing, not the wire."""
        from ..observability import training as trmod

        n = self.dp
        key = ("comm", elems)
        fns = self._probes.get(key)
        if fns is None:
            mesh = self.mesh

            def rs_body(x):
                return ordered_psum_scatter(x, DP_AXIS)

            def ag_body(s):
                return jax.lax.all_gather(s, DP_AXIS).reshape(-1)

            rs = jax.jit(_shard_map(
                rs_body, mesh=mesh, in_specs=P(), out_specs=P(DP_AXIS),
                check_rep=False,  # noqa: COLLECTIVE-MESH — probe scatter of a replicated buffer; rep tracking adds latency to the very overhead being measured
                ))
            ag = jax.jit(_shard_map(
                ag_body, mesh=mesh, in_specs=P(DP_AXIS), out_specs=P(),
                check_rep=False,  # noqa: COLLECTIVE-MESH — probe gather; the all_gather output is replicated by construction
                ))
            fns = (rs, ag)
            self._probes[key] = fns
        rs, ag = fns
        x = jax.device_put(jnp.zeros((n * elems,), jnp.float32),
                           NamedSharding(self.mesh, P()))
        s = jax.device_put(jnp.zeros((n * elems,), jnp.float32),
                           NamedSharding(self.mesh, P(DP_AXIS)))
        out: Dict[str, float] = {}
        for name, fn, arg in (("reduce_scatter", rs, x),
                              ("all_gather", ag, s)):
            fn(arg).block_until_ready()        # compile + warm
            fn(arg).block_until_ready()
            best = []
            for _ in range(max(int(samples), 1)):
                trials = []
                for _ in range(max(int(best_of), 1)):
                    t0 = time.perf_counter()
                    fn(arg).block_until_ready()
                    trials.append(time.perf_counter() - t0)
                best.append(trmod.probe_best_of(trials))
            if self._telemetry is not None:
                for sec in best:
                    self._telemetry.observe_comm(name, sec)
            else:
                from ..observability import global_registry

                hist = global_registry().histogram(
                    "training_comm_seconds",
                    "warmed best-of-N ZeRO collective probe "
                    "(reduce-scatter / all-gather wall seconds)",
                    labels={"collective": name})
                for sec in best:
                    hist.observe(sec)
            out[name] = trmod.probe_best_of(best)
        return out

    def measure_overlap_fraction(self, samples: int = 3,
                                 best_of: int = 3) -> float:
        """Measured fraction of the bucket collectives' wall time the
        ring pipeline hides behind shard-local update math — the
        training twin of serving's `measure_overlap_fraction`. Three
        probes over the REAL recorded bucket layout (so the measured
        schedule is the step's schedule): (a) collectives only, (b)
        strictly serialized transport→reduce→update→gather per bucket
        (`optimization_barrier` fences between buckets pin the serial
        order), (c) the shared `ring_pipeline` double-buffered
        schedule. fraction = clip((b - c) / a, 0, 1), warmed
        best-of-N. On a CPU mesh the backends can't overlap transport
        with compute, so ~0.0 is the honest null — the probe measures,
        it does not assume. Stored on the instance and pushed into
        telemetry (`training_overlap_fraction` +
        `describe()["telemetry"]["overlap_fraction"]`) when bound."""
        from ..observability import training as trmod

        if not self._buckets:
            raise RuntimeError(
                "no bucket layout — call init_state() first on a "
                "bucketed/overlap engine (stage >= 1, dp > 1 with "
                "bucket_bytes or overlap set)")
        n = self.dp
        buckets = self._buckets
        dtype = (self._param_dtype if self._param_dtype is not None
                 else jnp.float32)
        mesh = self.mesh

        def surrogate(shard):
            # Adam-shaped elementwise cost stand-in for the shard-local
            # update (the probe times schedules, not the optimizer)
            m = shard * jnp.float32(0.9) + shard * jnp.float32(0.1)
            v = shard * shard
            return shard - jnp.float32(0.01) * m / (
                jnp.sqrt(v) + jnp.float32(1e-8))

        def coll_body(x):
            acc = jnp.float32(0.0)
            for b in buckets:
                flat = jnp.full((n * b["width"],), x).astype(dtype)
                moved = ring_collect(flat, DP_AXIS, n)
                red = collected_shard_sum(moved, DP_AXIS)
                gat = ring_collect(red, DP_AXIS, n)
                acc = acc + gat.astype(jnp.float32).sum()
            return acc

        def serial_body(x):
            acc = jnp.float32(0.0)
            for b in buckets:
                flat = jnp.full((n * b["width"],), x).astype(dtype)
                # fence: bucket j+1's transport may not hoist above
                # bucket j's consume — this IS the serial schedule
                flat, acc = jax.lax.optimization_barrier((flat, acc))
                moved = ring_collect(flat, DP_AXIS, n)
                red = collected_shard_sum(moved, DP_AXIS)
                upd = surrogate(red.astype(jnp.float32))
                gat = ring_collect(upd.astype(dtype), DP_AXIS, n)
                acc = acc + gat.astype(jnp.float32).sum()
            return acc

        def overlap_body(x):
            acc = [jnp.float32(0.0)]

            def transport(b):
                flat = jnp.full((n * b["width"],), x).astype(dtype)
                return ring_collect(flat, DP_AXIS, n)

            def reduce(moved):
                return collected_shard_sum(moved, DP_AXIS)

            def consume(j, red):
                upd = surrogate(red.astype(jnp.float32))
                gat = ring_collect(upd.astype(dtype), DP_AXIS, n)
                acc[0] = acc[0] + gat.astype(jnp.float32).sum()

            ring_pipeline(buckets, transport, reduce, consume)
            return acc[0]

        def timed(body):
            fn = jax.jit(_shard_map(
                body, mesh=mesh, in_specs=P(), out_specs=P(),
                check_rep=False,  # noqa: COLLECTIVE-MESH — schedule probe over the ring collectives; per-shard by design
                ))
            x = jnp.float32(1.0)
            fn(x).block_until_ready()          # compile + warm
            fn(x).block_until_ready()
            trials = []
            for _ in range(max(int(samples) * max(int(best_of), 1), 1)):
                t0 = time.perf_counter()
                fn(x).block_until_ready()
                trials.append(time.perf_counter() - t0)
            return trmod.probe_best_of(trials)

        t_coll = timed(coll_body)
        t_serial = timed(serial_body)
        t_overlap = timed(overlap_body)
        frac = 0.0
        if t_coll > 0.0:
            frac = float(np.clip((t_serial - t_overlap) / t_coll,
                                 0.0, 1.0))
        self._overlap_fraction = frac
        if self._telemetry is not None:
            self._telemetry.set_overlap_fraction(frac)
        return frac

    def describe(self) -> Dict[str, object]:
        return {
            "dp": self.dp,
            "tp": self.tp,
            "stage": self.stage,
            "grad_accum": self.grad_accum,
            "devices": [d.id for d in self.devices],
            "params": len(self._shapes),
            "chunk_elems": sum(self._chunks.values()),
            "param_dtype": ("bf16" if self._param_dtype is not None
                            else "fp32"),
            "bucket_bytes": self.bucket_bytes,
            "overlap": self.overlap,
            "buckets": len(self._buckets),
            "overlap_fraction": self._overlap_fraction,
            "telemetry": (self._telemetry.summary()
                          if self._telemetry is not None else None),
        }


def zero_train_step(model, optimizer, loss_fn=None, *, stage: int = 1,
                    **kwargs) -> ZeroTrainStep:
    """Builder form of `ZeroTrainStep` (the API named in ROADMAP item
    4): `step = zero_train_step(model, opt, stage=1); params, st =
    step.init_state(); loss, params, st = step(params, st, (x, y), lr,
    t)`."""
    return ZeroTrainStep(model, optimizer, loss_fn, stage=stage, **kwargs)


def save_optimizer_state(step: ZeroTrainStep, opt_state):
    """Module-level alias of the degree-blind save (mirrors the serving
    journal's snapshot helpers)."""
    return step.save_optimizer_state(opt_state)


def load_optimizer_state(step: ZeroTrainStep, host_state):
    return step.load_optimizer_state(host_state)


# ===================================================================
# paddle-compat GroupSharded surface (GSPMD sharding-annotation flavor)
# -------------------------------------------------------------------
# Ref: fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py,
# group_sharded_optimizer_stage2.py + python/paddle/distributed/
# sharding/group_sharded.py (upstream layout, unverified — mount empty).
#
# Paddle implements ZeRO with explicit param slicing, pre-forward
# allgathers, grad reduce-scatter hooks and rank-local optimizer
# updates. This surface keeps the TPU-native GSPMD equivalent —
# sharding ANNOTATIONS consumed by a jitted train step (stage 1:
# opt-state dim-0 sharded; stage 2: + grads constrained to the
# scattered layout; stage 3: + params sharded with gather-on-use
# scheduled by XLA) — and now shares the repo's one mesh substrate and
# bridges to the explicit shard_map engine above via
# `zero_train_step()`.
# ===================================================================

def _default_mesh(axis: str = "sharding"):
    devs = device_order()
    return build_mesh(((axis, len(devs)),))


class _ShardedBase(Layer):
    stage = None
    _shard_params = False

    def __init__(self, layer: Layer, optimizer=None, group=None,
                 sync_buffers: bool = False, device: str = "tpu",
                 segment_size: int = 2 ** 20, offload: bool = False,
                 hcg=None, **kwargs):
        super().__init__()
        self._layers = layer
        self._optimizer = optimizer
        self.offload = offload
        if offload:
            try:  # fail LOUDLY at construction, not mid-training
                jax.devices()[0].memory("pinned_host")
            except Exception as e:
                raise NotImplementedError(
                    "offload=True needs a backend with pinned_host memory "
                    f"support; {jax.devices()[0].platform} reports none"
                ) from e
        if hcg is not None and hcg.mesh is not None and \
                hcg.get_sharding_parallel_world_size() > 1:
            self.mesh = hcg.mesh
            self.axis = "sharding"
        elif group is not None and getattr(group, "mesh", None) is not None:
            self.mesh = group.mesh
            self.axis = group.axis_name
        else:
            self.mesh = _default_mesh()
            self.axis = "sharding"
        if self._shard_params:
            self._place_params()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # ------------------------------------------------ sharding hint trees
    def data_sharding(self):
        axes = tuple(a for a in self.mesh.axis_names
                     if a in ("dp", "sharding") and self.mesh.shape[a] > 1)
        return NamedSharding(self.mesh, P(axes if axes else None))

    def param_sharding(self):
        """Prefix sharding for params: stage 1/2 replicate params."""
        return NamedSharding(self.mesh, P())

    def param_shardings(self, params: dict):
        if not self._shard_params:
            sh = self.param_sharding()
            return {k: sh for k in params}
        return {k: shard_leaf(v, self.mesh, self.axis)
                for k, v in params.items()}

    def opt_state_shardings(self, opt_state: dict):
        """Moment slots shaped like the param shard dim-0; scalars repl.
        With offload=True the slots additionally live in pinned host memory
        (ZeRO-offload: HBM holds only params/grads/activations; XLA streams
        the moments in for the update)."""
        out = {}
        for pname, acc in opt_state.items():
            shardings = {}
            for slot, v in acc.items():
                sh = shard_leaf(v, self.mesh, self.axis)
                if self.offload:
                    sh = sh.with_memory_kind("pinned_host")
                shardings[slot] = sh
            out[pname] = shardings
        return out

    def grad_shardings(self, params: dict):
        if self.stage >= 2:
            return {k: shard_leaf(v, self.mesh, self.axis)
                    for k, v in params.items()}
        return {k: NamedSharding(self.mesh, P()) for k in params}

    def _place_params(self):
        for _, p in self._layers.named_parameters():
            p._data = jax.device_put(
                p._data, shard_leaf(p._data, self.mesh, self.axis))

    # ------------------------------------------ explicit-engine bridge
    def zero_train_step(self, loss_fn=None, criterion=None,
                        **kwargs) -> ZeroTrainStep:
        """The one-implementation bridge (ISSUE 16 satellite): build
        the explicit shard_map ZeRO step for THIS wrapper's model +
        optimizer at dp = the sharding axis size. Stage 3 has no
        shard_map twin — its gather-on-use param sharding is the GSPMD
        placement-tree contract — so it refuses."""
        if self.stage >= 3:
            raise NotImplementedError(
                "stage 3 (p_g_os) shards params via the GSPMD placement "
                "trees (param_shardings); the explicit shard_map engine "
                "covers stages 1/2")
        return ZeroTrainStep(self._layers, self._optimizer,
                             loss_fn, criterion=criterion,
                             dp=int(self.mesh.shape[self.axis]),
                             stage=self.stage, **kwargs)

    # ------------------------------------------------------- delegation
    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        out = self._layers.set_state_dict(sd, *a, **k)
        if self._shard_params:
            self._place_params()
        return out

    def get_all_parameters(self, convert2cpu: bool = False):
        """stage3 API: gather full params (device_put to replicated)."""
        repl = NamedSharding(self.mesh, P())
        for _, p in self._layers.named_parameters():
            p._data = jax.device_put(p._data, repl)
        return self._layers.parameters()


class GroupShardedStage2(_ShardedBase):
    stage = 2
    _shard_params = False


class GroupShardedStage3(_ShardedBase):
    stage = 3
    _shard_params = True


class GroupShardedOptimizerStage2:
    """Optimizer wrapper partitioning state over the sharding axis (ZeRO-1/2
    optimizer side). Delegates the whole surface; the sharded placement is
    applied by the jitted step through opt_state_shardings."""

    def __init__(self, params, optim, group=None, offload: bool = False,
                 device: str = "tpu", **kwargs):
        self._optim = optim
        self._params = params
        self.offload = offload
        self.group = group

    def __getattr__(self, name):
        return getattr(self._optim, name)

    def step(self):
        return self._optim.step()

    def minimize(self, *a, **k):
        return self._optim.minimize(*a, **k)


def group_sharded_parallel(model: Layer, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm: bool = False,
                           dp_group=None, exclude_layer=None):
    """paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(
            f"group_sharded_parallel level must be 'os' (ZeRO-1), 'os_g' "
            f"(ZeRO-2) or 'p_g_os' (ZeRO-3); got {level!r}")
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer=optimizer, group=group,
                                     offload=offload)
    else:
        wrapped = GroupShardedStage2(model, optimizer=optimizer, group=group,
                                     offload=offload)
        wrapped.stage = 1 if level == "os" else 2
    opt = GroupShardedOptimizerStage2(model.parameters(), optimizer,
                                      group=group, offload=offload)
    if scaler is not None:
        return wrapped, opt, scaler
    return wrapped, opt


def save_group_sharded_model(model, output, optimizer=None):
    """Gather-on-rank0 save (ref: group_sharded.py save util)."""
    from ..framework.io import save as _save

    if hasattr(model, "get_all_parameters"):
        model.get_all_parameters()
    _save(model.state_dict(), str(output) + ".pdparams")
    if optimizer is not None:
        inner = getattr(optimizer, "_optim", optimizer)
        _save(inner.state_dict(), str(output) + ".pdopt")
