"""KV-cache generation (models/generation.py): cache parity vs full
recompute, greedy/sampling/eos behavior, GPT + LLaMA (GQA) coverage."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.functional import call_functional, extract_state
from paddle_tpu.models import (
    GPTConfig, GPTForCausalLM, LlamaConfig, LlamaForCausalLM,
)
from paddle_tpu.models.generation import init_caches


def _llama():
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m, LlamaConfig.tiny()


def _gpt():
    m = GPTForCausalLM(GPTConfig.tiny())
    m.eval()
    return m, GPTConfig.tiny()


@pytest.mark.parametrize("mk", [_llama, _gpt], ids=["llama", "gpt"])
class TestCacheParity:
    def test_prefill_matches_full_forward(self, mk):
        m, cfg = mk()
        ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8))
        full = m(paddle.to_tensor(ids)).numpy()
        params, buffers = extract_state(m)
        caches = init_caches(m, 2, 16)
        (cached, _), _ = call_functional(
            m, params, buffers, (Tensor(jnp.asarray(ids)),),
            kwargs={"caches": caches, "start_pos": 0}, training=False)
        np.testing.assert_allclose(np.asarray(cached), full, atol=2e-4)

    def test_greedy_generate_matches_full_recompute(self, mk):
        m, cfg = mk()
        ids = np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 6))
        out = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                         temperature=0.0).numpy()
        cur = ids.copy()
        for _ in range(5):
            lg = m(paddle.to_tensor(cur)).numpy()
            cur = np.concatenate([cur, lg[:, -1].argmax(-1)[:, None]],
                                 axis=1)
        np.testing.assert_array_equal(out, cur)


class TestSampling:
    def test_seeded_sampling_reproducible(self):
        m, cfg = _llama()
        ids = np.random.RandomState(2).randint(0, cfg.vocab_size, (1, 4))
        a = m.generate(paddle.to_tensor(ids), max_new_tokens=6,
                       temperature=0.8, seed=7).numpy()
        b = m.generate(paddle.to_tensor(ids), max_new_tokens=6,
                       temperature=0.8, seed=7).numpy()
        c = m.generate(paddle.to_tensor(ids), max_new_tokens=6,
                       temperature=0.8, seed=8).numpy()
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)  # different seed diverges (w.h.p.)

    def test_unseeded_sampling_differs_across_calls(self):
        m, cfg = _llama()
        ids = np.random.RandomState(6).randint(0, cfg.vocab_size, (1, 4))
        outs = {tuple(m.generate(paddle.to_tensor(ids), max_new_tokens=8,
                                 temperature=1.5).numpy()[0])
                for _ in range(4)}
        assert len(outs) > 1  # fresh entropy per unseeded call (w.h.p.)

    def test_jitted_steps_memoized_across_calls(self):
        m, cfg = _llama()
        ids = np.random.RandomState(7).randint(0, cfg.vocab_size, (1, 4))
        m.generate(paddle.to_tensor(ids), max_new_tokens=3, temperature=0.0)
        m.generate(paddle.to_tensor(ids), max_new_tokens=3, temperature=0.0)
        assert len(m._generate_jit_cache) == 1  # same shapes -> one entry

    def test_mismatched_cache_count_raises(self):
        m, cfg = _llama()
        from paddle_tpu.models.generation import init_caches
        caches = init_caches(m, 1, 8)[:-1]  # one short
        ids = paddle.to_tensor(np.zeros((1, 4), np.int64))
        with pytest.raises(ValueError, match="caches"):
            m(ids, caches=caches, start_pos=0)

    def test_top_k_one_is_greedy(self):
        m, cfg = _llama()
        ids = np.random.RandomState(3).randint(0, cfg.vocab_size, (1, 4))
        greedy = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                            temperature=0.0).numpy()
        topk1 = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                           temperature=0.5, top_k=1, seed=0).numpy()
        np.testing.assert_array_equal(greedy, topk1)

    def test_output_shape_and_prompt_preserved(self):
        m, cfg = _gpt()
        ids = np.random.RandomState(4).randint(0, cfg.vocab_size, (3, 5))
        out = m.generate(paddle.to_tensor(ids), max_new_tokens=4,
                         temperature=0.0).numpy()
        assert out.shape == (3, 9)
        np.testing.assert_array_equal(out[:, :5], ids)
        assert (out >= 0).all() and (out < cfg.vocab_size).all()

    def test_eos_padding(self):
        m, cfg = _llama()
        ids = np.random.RandomState(5).randint(0, cfg.vocab_size, (1, 4))
        # force eos on the very first sampled token by making every token eos
        out_free = m.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              temperature=0.0).numpy()
        eos = int(out_free[0, 4])  # greedy first new token
        out = m.generate(paddle.to_tensor(ids), max_new_tokens=6,
                         temperature=0.0, eos_token_id=eos).numpy()
        assert out.shape == (1, 10)
        # after the first eos, everything is eos
        assert (out[0, 4:] == eos).all()
