"""auto_parallel Engine — prepare/fit/evaluate/predict over a ProcessMesh
(ref: python/paddle/distributed/auto_parallel/static/engine.py + the
completion/partitioner passes, upstream layout, unverified — mount empty).

Upstream's Engine lowers a dygraph model to a distributed static program in
three passes: *completion* (propagate dist attrs to unannotated tensors),
*partitioner* (split the serial program per rank), *reshard* (insert
communication). The TPU-native pipeline keeps the same three seams but each
is a fraction of the upstream size because GSPMD owns the hard parts:

- completion  → :func:`complete_param_shardings`: every parameter gets a
  NamedSharding — its Megatron ``dist_spec`` mark if present (axes missing
  from the mesh drop to replicated), else replicated; inputs get the batch
  axis sharded over the mesh's data dims;
- partitioner → ``jax.jit`` with those shardings over the global mesh: XLA
  partitions every op and inserts the collectives (the reshard pass);
- the Engine drives the jitted step: fit/evaluate/predict with functional
  optimizer state threaded through, mirroring the hapi Model loop.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["Engine", "complete_param_shardings"]


def complete_param_shardings(layer, mesh):
    """The completion pass: per-param NamedSharding from dist_spec marks
    (replicated when unmarked), plus the batch-data sharding. One rule,
    shared with the TP layers and the static fleet pass."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..static.fleet_pass import data_sharding
    from .fleet.meta_parallel.parallel_layers import mp_shardings

    return (mp_shardings(layer, mesh), data_sharding(mesh),
            NamedSharding(mesh, P()))


class Engine:
    """auto.Engine analog: one jitted hybrid step over the whole mesh."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 strategy=None, mesh=None):
        self._model = model
        self._loss = loss
        self._opt = optimizer
        self._metrics = ([] if metrics is None else
                         metrics if isinstance(metrics, (list, tuple))
                         else [metrics])
        self._strategy = strategy
        if mesh is None:
            from .auto_parallel import get_mesh

            pm = get_mesh()
            mesh = pm.jax_mesh() if pm is not None else None
        self._mesh = getattr(mesh, "jax_mesh", lambda: mesh)() \
            if hasattr(mesh, "jax_mesh") else mesh
        self._prepared = False
        self._opt_state = None
        self.history: Dict[str, List[float]] = {"loss": []}

    # ------------------------------------------------------------- prepare
    def prepare(self):
        """Completion + partitioning: place params, build the jitted
        train/eval/predict steps."""
        if self._prepared:
            return
        if self._mesh is None:
            raise ValueError("Engine needs a mesh (pass mesh= or set_mesh)")
        from ..jit.functional import call_functional, extract_state

        param_sh, data_sh, repl = complete_param_shardings(
            self._model, self._mesh)
        self._param_sh, self._data_sh, self._repl = param_sh, data_sh, repl

        # place the live parameters once (completion materialized)
        named = dict(self._model.named_parameters())
        for name, p in named.items():
            p._data = jax.device_put(p._data, param_sh[name])

        model, loss_fn = self._model, self._loss
        opt = self._opt

        def fwd(params, buffers, x, training):
            outs, new_buffers = call_functional(
                model, params, buffers, (x,), training=training)
            return outs, new_buffers

        def train_step(params, buffers, opt_state, lr, t, x, y):
            def loss_of(p):
                outs, new_buffers = fwd(p, buffers, x, True)
                logits = outs[0] if isinstance(outs, (tuple, list)) else outs
                from ..core import tape as tape_mod

                with tape_mod.no_grad():
                    loss = loss_fn(Tensor(logits), Tensor(y))
                return loss._data, (new_buffers, logits)

            (loss, (new_buffers, logits)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_state = opt.functional_step(params, grads,
                                                        opt_state, lr, t)
            return loss, logits, new_params, new_buffers, new_state

        def eval_step(params, buffers, x, y):
            outs, _ = fwd(params, buffers, x, False)
            logits = outs[0] if isinstance(outs, (tuple, list)) else outs
            from ..core import tape as tape_mod

            with tape_mod.no_grad():
                loss = loss_fn(Tensor(logits), Tensor(y))
            return loss._data, logits

        def predict_step(params, buffers, x):
            outs, _ = fwd(params, buffers, x, False)
            return outs[0] if isinstance(outs, (tuple, list)) else outs

        if opt is not None:
            # ZeRO over the mesh's `sharding` axis: moments of replicated
            # params are dim-0 sharded (rank-local optimizer state);
            # TP-sharded params keep their moment layout. Outputs are
            # pinned so sharded moments can't drift new_params' layout
            # past the next call's in_shardings. Only the state's SHAPE
            # structure is needed here (eval_shape, no allocation) — the
            # real buffers materialize on first fit(), so an eval/predict-
            # only Engine never pays the optimizer-state memory.
            params0, _ = extract_state(model)
            state_shapes = jax.eval_shape(opt.functional_state, params0)
            self._opt_sh = self._opt_state_shardings(state_shapes, params0,
                                                     param_sh)
            self._train_jit = jax.jit(
                train_step,
                in_shardings=(param_sh, repl, self._opt_sh, repl, repl,
                              data_sh, data_sh),
                out_shardings=(None, None, param_sh, repl, self._opt_sh),
                donate_argnums=(0, 2))
        self._eval_jit = jax.jit(
            eval_step, in_shardings=(param_sh, repl, data_sh, data_sh))
        self._predict_jit = jax.jit(
            predict_step, in_shardings=(param_sh, repl, data_sh))
        self._extract_state = extract_state
        self._prepared = True

    def _opt_state_shardings(self, state_shapes, params0, param_sh):
        """Per-slot placement over the state's ShapeDtypeStruct tree:
        param-layout for TP-sharded params, ZeRO dim-0 over the `sharding`
        axis for the rest (when the mesh has one), replicated otherwise."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .fleet.meta_parallel.sharding import shard_leaf

        mesh = self._mesh
        zero = ("sharding" in mesh.axis_names
                and mesh.shape["sharding"] > 1)
        repl = NamedSharding(mesh, P())

        def slot_sh(psh, tp_sharded, v, pshape):
            # slots are not guaranteed param-shaped (e.g. ASGD's history
            # slot prepends a batch dim): the param spec only applies to a
            # slot whose shape matches the param's
            if tp_sharded:
                return psh if tuple(getattr(v, "shape", ())) == pshape \
                    else repl
            if zero:
                return shard_leaf(v, mesh, "sharding")
            return repl

        out = {}
        for name, acc in state_shapes.items():
            psh = param_sh.get(name)
            tp_sharded = psh is not None and any(tuple(psh.spec))
            pshape = tuple(params0[name].shape) if tp_sharded else None
            out[name] = {slot: slot_sh(psh, tp_sharded, v, pshape)
                         for slot, v in acc.items()}
        return out

    def _ensure_opt_state(self, params):
        if self._opt_state is None:
            self._opt_state = jax.tree_util.tree_map(
                jax.device_put, self._opt.functional_state(params),
                self._opt_sh, is_leaf=lambda x: isinstance(x, jax.Array))

    # -------------------------------------------------------------- loops
    def _loader(self, data, batch_size, train=False):
        from ..io import DataLoader, Dataset

        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            # drop_last only where the jitted train step needs shape
            # stability; eval/predict must cover the tail batch
            return DataLoader(data, batch_size=batch_size or 32,
                              drop_last=train)
        raise TypeError("Engine expects a Dataset or DataLoader")

    @staticmethod
    def _arrays(batch):
        out = []
        for b in batch:
            out.append(b._data if isinstance(b, Tensor)
                       else jnp.asarray(np.asarray(b)))
        return out

    def fit(self, train_data, epochs: int = 1, batch_size: Optional[int] = None,
            verbose: int = 0, log_freq: int = 10):
        if self._opt is None or self._loss is None:
            raise ValueError("fit() needs both an optimizer and a loss")
        self.prepare()
        loader = self._loader(train_data, batch_size, train=True)
        params, buffers = self._extract_state(self._model)
        self._ensure_opt_state(params)   # lazy: ZeRO-aware layout
        try:
            for epoch in range(epochs):
                for batch in loader:
                    x, y = self._arrays(batch)[:2]
                    self._opt._step_count += 1
                    lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
                    t = jnp.asarray(self._opt._step_count, jnp.int32)
                    loss, logits, params, buffers, self._opt_state = \
                        self._train_jit(params, buffers, self._opt_state,
                                        lr, t, x, y)
                    self.history["loss"].append(float(np.asarray(loss)))
                if verbose:
                    print(f"[auto.Engine] epoch {epoch + 1}/{epochs} "
                          f"loss={self.history['loss'][-1]:.4f}")
        finally:
            # ALWAYS write state back: the step donates the param buffers,
            # so bailing out mid-fit without rebinding would leave the live
            # model pointing at deleted arrays
            named = dict(self._model.named_parameters())
            for name, val in params.items():
                named[name]._data = val
            bnamed = {n: b for n, b in self._model.named_buffers()
                      if b is not None}
            for name, val in buffers.items():
                if name in bnamed:
                    bnamed[name]._data = val
        return self.history

    def evaluate(self, eval_data, batch_size: Optional[int] = None,
                 verbose: int = 0):
        if self._loss is None:
            raise ValueError("evaluate() needs a loss")
        self.prepare()
        loader = self._loader(eval_data, batch_size)
        params, buffers = self._extract_state(self._model)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            x, y = self._arrays(batch)[:2]
            loss, logits = self._eval_jit(params, buffers, x, y)
            losses.append(float(np.asarray(loss)))
            for m in self._metrics:
                m.update(m.compute(Tensor(logits), Tensor(y)))
        out = {"loss": float(np.mean(losses))}
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if not isinstance(names, (list, tuple)):
                names, vals = [names], [vals]
            elif not isinstance(vals, (list, tuple)):
                vals = [vals]
            out.update(zip(names, vals))
        return out

    def predict(self, test_data, batch_size: Optional[int] = None):
        self.prepare()
        loader = self._loader(test_data, batch_size)
        params, buffers = self._extract_state(self._model)
        outs = []
        for batch in loader:
            arrays = self._arrays(batch)
            outs.append(np.asarray(self._predict_jit(params, buffers,
                                                     arrays[0])))
        return outs

    # ------------------------------------------------------- introspection
    def param_shardings(self):
        self.prepare()
        return dict(self._param_sh)
