"""Drive the rules over files/trees and produce findings + reports."""
import os
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

from .core import Finding, ModuleCache, Rule
from .rules import all_rules

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    """Expand files/directories into .py files, deterministic order."""
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _rel(path: str, root: Optional[str]) -> str:
    if root is not None:
        try:
            rel = os.path.relpath(path, root)
            if not rel.startswith(".."):
                return rel.replace(os.sep, "/")
        except ValueError:
            pass  # different drive on windows
    return path.replace(os.sep, "/")


def run_paths(paths: Sequence[str],
              rules: Optional[Sequence[Rule]] = None,
              root: Optional[str] = None,
              cache: Optional[ModuleCache] = None) -> List[Finding]:
    """Analyze all .py files under `paths`; findings carry paths relative
    to `root` (so baselines are checkout-location independent). Inline
    noqa suppressions are already applied; baseline filtering is the
    caller's job (the CLI/gate owns the baseline)."""
    rules = list(rules) if rules is not None else all_rules()
    cache = cache or ModuleCache()
    findings: List[Finding] = []
    for filename in iter_python_files(paths):
        module = cache.parse_file(filename, _rel(filename, root))
        if module is None:
            continue
        for rule in rules:
            findings.extend(rule.check(module))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_source(source: str, path: str = "<memory>",
               rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Analyze one in-memory snippet (the fixture-test entry point)."""
    rules = list(rules) if rules is not None else all_rules()
    cache = ModuleCache()
    module = cache.parse_source(source, path)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(module))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def report_json(findings: Sequence[Finding],
                baselined: Sequence[Finding] = (),
                stale: Sequence[dict] = (),
                errors: Optional[Dict[str, str]] = None) -> dict:
    """Machine-readable report (bench.py embeds this as a `lint` phase)."""
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "unbaselined": [f.to_json() for f in findings],
        "unbaselined_count": len(findings),
        "baselined_count": len(baselined),
        "stale_baseline_count": len(stale),
        "by_rule": dict(sorted(by_rule.items())),
        "parse_errors": dict(errors or {}),
        "clean": not findings and not (errors or {}),
    }
