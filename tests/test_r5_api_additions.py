"""Behavioral tests for the round-5 API-audit closures (VERDICT r4 #7):
every name added to reach 100% coverage does real work, not just import."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _fit_quadratic(opt_cls, **kw):
    paddle.seed(0)
    lin = nn.Linear(1, 1, bias_attr=False)
    lin.weight.set_value(np.array([[3.0]], np.float32))
    opt = opt_cls(learning_rate=0.1, parameters=lin.parameters(), **kw)
    for _ in range(150):
        loss = (lin.weight * lin.weight).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return abs(float(np.asarray(lin.weight.numpy())[0, 0]))


class TestNewOptimizers:
    def test_nadam_converges(self):
        assert _fit_quadratic(paddle.optimizer.NAdam) < 0.3

    def test_radam_converges(self):
        assert _fit_quadratic(paddle.optimizer.RAdam) < 0.3


class TestAmpSupportFlags:
    def test_flags(self):
        assert paddle.amp.is_bfloat16_supported() is True
        assert paddle.amp.is_float16_supported() is True


class TestJitToggles:
    def test_enable_to_static_off_runs_eager(self):
        calls = []

        def f(x):
            calls.append(1)
            return x * 2

        sf = paddle.jit.to_static(f)
        paddle.jit.enable_to_static(False)
        try:
            out = sf(paddle.to_tensor(np.ones(2, np.float32)))
            np.testing.assert_allclose(np.asarray(out.numpy()), [2., 2.])
            assert not sf._cache, "disabled to_static still compiled"
        finally:
            paddle.jit.enable_to_static(True)
        out = sf(paddle.to_tensor(np.ones(2, np.float32)))
        assert sf._cache, "re-enabled to_static did not compile"

    def test_verbosity_setters_exist(self):
        paddle.jit.set_code_level(0)
        paddle.jit.set_verbosity(0)


class TestSavedTensorHooks:
    def test_pack_unpack_intercept(self):
        packed, unpacked = [], []

        def pack(t):
            packed.append(t)
            return np.asarray(t.numpy())      # e.g. offload to host

        def unpack(h):
            unpacked.append(h)
            return paddle.to_tensor(h)

        class Square(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor
                return dy * 2.0 * x

        x = paddle.to_tensor(np.array([3.0], np.float32))
        x.stop_gradient = False
        with paddle.autograd.saved_tensors_hooks(pack, unpack):
            y = Square.apply(x)
        y.backward()
        assert len(packed) == 1 and isinstance(packed[0], paddle.Tensor)
        assert len(unpacked) == 1 and isinstance(unpacked[0], np.ndarray)
        np.testing.assert_allclose(np.asarray(x.grad.numpy()), [6.0])


class TestSparseReshape:
    def test_roundtrip_dense(self):
        dense = np.zeros((2, 6), np.float32)
        dense[0, 1] = 3.0
        dense[1, 4] = -2.0
        sp = paddle.sparse.sparse_coo_tensor(
            paddle.to_tensor(np.array([[0, 1], [1, 4]])),
            paddle.to_tensor(np.array([3.0, -2.0], np.float32)),
            shape=[2, 6])
        out = paddle.sparse.reshape(sp, [3, 4])
        np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                                   dense.reshape(3, 4))


class TestSegmentOps:
    def test_segment_family(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2))
        ids = paddle.to_tensor(np.array([0, 0, 1, 1, 1, 2]))
        inc = paddle.incubate
        np.testing.assert_allclose(
            np.asarray(inc.segment_sum(x, ids).numpy()),
            [[2, 4], [18, 21], [10, 11]])
        np.testing.assert_allclose(
            np.asarray(inc.segment_mean(x, ids).numpy()),
            [[1, 2], [6, 7], [10, 11]])
        np.testing.assert_allclose(
            np.asarray(inc.segment_min(x, ids).numpy()),
            [[0, 1], [4, 5], [10, 11]])

    def test_softmax_mask_fuse_and_identity_loss(self):
        x = paddle.to_tensor(np.zeros((1, 4), np.float32))
        mask = paddle.to_tensor(
            np.array([[0., 0., -1e9, -1e9]], np.float32))
        out = np.asarray(paddle.incubate.softmax_mask_fuse(x, mask).numpy())
        np.testing.assert_allclose(out, [[0.5, 0.5, 0.0, 0.0]], atol=1e-6)
        v = paddle.incubate.identity_loss(
            paddle.to_tensor(np.array([2.0, 4.0], np.float32)), "mean")
        assert float(np.asarray(v.numpy())) == 3.0

    def test_graph_send_recv(self):
        x = paddle.to_tensor(np.eye(3, dtype=np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 2]))
        dst = paddle.to_tensor(np.array([1, 0, 0, 1]))
        out = paddle.incubate.graph_send_recv(x, src, dst, "sum")
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   [[0, 1, 1], [1, 0, 1], [0, 0, 0]])


class TestDetectionOpsR5:
    def test_psroi_pool_constant_plane(self):
        # channel layout (oc, ph, pw): c = o*4 + i*2 + j. Constant planes
        # per channel: output bin (i, j), channel o must read exactly
        # channel o*4 + i*2 + j's constant
        x = np.zeros((1, 8, 4, 4), np.float32)
        for c in range(8):
            x[0, c] = 10 * (c // 4) + (c % 4)
        boxes = paddle.to_tensor(np.array([[0., 0., 3., 3.]], np.float32))
        out = paddle.vision.ops.psroi_pool(
            paddle.to_tensor(x), boxes,
            paddle.to_tensor(np.array([1])), 2)
        got = np.asarray(out.numpy())[0]
        for o in range(2):
            for i in range(2):
                for j in range(2):
                    np.testing.assert_allclose(got[o, i, j],
                                               10 * o + i * 2 + j)

    def test_distribute_fpn_proposals_levels(self):
        rois = paddle.to_tensor(np.array(
            [[0, 0, 16, 16], [0, 0, 220, 220], [0, 0, 56, 56]], np.float32))
        mr, nums, restore = paddle.vision.ops.distribute_fpn_proposals(
            rois, 2, 5, 4, 224, rois_num=paddle.to_tensor(np.array([3])))
        sizes = [np.asarray(m.numpy()).shape[0] for m in mr]
        assert sum(sizes) == 3
        assert sizes[0] >= 1          # the 16x16 box lands on min_level
        # restore maps each input RoI to its row in concat(levels)
        cat = np.concatenate([np.asarray(m.numpy())
                              for m in mr if len(np.asarray(m.numpy()))])
        orig = np.asarray(paddle.to_tensor(np.array(
            [[0, 0, 16, 16], [0, 0, 220, 220], [0, 0, 56, 56]],
            np.float32)).numpy())
        np.testing.assert_allclose(cat[np.asarray(restore.numpy())], orig)

    def test_generate_proposals_shapes(self):
        R = np.random.RandomState(0)
        h = w = 4
        scores = paddle.to_tensor(R.rand(3, h, w).astype("float32"))
        deltas = paddle.to_tensor(
            (R.randn(12, h, w) * 0.1).astype("float32"))
        anchors = paddle.to_tensor(R.rand(h, w, 3, 4).astype("float32")
                                   * 32)
        var = paddle.to_tensor(np.ones((h, w, 3, 4), np.float32))
        rois, rsc, nums = paddle.vision.ops.generate_proposals(
            scores, deltas, paddle.to_tensor(np.array([64., 64.])),
            anchors, var, pre_nms_top_n=20, post_nms_top_n=6,
            return_rois_num=True)
        n = int(np.asarray(nums.numpy())[0])
        assert 1 <= n <= 6
        assert np.asarray(rois.numpy()).shape == (n, 4)
        # scores sorted descending after NMS keep-order
        s = np.asarray(rsc.numpy())
        assert (np.diff(s) <= 1e-6).all()

    def test_yolo_loss_finite_and_positive(self):
        R = np.random.RandomState(0)
        x = paddle.to_tensor(R.randn(2, 24, 4, 4).astype("float32"))
        gtb = paddle.to_tensor(np.array(
            [[[0.5, 0.5, 0.3, 0.4], [0, 0, 0, 0]]] * 2, np.float32))
        gtl = paddle.to_tensor(np.array([[1, 0]] * 2, np.int64))
        loss = paddle.vision.ops.yolo_loss(
            x, gtb, gtl,
            anchors=[10, 13, 16, 30, 33, 23], anchor_mask=[0, 1, 2][:3],
            class_num=3, ignore_thresh=0.7, downsample_ratio=32)
        v = np.asarray(loss.numpy())
        assert v.shape == (2,) and np.isfinite(v).all() and (v > 0).all()

    def test_fused_matmul_bias(self):
        R = np.random.RandomState(1)
        x = paddle.to_tensor(R.randn(3, 4).astype("float32"))
        y = paddle.to_tensor(R.randn(4, 5).astype("float32"))
        b = paddle.to_tensor(R.randn(5).astype("float32"))
        out = paddle.incubate.nn.functional.fused_matmul_bias(x, y, b)
        np.testing.assert_allclose(
            np.asarray(out.numpy()),
            np.asarray(x.numpy()) @ np.asarray(y.numpy())
            + np.asarray(b.numpy()), rtol=1e-5)
