"""Automatic mixed precision — paddle.amp analog, TPU-first.

Ref: python/paddle/amp/auto_cast.py, grad_scaler.py (upstream layout,
unverified — mount empty). O1 = white/black-list autocast at op dispatch; O2 =
"pure" low-precision (params decorated to the amp dtype, fp32 master weights in
the optimizer). On TPU the natural dtype is bfloat16, whose exponent range
matches fp32 — so loss scaling is mathematically unnecessary; GradScaler keeps
paddle's API/semantics (incl. dynamic scaling for float16) but defaults to a
no-op-safe identity path under bfloat16.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core import dispatch as _dispatch
from ..core.tensor import Tensor

# Ops that are numerically safe & fast in low precision (MXU-bound).
WHITE_LIST = {
    "matmul", "mm", "bmm", "conv1d", "conv2d", "conv3d", "conv2d_transpose",
    "linear", "einsum", "addmm",
}
# Ops kept in fp32 for numerical stability.
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "nll_loss", "cosine_similarity", "mean", "sum", "pow", "rsqrt",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "norm",
    "cumsum", "cumprod", "sigmoid_cross_entropy_with_logits", "erfinv",
    "kl_div",
}

_STATE = {
    "enabled": False,
    "level": "O1",
    "dtype": jnp.bfloat16,
    "white": frozenset(),
    "black": frozenset(),
}


def _is_float(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.floating)


def _amp_handler(opdef, datas):
    """Installed into core.dispatch: cast op inputs per list membership."""
    if not _STATE["enabled"]:
        return datas
    if opdef.inplace_view:
        return datas
    name = opdef.name
    amp_dtype = _STATE["dtype"]
    # name lists first, then the OpDef's own amp_list declaration (the
    # ops.yaml `amp:` field) — one policy, two declaration sites
    if name in _STATE["black"] or opdef.amp_list == "black":
        target = jnp.float32
    elif (_STATE["level"] == "O2" or name in _STATE["white"]
          or opdef.amp_list == "white"):
        target = amp_dtype
    else:
        return datas
    return [
        d.astype(target) if _is_float(d.dtype) and d.dtype != target else d
        for d in datas
    ]


_dispatch.set_amp_handler(_amp_handler)


def _resolve_dtype(dtype):
    if dtype in ("float16", "fp16", jnp.float16, np.float16):
        return jnp.float16
    return jnp.bfloat16


class auto_cast:
    """Context manager enabling autocast (paddle.amp.auto_cast).

    level 'O1': white-listed ops run in `dtype`, black-listed ops in fp32,
    everything else follows its inputs. 'O2': all float ops in `dtype` except
    the black list.
    """

    def __init__(self, enable: bool = True,
                 custom_white_list: Optional[Sequence[str]] = None,
                 custom_black_list: Optional[Sequence[str]] = None,
                 level: str = "O1", dtype: str = "bfloat16",
                 use_promote: bool = True):
        if level not in ("O0", "O1", "O2", "OD"):
            raise ValueError(f"level must be O0/OD/O1/O2, got {level!r}")
        self.enable = enable and level not in ("O0",)
        self.level = level
        self.dtype = _resolve_dtype(dtype)
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        self.white = frozenset(white)
        self.black = frozenset(black)
        self._saved = None

    def __enter__(self):
        self._saved = dict(_STATE)
        _STATE.update(
            enabled=self.enable, level=self.level, dtype=self.dtype,
            white=self.white, black=self.black,
        )
        return self

    def __exit__(self, *exc):
        _STATE.update(self._saved)
        return False


amp_guard = auto_cast  # legacy alias (paddle.fluid.dygraph.amp_guard)


def is_auto_cast_enabled() -> bool:
    return _STATE["enabled"]


def get_amp_dtype():
    return _STATE["dtype"] if _STATE["enabled"] else jnp.float32


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate: cast model params to the amp dtype (O2 path).

    Optimizers already keep fp32 master copies per-param (multi_precision), so
    only the live params are cast here.
    """
    if level not in ("O1", "O2"):
        raise ValueError("decorate level must be O1 or O2")
    target = _resolve_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if _is_float(p._data.dtype):
                    p._data = p._data.astype(target)
    if optimizers is None:
        return models
    # O2 updates low-precision params; unless the caller explicitly opted out
    # (master_weight=False), the optimizer must keep fp32 master weights —
    # paddle's decorate enables multi_precision by default for this reason.
    if level == "O2" and master_weight is not False:
        single_opt = not isinstance(optimizers, (list, tuple))
        for opt in [optimizers] if single_opt else list(optimizers):
            if hasattr(opt, "_multi_precision"):
                opt._multi_precision = True
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (paddle.amp.GradScaler).

    Ref: python/paddle/amp/grad_scaler.py (upstream layout, unverified).
    Under bfloat16 (TPU default) scaling is unnecessary; `enable=False` or
    bfloat16 autocast makes scale/step the identity path with zero overhead.
    """

    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2, use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer unscale tracking (paddle's OptimizerState INIT/
        # UNSCALED/STEPPED): the documented pattern
        #   scaler.unscale_(opt); clip(...); scaler.step(opt)
        # must not divide the grads by the scale a second time in step().
        # WeakSet so a GC'd optimizer can never alias a new one's identity;
        # each optimizer's own inf-status rides on the optimizer object.
        import weakref

        self._unscaled = weakref.WeakSet()
        self._stepped = weakref.WeakSet()

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._use_dynamic

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable or self._scale == 1.0:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """check_finite_and_unscale analog: divide grads by scale, detect inf."""
        if not self._enable:
            return
        if optimizer in self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data
            if self._scale != 1.0:
                g = g * jnp.asarray(inv, dtype=g.dtype)
                p.grad._data = g
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
        optimizer._amp_found_inf = found
        self._found_inf = self._found_inf or found  # aggregate for update()
        self._unscaled.add(optimizer)

    def step(self, optimizer):
        if self._enable and optimizer in self._stepped:
            # paddle's contract: without this, the second step() would skip
            # unscaling (opt still marked UNSCALED) and apply gradients still
            # multiplied by the loss scale — silent divergence
            raise RuntimeError(
                "step() has already been called since the last update()")
        if self._enable and optimizer not in self._unscaled:
            self.unscale_(optimizer)
        # consult THIS optimizer's inf status, not whichever optimizer was
        # unscaled last — skipping opt1's step because opt2 overflowed (or
        # vice versa) corrupts multi-optimizer training
        if not getattr(optimizer, "_amp_found_inf", self._found_inf):
            optimizer.step()
        self._stepped.add(optimizer)

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        """update_loss_scaling analog: grow/shrink the scale."""
        for opt in list(self._unscaled):
            opt._amp_found_inf = False
        self._unscaled.clear()
        self._stepped.clear()
        if not (self._enable and self._use_dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, new_scale: float):
        self._scale = float(new_scale)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


def is_float16_supported(device=None) -> bool:
    """XLA computes fp16 on every backend we target (TPU runs it through
    the bf16/fp32 units; CPU emulates) — supported, though bfloat16 is the
    native/recommended low-precision dtype on TPU."""
    return True


def is_bfloat16_supported(device=None) -> bool:
    """bfloat16 is the TPU MXU's native input dtype."""
    return True
