"""paddle.signal — STFT family (ref: python/paddle/signal.py, upstream
layout, unverified — mount empty): frame, overlap_add, stft, istft.

TPU note: framing is a gather over a [frames, frame_length] index grid and
the transforms are jnp.fft (XLA-native), so everything here jits; istft's
overlap-add uses segment-style scatter-add (`.at[].add`), which XLA lowers
to an efficient scatter on TPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _get_window(window, win_length, dtype):
    if window is None:
        return jnp.ones((win_length,), dtype)
    w = _unwrap(window)
    if w.shape[-1] != win_length:
        raise ValueError(
            f"window length {w.shape[-1]} != win_length {win_length}")
    return w.astype(dtype)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along `axis` (librosa-style)."""
    xd = _unwrap(x)
    if axis not in (-1, xd.ndim - 1, 0):
        raise ValueError("frame supports axis=0 or axis=-1")
    seq_last = axis in (-1, xd.ndim - 1)
    T = xd.shape[-1] if seq_last else xd.shape[0]
    if frame_length > T:
        raise ValueError(f"frame_length {frame_length} > signal length {T}")
    n_frames = 1 + (T - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    if seq_last:
        out = xd[..., idx]                       # [..., frames, frame_len]
        out = jnp.swapaxes(out, -1, -2)          # [..., frame_len, frames]
    else:
        out = xd[idx]                            # [frames, frame_len, ...]
        out = jnp.moveaxis(out, (0, 1), (1, 0))  # [frame_len, frames, ...]
    return Tensor(out)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of `frame`: add overlapping frames back into a signal.

    x: [..., frame_length, n_frames] (axis=-1) or
       [frame_length, n_frames, ...] (axis=0).
    """
    xd = _unwrap(x)
    if axis not in (-1, xd.ndim - 1, 0):
        raise ValueError("overlap_add supports axis=0 or axis=-1")
    seq_last = axis in (-1, xd.ndim - 1)
    if not seq_last:
        xd = jnp.moveaxis(xd, (0, 1), (-2, -1))
    frame_length, n_frames = xd.shape[-2], xd.shape[-1]
    T = hop_length * (n_frames - 1) + frame_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])     # [frames, frame_len]
    out = jnp.zeros(xd.shape[:-2] + (T,), xd.dtype)
    contrib = jnp.swapaxes(xd, -1, -2)              # [..., frames, flen]
    out = out.at[..., idx].add(contrib)
    if not seq_last:
        out = jnp.moveaxis(out, -1, 0)
    return Tensor(out)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform. x: (B, T) or (T,) real or complex;
    returns complex (B, F, n_frames) with F = n_fft//2+1 if onesided."""
    xd = _unwrap(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    is_complex = jnp.iscomplexobj(xd)
    if is_complex and onesided:
        raise ValueError("onesided is not supported for complex inputs")
    real_dtype = jnp.float32 if xd.dtype != jnp.float64 else jnp.float64
    w = _get_window(window, win_length, real_dtype)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    if center:
        pad = [(0, 0)] * (xd.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        xd = jnp.pad(xd, pad, mode=pad_mode)
    T = xd.shape[-1]
    if T < n_fft:
        raise ValueError(
            f"stft input length {T} (after centering) < n_fft {n_fft}")
    n_frames = 1 + (T - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    frames = xd[..., idx] * w                        # [..., frames, n_fft]
    if onesided:
        spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
    else:
        spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, real_dtype))
    return Tensor(jnp.moveaxis(spec, -1, -2))        # [..., F, frames]


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope (NOLA) normalization."""
    xd = _unwrap(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    real_dtype = jnp.float32
    w = _get_window(window, win_length, real_dtype)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    spec = jnp.moveaxis(xd, -2, -1)                  # [..., frames, F]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, real_dtype))
    if onesided:
        if return_complex:
            raise ValueError(
                "return_complex=True requires onesided=False (a onesided "
                "spectrum reconstructs a real signal)")
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, n=n_fft, axis=-1)
        if not return_complex:
            frames = frames.real
    wf = frames * w                                  # synthesis window
    n_frames = wf.shape[-2]
    T = hop_length * (n_frames - 1) + n_fft
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    sig = jnp.zeros(wf.shape[:-2] + (T,), wf.dtype)
    sig = sig.at[..., idx].add(wf)
    env = jnp.zeros((T,), real_dtype).at[idx.reshape(-1)].add(
        jnp.broadcast_to(w * w, (n_frames, n_fft)).reshape(-1))
    sig = sig / jnp.maximum(env, 1e-11)
    if center:
        sig = sig[..., n_fft // 2:T - n_fft // 2]
    if length is not None:
        sig = sig[..., :length]
    return Tensor(sig)
