"""Pallas flash attention — interpret-mode CI (verdict item #4).

The round-1 kernel never ran in CI (CPU always took the jnp fallback) and had
no backward. These tests run the REAL kernel via pallas_call(interpret=True)
on CPU, forward and backward, against the jnp reference, across the widened
shape space: head_dim 64 (flagship), seq not a multiple of the block, causal,
additive masks (broadcast and per-head).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_kernels import _flash_attention_data


def _ref_attention(q, k, v, mask=None, is_causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if mask is not None:
        s = s + mask
    if is_causal:
        sq, sk = s.shape[-2], s.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(causal, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _rand_qkv(rng, b, sq, sk, h, d):
    q = jnp.asarray(rng.randn(b, sq, h, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, sk, h, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, sk, h, d).astype("float32"))
    return q, k, v


CASES = [
    # (sq, sk, h, d, causal) — d=64 is the ERNIE/GPT-base flagship shape
    (128, 128, 2, 64, False),
    (128, 128, 2, 64, True),
    (200, 200, 1, 64, True),     # seq not a multiple of 128
    (256, 384, 2, 32, False),    # cross-attention, small head
    (96, 96, 1, 80, False),      # d not a power of two
    (128, 128, 4, 128, True),    # d=128: PACKED (b, S, h*d) layout
    (200, 200, 2, 128, False),   # packed + ragged seq padding
]


@pytest.mark.parametrize("sq,sk,h,d,causal", CASES)
def test_forward_matches_reference(sq, sk, h, d, causal):
    rng = np.random.RandomState(0)
    q, k, v = _rand_qkv(rng, 2, sq, sk, h, d)
    out = _flash_attention_data(q, k, v, is_causal=causal, interpret=True)
    ref = _ref_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_forward_with_additive_mask():
    rng = np.random.RandomState(1)
    q, k, v = _rand_qkv(rng, 2, 128, 128, 2, 64)
    # block half the keys for the first batch element, broadcast over heads
    mask = np.zeros((2, 1, 128, 128), dtype="float32")
    mask[0, :, :, 64:] = -1e9
    mask = jnp.asarray(mask)
    out = _flash_attention_data(q, k, v, mask, has_mask=True,
                                interpret=True)
    ref = _ref_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_forward_per_head_mask():
    rng = np.random.RandomState(2)
    q, k, v = _rand_qkv(rng, 1, 128, 128, 2, 64)
    mask = jnp.asarray(
        rng.choice([0.0, -1e9], size=(1, 2, 128, 128),
                   p=[0.9, 0.1]).astype("float32"))
    out = _flash_attention_data(q, k, v, mask, has_mask=True,
                                interpret=True)
    ref = _ref_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("sq,sk,h,d,causal", [
    (128, 128, 2, 64, False),
    (128, 128, 1, 64, True),
    (200, 200, 1, 32, True),
    (128, 128, 2, 128, True),    # d=128: PACKED layout backward
])
def test_backward_matches_reference(sq, sk, h, d, causal):
    rng = np.random.RandomState(3)
    q, k, v = _rand_qkv(rng, 1, sq, sk, h, d)

    def loss_pallas(q, k, v):
        out = _flash_attention_data(q, k, v, is_causal=causal,
                                    interpret=True)
        return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

    def loss_ref(q, k, v):
        out = _ref_attention(q, k, v, is_causal=causal)
        return jnp.sum(out * jnp.cos(out))

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_backward_with_mask():
    rng = np.random.RandomState(4)
    q, k, v = _rand_qkv(rng, 1, 128, 128, 2, 64)
    mask = np.zeros((1, 1, 128, 128), dtype="float32")
    mask[..., 100:] = -1e9
    mask = jnp.asarray(mask)

    def loss_pallas(q, k, v):
        return jnp.sum(_flash_attention_data(
            q, k, v, mask, has_mask=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, mask=mask) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)


def test_tensor_level_wrapper_backward():
    """flash_attention through the framework tape (Tensor.backward)."""
    import paddle_tpu as paddle
    from paddle_tpu.ops.pallas_kernels import flash_attention

    rng = np.random.RandomState(5)
    q = paddle.to_tensor(rng.randn(1, 128, 2, 64).astype("float32"),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.randn(1, 128, 2, 64).astype("float32"),
                         stop_gradient=False)
    v = paddle.to_tensor(rng.randn(1, 128, 2, 64).astype("float32"),
                         stop_gradient=False)
    out = flash_attention(q, k, v, is_causal=True, interpret=True)
    out.sum().backward()
    assert q.grad is not None and np.isfinite(
        np.asarray(q.grad.numpy())).all()
    assert k.grad is not None and v.grad is not None


def test_trainable_mask_gets_gradient():
    """A learned additive bias passed as attn_mask must receive d(mask)=ds,
    not silent zeros (round-2 review finding)."""
    rng = np.random.RandomState(6)
    q, k, v = _rand_qkv(rng, 2, 128, 128, 2, 64)
    mask = jnp.asarray(rng.randn(1, 1, 128, 128).astype("float32") * 0.1)

    def loss_pallas(m):
        return jnp.sum(_flash_attention_data(
            q, k, v, m, has_mask=True, mask_needs_grad=True,
            interpret=True) ** 2)

    def loss_ref(m):
        return jnp.sum(_ref_attention(q, k, v, mask=m) ** 2)

    gp = jax.grad(loss_pallas)(mask)
    gr = jax.grad(loss_ref)(mask)
    assert float(jnp.abs(gr).max()) > 1e-4  # reference grad is nonzero
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=5e-3, atol=1e-5)


def test_attention_dropout_applied():
    """dropout_p>0 in training must actually drop attention probs."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(7)
    q = paddle.to_tensor(rng.randn(1, 16, 2, 8).astype("float32"))
    out_nodrop = F.scaled_dot_product_attention(q, q, q, dropout_p=0.0)
    out_drop = F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                              training=True)
    # with p=0.5 over 16 keys, outputs must differ from the dense result
    assert not np.allclose(np.asarray(out_drop.numpy()),
                           np.asarray(out_nodrop.numpy()))
    # eval mode: no dropout regardless of p
    out_eval = F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                              training=False)
    np.testing.assert_allclose(np.asarray(out_eval.numpy()),
                               np.asarray(out_nodrop.numpy()), rtol=1e-6)


def test_padding_mask_broadcast_q_dim():
    """(b,1,1,sk) padding mask — must not materialize O(s^2); numerics match."""
    rng = np.random.RandomState(8)
    q, k, v = _rand_qkv(rng, 2, 128, 128, 2, 64)
    mask = np.zeros((2, 1, 1, 128), dtype="float32")
    mask[0, :, :, 100:] = -1e9  # pad out the first element's tail keys
    mask = jnp.asarray(mask)
    out = _flash_attention_data(q, k, v, mask, has_mask=True,
                                interpret=True)
    ref = _ref_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def loss_pallas(m):
        return jnp.sum(_flash_attention_data(
            q, k, v, m, has_mask=True, mask_needs_grad=True,
            interpret=True) ** 2)

    def loss_ref(m):
        return jnp.sum(_ref_attention(q, k, v, mask=m) ** 2)

    gp = jax.grad(loss_pallas)(mask)
    gr = jax.grad(loss_ref)(mask)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=5e-3, atol=1e-4)


# ------------------------------------------------------- in-kernel dropout
class TestKernelDropout:
    """dropout_p > 0 runs INSIDE the kernel (on-chip PRNG), fwd and bwd
    regenerating the same mask from the same (seed, b, h, qi, ki) tuple."""

    def test_deterministic_given_seed(self):
        rng = np.random.RandomState(11)
        q, k, v = _rand_qkv(rng, 1, 128, 128, 2, 64)
        seed = jnp.asarray([123], jnp.int32)
        a = _flash_attention_data(q, k, v, seed=seed, dropout_p=0.3,
                                  interpret=True)
        b = _flash_attention_data(q, k, v, seed=seed, dropout_p=0.3,
                                  interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = _flash_attention_data(q, k, v, seed=seed + 1, dropout_p=0.3,
                                  interpret=True)
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_differs_from_dense_and_preserves_expectation(self):
        rng = np.random.RandomState(12)
        q, k, v = _rand_qkv(rng, 1, 128, 128, 1, 64)
        dense = _flash_attention_data(q, k, v, interpret=True)
        drops = [
            np.asarray(_flash_attention_data(
                q, k, v, seed=jnp.asarray([s], jnp.int32), dropout_p=0.5,
                interpret=True))
            for s in range(8)
        ]
        assert not np.allclose(drops[0], np.asarray(dense))
        # upscale_in_train: the mean over seeds approaches the dense output
        mean = np.mean(drops, axis=0)
        corr = np.corrcoef(mean.ravel(), np.asarray(dense).ravel())[0, 1]
        assert corr > 0.9, corr

    def test_grads_consistent_with_forward(self):
        """Finite differences validate that bwd regenerates the SAME keep
        mask as fwd — a seed mismatch would fail wildly."""
        rng = np.random.RandomState(13)
        q, k, v = _rand_qkv(rng, 1, 128, 128, 1, 32)
        seed = jnp.asarray([7], jnp.int32)
        w = jnp.asarray(rng.randn(1, 128, 1, 32).astype("float32"))

        def f(qq):
            out = _flash_attention_data(qq, k, v, seed=seed, dropout_p=0.4,
                                        interpret=True)
            return jnp.sum(out * w)

        g = jax.grad(f)(q)
        eps = 1e-2
        idxs = [(0, 3, 0, 5), (0, 60, 0, 12), (0, 120, 0, 31)]
        for idx in idxs:
            dq = jnp.zeros_like(q).at[idx].set(eps)
            fd = (f(q + dq) - f(q - dq)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(fd), np.asarray(g[idx]),
                                       rtol=0.08, atol=5e-3)

    def test_training_dispatch_reaches_flash_policy(self, monkeypatch):
        """The functional dispatch must hand dropout>0 training calls to the
        flash path whenever the kernel is available — regression guard for
        the round-2 policy that silently fell back to materialized softmax
        for every training config with attention dropout."""
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.ops import pallas_kernels

        q = jnp.ones((1, 128, 2, 64), jnp.float32)
        # CPU: unavailable regardless of dropout — the reference runs
        assert not pallas_kernels.flash_attention_available(q, q, q)

        calls = {}

        def fake_available(*a, **k):
            return True

        def fake_flash(q, k, v, attn_mask=None, is_causal=False,
                       dropout_p=0.0, rng_key=None, interpret=False):
            calls["dropout_p"] = dropout_p
            calls["rng_key"] = rng_key
            return q

        monkeypatch.setattr(pallas_kernels, "flash_attention_available",
                            fake_available)
        monkeypatch.setattr(pallas_kernels, "flash_attention", fake_flash)
        t = paddle.to_tensor(np.zeros((1, 16, 2, 8), np.float32))
        F.scaled_dot_product_attention(t, t, t, dropout_p=0.25,
                                       training=True)
        assert calls["dropout_p"] == 0.25      # training reaches flash
        assert calls["rng_key"] is not None    # with a derived seed
        F.scaled_dot_product_attention(t, t, t, dropout_p=0.25,
                                       training=False)
        assert calls["dropout_p"] == 0.0       # eval: no dropout


# --------------------------------------------------------- real-TPU gates
_on_real_tpu = jax.devices()[0].platform not in ("cpu",)


@pytest.mark.skipif(not _on_real_tpu, reason="needs a real TPU chip")
class TestRealTPU:
    """Non-interpret compilation on the actual chip (VERDICT r2 item 1b:
    every round-2 test ran interpret=True and the kernel failed Mosaic
    lowering for all multi-head inputs)."""

    def test_fwd_bwd_compile_and_match_reference(self):
        rng = np.random.RandomState(21)
        q, k, v = _rand_qkv(rng, 2, 512, 512, 8, 64)
        out = _flash_attention_data(q, k, v, is_causal=True)
        ref = _ref_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

        def loss(q, k, v):
            return jnp.sum(
                _flash_attention_data(q, k, v, is_causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(_ref_attention(q, k, v, is_causal=True) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-2, atol=2e-2)

    def test_dropout_compiles_on_tpu(self):
        rng = np.random.RandomState(22)
        q, k, v = _rand_qkv(rng, 1, 512, 512, 8, 64)
        seed = jnp.asarray([5], jnp.int32)
        out = _flash_attention_data(q, k, v, seed=seed, dropout_p=0.1,
                                    is_causal=True)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_eval_mha_on_tpu_does_not_crash(self):
        """Round-2 regression: eval-mode MultiHeadAttention crashed with the
        Mosaic lowering ValueError on every real-TPU forward."""
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        mha = nn.MultiHeadAttention(embed_dim=128, num_heads=8)
        mha.eval()
        x = paddle.randn([2, 256, 128])
        out = mha(x)
        assert np.all(np.isfinite(out.numpy()))


def test_bf16_inputs_match_reference_loosely():
    """bf16 q/k/v ride the MXU-native matmul path (f32 accumulation)."""
    rng = np.random.RandomState(31)
    q, k, v = _rand_qkv(rng, 1, 128, 128, 2, 64)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = _flash_attention_data(qb, kb, vb, is_causal=True, interpret=True)
    ref = _ref_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)

    def loss(qq):
        return jnp.sum(_flash_attention_data(
            qq, kb, vb, is_causal=True, interpret=True).astype(jnp.float32))

    g = jax.grad(loss)(qb)
    assert g.dtype == jnp.bfloat16
    assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))


class TestPublicFlashAPI:
    """paddle.nn.functional.flash_attention parity surface (round 3)."""

    def test_matches_sdpa(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        r = np.random.RandomState(0)
        q = paddle.to_tensor(
            r.standard_normal((2, 32, 4, 16)).astype(np.float32))
        out, softmax = F.flash_attention(q, q, q, causal=True,
                                         training=False)
        assert softmax is None
        ref = F.scaled_dot_product_attention(q, q, q, is_causal=True,
                                             training=False)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_return_softmax_rejected(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        q = paddle.to_tensor(np.zeros((1, 8, 2, 8), np.float32))
        with pytest.raises(NotImplementedError):
            F.flash_attention(q, q, q, return_softmax=True)

    def test_unpadded_rejected_with_guidance(self):
        import paddle_tpu.nn.functional as F
        with pytest.raises(NotImplementedError, match="pad"):
            F.flash_attn_unpadded(None, None, None, None, None, 0, 0)


class TestPackedLayout:
    """d=128 heads ride the PACKED (b, S, h*d) layout (r5): every feature
    combination the d=64 transpose path is tested with must also hold
    packed — mask, trainable-mask gradient, in-kernel dropout, ragged
    backward (review finding r5)."""

    def test_backward_with_mask_packed(self):
        rng = np.random.RandomState(11)
        q, k, v = _rand_qkv(rng, 1, 128, 128, 2, 128)
        mask = np.zeros((1, 1, 128, 128), dtype="float32")
        mask[..., 100:] = -1e9
        mask = jnp.asarray(mask)

        def loss_pallas(q, k, v):
            return jnp.sum(_flash_attention_data(
                q, k, v, mask, has_mask=True, interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(_ref_attention(q, k, v, mask=mask) ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4)

    def test_trainable_mask_gradient_packed(self):
        rng = np.random.RandomState(12)
        q, k, v = _rand_qkv(rng, 2, 128, 128, 2, 128)
        mask = jnp.asarray(rng.randn(1, 1, 128, 128).astype("float32")
                           * 0.1)

        def loss_pallas(m):
            return jnp.sum(_flash_attention_data(
                q, k, v, m, has_mask=True, mask_needs_grad=True,
                interpret=True) ** 2)

        def loss_ref(m):
            return jnp.sum(_ref_attention(q, k, v, mask=m) ** 2)

        gp = jax.grad(loss_pallas)(mask)
        gr = jax.grad(loss_ref)(mask)
        assert float(jnp.abs(gr).max()) > 1e-4
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=5e-3, atol=1e-5)

    def test_ragged_backward_packed(self):
        # sq=200 pads to 256: padded rows must contribute zero grads
        rng = np.random.RandomState(13)
        q, k, v = _rand_qkv(rng, 1, 200, 200, 2, 128)

        def loss_pallas(q, k, v):
            out = _flash_attention_data(q, k, v, is_causal=True,
                                        interpret=True)
            return jnp.sum(out * jnp.cos(out))

        def loss_ref(q, k, v):
            out = _ref_attention(q, k, v, is_causal=True)
            return jnp.sum(out * jnp.cos(out))

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gp, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_dropout_fwd_bwd_consistent_packed(self):
        # same seed fwd/bwd: E[out] preserved and grads finite/consistent
        rng = np.random.RandomState(14)
        q, k, v = _rand_qkv(rng, 1, 128, 128, 2, 128)
        seed = jnp.asarray([77], jnp.int32)

        def loss(q):
            out = _flash_attention_data(q, k, v, seed=seed,
                                        dropout_p=0.3, interpret=True)
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(q)
        assert np.all(np.isfinite(np.asarray(g)))
        out_drop = _flash_attention_data(q, k, v, seed=seed,
                                         dropout_p=0.3, interpret=True)
        out_dense = _flash_attention_data(q, k, v, interpret=True)
        assert not np.allclose(np.asarray(out_drop),
                               np.asarray(out_dense))
