"""nn.utils reparametrizations (weight_norm / spectral_norm)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.utils import (
    parameters_to_vector, remove_weight_norm, spectral_norm,
    vector_to_parameters, weight_norm,
)


class TestWeightNorm:
    def test_preserves_function_at_attach(self, rng):
        l = nn.Linear(4, 3)
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype(np.float32))
        before = l(x).numpy()
        weight_norm(l, dim=0)
        after = l(x).numpy()
        np.testing.assert_allclose(before, after, rtol=1e-5, atol=1e-6)
        names = dict(l.named_parameters())
        assert "weight_g" in names and "weight_v" in names
        assert "weight" not in names
        assert list(names["weight_g"].shape) == [4, 1]

    def test_g_scales_output(self, rng):
        l = nn.Linear(3, 3, bias_attr=False)
        weight_norm(l, dim=None)
        x = paddle.to_tensor(rng.standard_normal((2, 3)).astype(np.float32))
        base = l(x).numpy()
        l.weight_g._data = l.weight_g._data * 2.0
        doubled = l(x).numpy()
        np.testing.assert_allclose(doubled, 2.0 * base, rtol=1e-5)

    def test_grads_flow_to_g_and_v(self, rng):
        l = nn.Linear(4, 2)
        weight_norm(l)
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype(np.float32))
        loss = (l(x) * l(x)).sum()
        loss.backward()
        assert l.weight_g.grad is not None
        assert l.weight_v.grad is not None
        assert float(np.abs(l.weight_g.grad.numpy()).max()) > 0

    def test_remove_restores_plain_param(self, rng):
        l = nn.Linear(4, 3)
        x = paddle.to_tensor(rng.standard_normal((1, 4)).astype(np.float32))
        weight_norm(l)
        normed = l(x).numpy()
        remove_weight_norm(l)
        names = dict(l.named_parameters())
        assert "weight" in names and "weight_g" not in names
        np.testing.assert_allclose(l(x).numpy(), normed, rtol=1e-5, atol=1e-6)


class TestSpectralNorm:
    def test_sigma_converges_to_one(self, rng):
        l = nn.Linear(8, 6, bias_attr=False)
        # scale weight up so normalization is non-trivial
        l.weight._data = l.weight._data * 7.0
        spectral_norm(l, n_power_iterations=3)
        x = paddle.to_tensor(rng.standard_normal((2, 8)).astype(np.float32))
        for _ in range(10):  # power iteration refreshes each training fwd
            l(x)
        w_eff = l.weight.numpy()
        top = np.linalg.svd(w_eff, compute_uv=False)[0]
        assert abs(top - 1.0) < 1e-3, top

    def test_eval_freezes_u_v(self, rng):
        l = nn.Linear(5, 5, bias_attr=False)
        spectral_norm(l)
        x = paddle.to_tensor(rng.standard_normal((1, 5)).astype(np.float32))
        l(x)
        l.eval()
        u_before = l.weight_u.numpy().copy()
        l(x)
        np.testing.assert_array_equal(u_before, l.weight_u.numpy())

    def test_grads_flow_through_sigma(self, rng):
        l = nn.Linear(4, 4, bias_attr=False)
        spectral_norm(l)
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype(np.float32))
        loss = l(x).sum()
        loss.backward()
        assert l.weight_orig.grad is not None
        assert float(np.abs(l.weight_orig.grad.numpy()).max()) > 0


class TestReparamUnderJit:
    def test_weight_readable_after_traced_call(self, rng):
        """A to_static call must not leave an escaped tracer in l.weight."""
        l = nn.Linear(4, 3)
        weight_norm(l)
        sf = paddle.jit.to_static(lambda t: l(t))
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype(np.float32))
        traced = sf(x).numpy()
        w = l.weight.numpy()              # must not raise UnexpectedTracer
        assert np.all(np.isfinite(w))
        np.testing.assert_allclose(traced, l(x).numpy(), rtol=1e-5,
                                   atol=1e-6)

    def test_spectral_weight_readable_after_traced_call(self, rng):
        l = nn.Linear(4, 4, bias_attr=False)
        spectral_norm(l)
        sf = paddle.jit.to_static(lambda t: l(t))
        x = paddle.to_tensor(rng.standard_normal((1, 4)).astype(np.float32))
        sf(x)
        assert np.all(np.isfinite(l.weight.numpy()))


class TestParamVector:
    def test_roundtrip(self):
        l = nn.Linear(3, 2)
        vec = parameters_to_vector(l.parameters())
        assert list(vec.shape) == [8]
        doubled = vec * 2.0
        vector_to_parameters(doubled, l.parameters())
        np.testing.assert_allclose(
            parameters_to_vector(l.parameters()).numpy(), doubled.numpy())


class TestGradClipUtils:
    """clip_grad_norm_ / clip_grad_value_ (round 3)."""

    def _net_with_grads(self):
        import paddle_tpu.nn as nn
        lin = nn.Linear(3, 2)
        x = paddle.to_tensor(np.ones((4, 3), np.float32) * 10)
        (lin(x) ** 2).sum().backward()
        return lin

    def test_clip_grad_norm_scales_to_max(self):
        from paddle_tpu.nn.utils import clip_grad_norm_
        lin = self._net_with_grads()
        g0 = np.concatenate([p.grad.numpy().ravel()
                             for p in lin.parameters()])
        total = clip_grad_norm_(list(lin.parameters()), max_norm=1.0)
        np.testing.assert_allclose(float(total.numpy()),
                                   np.linalg.norm(g0), rtol=1e-4)
        g1 = np.concatenate([p.grad.numpy().ravel()
                             for p in lin.parameters()])
        np.testing.assert_allclose(np.linalg.norm(g1), 1.0, rtol=1e-4)

    def test_small_grads_not_scaled_up(self):
        from paddle_tpu.nn.utils import clip_grad_norm_
        import paddle_tpu.nn as nn
        lin = nn.Linear(2, 1)
        x = paddle.to_tensor(np.full((1, 2), 1e-4, np.float32))
        lin(x).sum().backward()
        g0 = np.concatenate([p.grad.numpy().ravel()
                             for p in lin.parameters()])
        clip_grad_norm_(list(lin.parameters()), max_norm=100.0)
        g1 = np.concatenate([p.grad.numpy().ravel()
                             for p in lin.parameters()])
        np.testing.assert_allclose(g0, g1)  # under the cap: untouched

    def test_inf_norm(self):
        from paddle_tpu.nn.utils import clip_grad_norm_
        lin = self._net_with_grads()
        g0 = max(float(np.abs(p.grad.numpy()).max())
                 for p in lin.parameters())
        total = clip_grad_norm_(list(lin.parameters()), max_norm=1.0,
                                norm_type=float("inf"))
        np.testing.assert_allclose(float(total.numpy()), g0, rtol=1e-5)

    def test_clip_grad_value(self):
        from paddle_tpu.nn.utils import clip_grad_value_
        lin = self._net_with_grads()
        clip_grad_value_(list(lin.parameters()), 0.05)
        for p in lin.parameters():
            assert np.abs(p.grad.numpy()).max() <= 0.05 + 1e-8
