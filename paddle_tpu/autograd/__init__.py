"""paddle.autograd analog: backward, grad, PyLayer, no_grad.

Ref: python/paddle/autograd/ (upstream layout, unverified — mount empty).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.tape import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from ..core.tensor import Tensor


def is_grad_enabled() -> bool:
    return tape_mod.grad_enabled()


def backward(tensors, grad_tensors=None, retain_graph=False):
    tape_mod.backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — returns grads of `outputs` w.r.t. `inputs` without
    touching .grad. With create_graph=True the backward itself is recorded
    on the tape (each node's vjp is re-derived from its pure function), so
    the returned grads are differentiable — call grad/backward on them
    again for higher orders."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    store = {}
    targets = {id(t) for t in inputs}
    retain = bool(retain_graph) if retain_graph is not None else create_graph
    tape_mod.backward(outputs, grad_tensors=grad_outputs,
                      retain_graph=retain, targets=targets, store=store,
                      accumulate_leaf=False, create_graph=create_graph)
    results: List[Optional[Tensor]] = []
    for t in inputs:
        if id(t) in store:
            g = store[id(t)]
            if create_graph:
                results.append(g)        # recorded Tensor, differentiable
            else:
                results.append(Tensor(g, stop_gradient=True))
        else:
            if not allow_unused:
                raise RuntimeError(
                    "one of the inputs received no gradient; pass "
                    "allow_unused=True to get None instead"
                )
            results.append(None)
    return results


def _call_pure(func, datas):
    """Run an eager Tensor-func on raw (possibly traced) arrays, no tape."""
    with no_grad():
        outs = func(*[Tensor(d) for d in datas])
    if isinstance(outs, (tuple, list)):
        return tuple(o._data for o in outs)
    return outs._data


def _multi_result(fn, xs, single_in, create_graph, name):
    """Evaluate a tuple-returning pure fn of the input datas; with
    create_graph the evaluation is recorded so results are differentiable."""
    if create_graph:
        from ..core.dispatch import apply_callable

        res = apply_callable(name, fn, *xs)
        out = res if isinstance(res, tuple) else (res,)
    else:
        with no_grad():
            vals = fn(*[x._data for x in xs])
        if not isinstance(vals, tuple):
            vals = (vals,)
        out = tuple(Tensor(v, stop_gradient=True) for v in vals)
    return out[0] if single_in else tuple(out)


def jacobian(func, inputs, create_graph=False, allow_unused=False):
    """Jacobian of ``func`` (a single-output Tensor function) at ``inputs``.

    Computed with jax.jacrev over the eager function — the eager ops run on
    tracers, so the whole Jacobian is one reverse-mode XLA program instead
    of a Python loop of per-row tape walks. Returns a Tensor for a single
    input, else a tuple with one Jacobian per input.
    """
    single_in = isinstance(inputs, Tensor)
    xs = [inputs] if single_in else list(inputs)

    def jac_fn(*ds):
        j = jax.jacrev(lambda *dd: _call_pure(func, dd),
                       argnums=tuple(range(len(ds))))(*ds)
        if isinstance(j, tuple) and isinstance(j[0], tuple):
            raise RuntimeError("jacobian supports single-output functions")
        if isinstance(j, tuple) and len(j) == 1:
            return j[0]   # bare single value: tape vjps expect no 1-tuples
        return j

    return _multi_result(jac_fn, xs, single_in, create_graph, "jacobian")


def hessian(func, inputs, create_graph=False, allow_unused=False):
    """Hessian of a scalar-valued Tensor function (forward-over-reverse).
    Single input → Tensor; N inputs → N×N nested tuple (flattened row-major
    tuple of Tensors per input pair)."""
    single_in = isinstance(inputs, Tensor)
    xs = [inputs] if single_in else list(inputs)
    n = len(xs)

    def scalar(*ds):
        out = _call_pure(func, ds)
        if isinstance(out, tuple):
            out = out[0]
        if out.size != 1:
            raise RuntimeError("hessian requires a scalar-valued function")
        return out.reshape(())

    def hes_fn(*ds):
        h = jax.hessian(scalar, argnums=tuple(range(len(ds))))(*ds)
        flat = tuple(h[i][j] for i in range(n) for j in range(n))
        return flat[0] if len(flat) == 1 else flat

    flat = _multi_result(hes_fn, xs, False, create_graph, "hessian")
    if single_in:
        return flat[0]
    return tuple(tuple(flat[i * n + j] for j in range(n)) for i in range(n))


def jvp(func, xs, v=None):
    """Forward-mode: (outputs, J @ v). Ref: paddle.incubate.autograd.jvp
    (upstream layout, unverified — mount empty). v defaults to ones."""
    single_in = isinstance(xs, Tensor)
    xs_t = (xs,) if single_in else tuple(xs)
    datas = tuple(x._data for x in xs_t)
    if v is None:
        tangents = tuple(jnp.ones_like(d) for d in datas)
    else:
        v_t = (v,) if isinstance(v, Tensor) else tuple(v)
        tangents = tuple(t._data for t in v_t)

    def pure(*ds):
        return _call_pure(func, ds)

    with no_grad():
        outs, tans = jax.jvp(pure, datas, tangents)
    wrap = lambda t: Tensor(t, stop_gradient=True)  # noqa: E731
    if isinstance(outs, tuple):
        return tuple(map(wrap, outs)), tuple(map(wrap, tans))
    return wrap(outs), wrap(tans)


def vjp(func, xs, v=None):
    """Reverse-mode: (outputs, vᵀ @ J). Ref: paddle.incubate.autograd.vjp
    (upstream layout, unverified — mount empty). v defaults to ones."""
    single_in = isinstance(xs, Tensor)
    xs_t = (xs,) if single_in else tuple(xs)
    datas = tuple(x._data for x in xs_t)

    def pure(*ds):
        return _call_pure(func, ds)

    with no_grad():
        outs, pullback = jax.vjp(pure, *datas)
        if v is None:
            if isinstance(outs, tuple):
                cots = tuple(jnp.ones_like(o) for o in outs)
            else:
                cots = jnp.ones_like(outs)
        else:
            if isinstance(v, Tensor):
                cots = v._data
            else:
                cots = tuple(t._data for t in v)
        grads = pullback(cots)
    wrap = lambda t: Tensor(t, stop_gradient=True)  # noqa: E731
    outs_w = tuple(map(wrap, outs)) if isinstance(outs, tuple) \
        else wrap(outs)
    grads_w = wrap(grads[0]) if single_in else tuple(map(wrap, grads))
    return outs_w, grads_w


#: active (pack, unpack) hook pair installed by saved_tensors_hooks
_SAVED_TENSOR_HOOKS: list = []


class saved_tensors_hooks:
    """paddle.autograd.saved_tensors_hooks: intercept what
    ctx.save_for_backward stores. `pack` runs when a tensor is saved (its
    return value is stored instead — e.g. a host copy, or a compressed
    form); `unpack` runs when the backward reads it back. The activation-
    offload / recompute customization seam."""

    def __init__(self, pack_hook, unpack_hook):
        self._pair = (pack_hook, unpack_hook)

    def __enter__(self):
        _SAVED_TENSOR_HOOKS.append(self._pair)
        return self

    def __exit__(self, *exc):
        _SAVED_TENSOR_HOOKS.remove(self._pair)
        return False


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._hooks = None
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        if _SAVED_TENSOR_HOOKS:
            self._hooks = _SAVED_TENSOR_HOOKS[-1]
            pack, _ = self._hooks
            self._saved = tuple(pack(t) for t in tensors)
        else:
            self._saved = tuple(tensors)

    def _unpacked(self):
        if self._hooks is not None:
            _, unpack = self._hooks
            return tuple(unpack(t) for t in self._saved)
        return self._saved

    @property
    def saved_tensor(self):
        return self._unpacked()

    def saved_tensors(self):
        return self._unpacked()


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd function (paddle.autograd.PyLayer analog).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = x.exp()
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        record = tape_mod.grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_tensors = [
            o if isinstance(o, Tensor) else Tensor(o) for o in out_list
        ]
        if record:
            n_out = len(out_tensors)

            def vjp_fn(cts):
                if n_out == 1 and not isinstance(cts, (tuple, list)):
                    cts = (cts,)
                ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                if not isinstance(gin, (tuple, list)):
                    gin = (gin,)
                gin = list(gin)
                # map returned grads onto tensor inputs
                out = []
                gi = 0
                for t in tensor_inputs:
                    g = gin[gi] if gi < len(gin) else None
                    gi += 1
                    if g is None:
                        out.append(jnp.zeros(t._data.shape, t._data.dtype))
                    else:
                        out.append(g._data if isinstance(g, Tensor)
                                   else jnp.asarray(g))
                return tuple(out)

            def vjp_tensor_fn(ct_tensors):
                # create_graph path: run the user backward with recording ON
                # so its Tensor ops land on the tape. Residuals saved from
                # the (no_grad) forward are constants; saving *inputs* in
                # forward keeps second-order flow through them.
                gin = cls.backward(ctx, *ct_tensors)
                if not isinstance(gin, (tuple, list)):
                    gin = (gin,)
                out = []
                for i, t in enumerate(tensor_inputs):
                    g = gin[i] if i < len(gin) else None
                    if g is not None and not isinstance(g, Tensor):
                        g = Tensor(jnp.asarray(g))
                    out.append(g)
                return tuple(out)

            node = tape_mod.GradNode(
                vjp_fn if len(out_tensors) > 1 else
                (lambda ct: vjp_fn((ct,))),
                tensor_inputs,
                n_outputs=len(out_tensors),
                name=cls.__name__,
                out_avals=[(o._data.shape, o._data.dtype)
                           for o in out_tensors],
                vjp_tensor_fn=vjp_tensor_fn,
            )
            for i, t in enumerate(out_tensors):
                t._grad_node = node
                t._out_index = i
                t.stop_gradient = False
        return tuple(out_tensors) if multi else out_tensors[0]


def set_to_zero_if_none(grads, refs):
    return [
        g if g is not None else Tensor(jnp.zeros(r._data.shape, r._data.dtype))
        for g, r in zip(grads, refs)
    ]
