"""Static graph IR: Program / Block / OpDesc / Variable.

Ref: paddle/fluid/framework/program_desc.* + python/paddle/base/framework.py
(upstream layout, unverified — mount empty). Paddle's ProgramDesc is a
protobuf op list interpreted by InterpreterCore; PIR made it SSA. Here the IR
is SSA from day one (SURVEY §7 hard part #3): each captured op is an OpDesc
naming SSA input/output vars, parameters are persistable vars bound to live
Parameter objects, and the Executor replays the op list as one pure jax
function compiled and cached per feed signature (the pjit-cache-as-
InterpreterCore design).
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import set_static_handler
from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor
from ..ops.registry import OPS, OpDef, get_op

__all__ = ["Program", "Block", "OpDesc", "Variable", "program_guard",
           "default_main_program", "default_startup_program",
           "in_static_mode", "enable_static", "disable_static",
           "in_dynamic_mode", "data", "name_scope"]

_name_counter = itertools.count()


def _unique_name(prefix="tmp"):
    return f"{prefix}_{next(_name_counter)}"


class Variable:
    """Symbolic SSA value in a Block (VarDesc analog). Dims of -1 are
    dynamic (batch)."""

    def __init__(self, block: "Block", name: str, shape, dtype,
                 persistable: bool = False, is_data: bool = False,
                 stop_gradient: bool = False):
        self.block = block
        self.name = name
        self.shape = list(shape)
        self.dtype = np.dtype(convert_dtype(dtype) or dtype)
        self.persistable = persistable
        self.is_data = is_data
        self.stop_gradient = stop_gradient

    @property
    def ndim(self):
        return len(self.shape)

    def dim(self):
        return len(self.shape)

    @property
    def size(self):
        return int(np.prod([d for d in self.shape if d > 0]))

    def astype(self, dtype):
        from ..core.dispatch import apply_op

        return apply_op(get_op("cast"), self, dtype=dtype)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype})")

    def __getitem__(self, item):
        """Python-value indexing (ints/slices/ellipsis), captured as an op —
        mirrors Tensor.__getitem__ so model code slices the same way in
        both modes. Tensor-valued indices are not supported in static capture."""
        from ..core.dispatch import apply_callable

        if isinstance(item, (Variable, Tensor)) or (
                isinstance(item, tuple) and any(
                    isinstance(e, (Variable, Tensor)) for e in item)):
            raise TypeError(
                "static-mode slicing supports Python indices only; use "
                "gather/index_select ops for tensor-valued indices")

        def fn(x):
            return x[item]

        return apply_callable("getitem", fn, self)

    # ---- op sugar: route every registered op through the dispatcher -----
    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        if item in OPS:
            from ..core.dispatch import apply_op

            def call(*args, **kwargs):
                return apply_op(get_op(item), self, *args, **kwargs)

            return call
        raise AttributeError(item)


def _make_var_operator(opname, reverse=False):
    def op(self, other=None):
        from ..core.dispatch import apply_op

        if other is None:
            return apply_op(get_op(opname), self)
        if reverse:
            return apply_op(get_op(opname), other, self)
        return apply_op(get_op(opname), self, other)

    return op


for _dunder, _opname in [
    ("__add__", "add"), ("__radd__", "add"), ("__sub__", "subtract"),
    ("__mul__", "multiply"), ("__rmul__", "multiply"),
    ("__truediv__", "divide"), ("__matmul__", "matmul"),
    ("__pow__", "pow"), ("__neg__", "neg"),
]:
    setattr(Variable, _dunder, _make_var_operator(
        _opname, reverse=_dunder.startswith("__r")))


class OpDesc:
    """One captured op: registry name + SSA input/output var names + attrs.
    Inputs that were live Tensors (parameters/constants) are recorded as
    persistable vars bound in the program's reference table."""

    def __init__(self, type: str, input_names: Sequence[str],
                 output_names: Sequence[str], attrs: Dict,
                 arg_template: List, fn=None):
        self.type = type
        self.input_names = list(input_names)
        self.output_names = list(output_names)
        self.attrs = dict(attrs)
        # positional skeleton: entries are ("var", idx_into_input_names) or
        # ("const", python_value)
        self.arg_template = arg_template
        # ad-hoc closure ops (getitem/slicing and other apply_callable
        # captures) are not in the registry; the concrete fn rides on the
        # OpDesc. Such Programs replay fine but are not serializable.
        self.fn = fn

    def __repr__(self):
        return (f"{{{', '.join(self.output_names)}}} = {self.type}"
                f"({', '.join(self.input_names)})")


class Block:
    def __init__(self, program: "Program", idx: int):
        self.program = program
        self.idx = idx
        self.ops: List[OpDesc] = []
        self.vars: Dict[str, Variable] = {}

    def create_var(self, name=None, shape=(), dtype="float32",
                   persistable=False, is_data=False, stop_gradient=False):
        name = name or _unique_name()
        v = Variable(self, name, shape, dtype, persistable=persistable,
                     is_data=is_data, stop_gradient=stop_gradient)
        self.vars[name] = v
        return v

    def var(self, name):
        return self.vars[name]

    def append_op(self, op: OpDesc):
        self.ops.append(op)


class Program:
    """Program ⊃ Block ⊃ OpDesc; binds persistable vars to live Tensors."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.refs: Dict[str, Tensor] = {}   # persistable name -> live Tensor
        self._data_vars: List[Variable] = []
        self.random_seed = 0
        self._minimize_hooks = []           # optimizer update records

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[-1]

    def list_vars(self):
        return list(self.global_block().vars.values())

    def all_parameters(self):
        return [self.refs[n] for n, v in self.global_block().vars.items()
                if v.persistable and isinstance(self.refs.get(n), Parameter)]

    def clone(self, for_test: bool = False):
        import copy

        p = Program()
        p.blocks = self.blocks
        p.refs = self.refs
        p._data_vars = list(self._data_vars)
        p._minimize_hooks = [] if for_test else list(self._minimize_hooks)
        return p

    def __repr__(self):
        ops = self.global_block().ops
        return f"Program({len(ops)} ops, {len(self.refs)} persistables)"


_default_main = [Program()]
_default_startup = [Program()]
_static_mode = [False]


def default_main_program() -> Program:
    return _default_main[-1]


def default_startup_program() -> Program:
    return _default_startup[-1]


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    _default_main.append(main_program)
    _default_startup.append(startup_program or Program())
    try:
        yield
    finally:
        _default_main.pop()
        _default_startup.pop()


def in_static_mode() -> bool:
    return _static_mode[0]


def in_dynamic_mode() -> bool:
    return not _static_mode[0]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


@contextlib.contextmanager
def name_scope(prefix):
    yield


def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """paddle.static.data — feed placeholder with dynamic (-1/None) dims."""
    shape = [-1 if s is None else int(s) for s in shape]
    block = default_main_program().global_block()
    v = block.create_var(name=name, shape=shape, dtype=dtype, is_data=True,
                         stop_gradient=True)
    default_main_program()._data_vars.append(v)
    return v


# --------------------------------------------------------- capture handler
def _concrete_shape(shape, dyn=2):
    return tuple(dyn if s in (-1, None) else int(s) for s in shape)


def _static_handler(opdef: OpDef, args, kwargs):
    """Called by core.dispatch for every op issued in static mode."""
    if getattr(opdef, "eager_only", False):
        raise NotImplementedError(
            f"op {opdef.name!r} has a data-dependent output shape and "
            "cannot be captured into a static Program; compute it eagerly "
            "outside the static region")
    program = default_main_program()
    block = program.current_block()

    input_names: List[str] = []
    template = []
    avals2, avals3 = [], []         # two probes to detect dynamic dims

    def record_input(x):
        if isinstance(x, Variable):
            input_names.append(x.name)
            template.append(("var", len(input_names) - 1))
            avals2.append(jax.ShapeDtypeStruct(_concrete_shape(x.shape, 2),
                                               x.dtype))
            avals3.append(jax.ShapeDtypeStruct(_concrete_shape(x.shape, 3),
                                               x.dtype))
        elif isinstance(x, Tensor):
            # live tensor (parameter / constant): persistable var
            name = None
            for n, t in program.refs.items():
                if t is x:
                    name = n
                    break
            if name is None:
                name = x.name or _unique_name("param")
                if name in program.refs and program.refs[name] is not x:
                    name = _unique_name(name)
                program.refs[name] = x
                block.create_var(name=name, shape=x.shape,
                                 dtype=x.dtype, persistable=True)
            input_names.append(name)
            template.append(("var", len(input_names) - 1))
            avals2.append(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype))
            avals3.append(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype))
        else:
            template.append(("const", x))

    for a in args:
        if isinstance(a, (list, tuple)) and any(
                isinstance(e, (Variable, Tensor)) for e in a):
            # op over a list of tensors (concat/stack): record elementwise
            sub_start = len(template)
            for e in a:
                record_input(e)
            template[sub_start:] = [("list", template[sub_start:])]
        else:
            record_input(a)

    import functools

    def probe(avals):
        def build_args(arrays):
            it = iter(arrays)
            out = []
            for kind, payload in template:
                if kind == "var":
                    out.append(next(it))
                elif kind == "list":
                    out.append([next(it) if k == "var" else p
                                for k, p in payload])
                else:
                    out.append(payload)
            return out

        return jax.eval_shape(
            lambda *xs: opdef.fn(*build_args(xs), **kwargs), *avals)

    out2 = probe(avals2)
    out3 = probe(avals3)

    multi = opdef.multi_output or isinstance(out2, (tuple, list))
    outs2 = list(out2) if multi else [out2]
    outs3 = list(out3) if multi else [out3]

    out_vars = []
    for o2, o3 in zip(outs2, outs3):
        shape = [(-1 if d2 != d3 else d2)
                 for d2, d3 in zip(o2.shape, o3.shape)]
        out_vars.append(block.create_var(shape=shape, dtype=o2.dtype))

    block.append_op(OpDesc(opdef.name, input_names,
                           [v.name for v in out_vars], kwargs, template,
                           fn=None if opdef.name in OPS else opdef.fn))
    return tuple(out_vars) if multi else out_vars[0]


set_static_handler(in_static_mode, _static_handler)
