"""paddle.distributed.rpc — socket RPC + master rendezvous
(SURVEY §2.3 rpc row)."""
import operator
import os
import socket
import subprocess
import sys

import pytest

from paddle_tpu.distributed import rpc


@pytest.fixture
def single_world():
    rpc.init_rpc("solo", rank=0, world_size=1)
    yield
    rpc.shutdown()


class TestSingleWorld:
    def test_self_call_sync(self, single_world):
        assert rpc.rpc_sync("solo", operator.add, args=(2, 3)) == 5

    def test_async_future(self, single_world):
        fut = rpc.rpc_async("solo", operator.mul, args=(6, 7))
        assert fut.wait() == 42
        assert fut.result() == 42

    def test_remote_exception_propagates(self, single_world):
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("solo", operator.truediv, args=(1, 0))

    def test_worker_info(self, single_world):
        info = rpc.get_current_worker_info()
        assert info.name == "solo" and info.rank == 0
        assert rpc.get_worker_info("solo").endpoint == info.endpoint

    def test_double_init_raises(self, single_world):
        with pytest.raises(RuntimeError, match="already"):
            rpc.init_rpc("again", rank=0, world_size=1)

    def test_reinit_after_shutdown(self):
        rpc.init_rpc("a", rank=0, world_size=1)
        rpc.shutdown()
        rpc.init_rpc("b", rank=0, world_size=1)
        assert rpc.rpc_sync("b", operator.neg, args=(4,)) == -4
        rpc.shutdown()


def test_two_real_processes_rpc(tmp_path):
    """Rank 0 executes functions on rank 1 through real sockets, with the
    master-endpoint rendezvous assembling the worker table."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ)
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "1", "--nproc_per_node", "2",
         os.path.join(os.path.dirname(__file__), "_rpc_worker.py"), master],
        capture_output=True, text=True, env=env, timeout=180,
        cwd="/root/repo")
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "rank0 rpc_ok" in out.stdout
    assert "rank1 served_ok" in out.stdout
