"""paddle.audio — spectral feature Layers, wav I/O, datasets.

Ref: python/paddle/audio/ (upstream layout, unverified — mount empty).
features are real STFT pipelines (frame → window → rfft → mel/dct), batched
and jittable; backends read/write canonical PCM wav via the stdlib so no
egress is needed; datasets follow the synthetic-fallback contract.
"""
from __future__ import annotations

import os
import warnings
import wave
import zlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..io import Dataset
from ..nn import Layer
from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    compute_fbank_matrix, create_dct, fft_frequencies, get_window, hz_to_mel,
    mel_frequencies, mel_to_hz, power_to_db,
)

__all__ = ["functional", "features", "backends", "datasets", "load", "save",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
           "ESC50", "TESS", "info"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ------------------------------------------------------------------ features

def _stft_frames(x, n_fft, hop_length, win_length, window, center,
                 pad_mode):
    """x: [..., T] -> power-ready complex STFT [..., F, frames]."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode if pad_mode != "constant"
                    else "constant")
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])                     # [frames, n_fft]
    frames = x[..., idx]                                     # [..., fr, n_fft]
    w = get_window(window, win_length)
    if win_length < n_fft:  # center-pad the window to n_fft
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    spec = jnp.fft.rfft(frames * w, n=n_fft, axis=-1)        # [..., fr, F]
    return jnp.moveaxis(spec, -1, -2)                        # [..., F, fr]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.window = window
        self.power = power
        self.center = center
        self.pad_mode = pad_mode

    def forward(self, x):
        spec = _stft_frames(_unwrap(x), self.n_fft, self.hop_length,
                            self.win_length, self.window, self.center,
                            self.pad_mode)
        mag = jnp.abs(spec)
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor(mag.astype(jnp.float32))


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm)

    def forward(self, x):
        spec = self.spectrogram(x)._data          # [..., F, frames]
        mel = jnp.einsum("mf,...ft->...mt", self.fbank, spec)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)._data
        return Tensor(power_to_db(m, self.ref_value, self.amin, self.top_db))


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db)
        self.dct = create_dct(n_mfcc, n_mels)

    def forward(self, x):
        lm = self.logmel(x)._data                 # [..., M, frames]
        return Tensor(jnp.einsum("mk,...mt->...kt", self.dct, lm))


class _FeaturesNS:
    Spectrogram = Spectrogram
    MelSpectrogram = MelSpectrogram
    LogMelSpectrogram = LogMelSpectrogram
    MFCC = MFCC


features = _FeaturesNS()


# ------------------------------------------------------------------ backends

def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Read PCM wav -> (Tensor [C, T] float32 in [-1, 1], sample_rate)."""
    with wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        n_ch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(n)
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, n_ch)
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, bits_per_sample: int = 16):
    data = np.asarray(_unwrap(src))
    if channels_first:
        data = data.T                             # [T, C]
    if data.ndim == 1:
        data = data[:, None]
    scale = float(2 ** (bits_per_sample - 1) - 1)
    pcm = np.clip(data, -1.0, 1.0) * scale
    pcm = pcm.astype({8: np.int8, 16: np.int16, 32: np.int32}[
        bits_per_sample])
    with wave.open(filepath, "wb") as w:
        w.setnchannels(data.shape[1])
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(pcm.tobytes())


class _AudioInfo:
    def __init__(self, sample_rate, num_frames, num_channels,
                 bits_per_sample):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample


def info(filepath: str) -> _AudioInfo:
    with wave.open(filepath, "rb") as w:
        return _AudioInfo(w.getframerate(), w.getnframes(),
                          w.getnchannels(), w.getsampwidth() * 8)


class _BackendsNS:
    load = staticmethod(load)
    save = staticmethod(save)
    info = staticmethod(info)

    @staticmethod
    def list_available_backends():
        return ["wave"]

    @staticmethod
    def get_current_backend():
        return "wave"

    @staticmethod
    def set_backend(backend: str):
        if backend != "wave":
            raise ValueError("only the stdlib 'wave' backend is available "
                             "in this offline environment")


backends = _BackendsNS()


# ------------------------------------------------------------------ datasets

def _dseed(*parts):
    return zlib.crc32("/".join(str(p) for p in parts).encode()) % (2 ** 31)


class _SynthAudioSet(Dataset):
    """Class-separable synthetic audio: each class is a distinct fundamental
    frequency plus noise, so spectral classifiers actually learn."""

    def __init__(self, name, n_classes, n_samples, sr, duration,
                 mode, feat_type="raw", **feat_kwargs):
        warnings.warn(f"{name}: no local data and no network access; using "
                      "deterministic synthetic samples.")
        self.sr = sr
        rng = np.random.RandomState(_dseed(name, mode))
        t = np.arange(int(sr * duration)) / sr
        self.labels = rng.randint(0, n_classes, size=n_samples).astype(
            np.int64)
        self.waves = []
        for y in self.labels:
            f0 = 110.0 * (2 ** (y / 2.0))     # class-keyed pitch
            sig = np.sin(2 * np.pi * f0 * t) + 0.1 * rng.randn(len(t))
            self.waves.append(sig.astype(np.float32))
        self.feat_type = feat_type
        self._feat = None
        if feat_type == "mfcc":
            self._feat = MFCC(sr=sr, **feat_kwargs)
        elif feat_type == "spectrogram":
            self._feat = Spectrogram(**feat_kwargs)
        elif feat_type == "melspectrogram":
            self._feat = MelSpectrogram(sr=sr, **feat_kwargs)
        elif feat_type == "logmelspectrogram":
            self._feat = LogMelSpectrogram(sr=sr, **feat_kwargs)

    def __len__(self):
        return len(self.waves)

    def __getitem__(self, i):
        w = self.waves[i]
        if self._feat is not None:
            return np.asarray(self._feat(jnp.asarray(w))._data), \
                self.labels[i]
        return w, self.labels[i]


class ESC50(_SynthAudioSet):
    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", archive=None, **kwargs):
        super().__init__("esc50", 50, 400 if mode == "train" else 100,
                         16000, 1.0, mode, feat_type, **kwargs)


class TESS(_SynthAudioSet):
    def __init__(self, mode: str = "train", n_folds: int = 5, split: int = 1,
                 feat_type: str = "raw", archive=None, **kwargs):
        super().__init__("tess", 7, 280 if mode == "train" else 70,
                         16000, 1.0, mode, feat_type, **kwargs)


class _DatasetsNS:
    ESC50 = ESC50
    TESS = TESS


datasets = _DatasetsNS()
