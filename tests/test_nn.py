"""nn.Layer machinery + layer numerics vs NumPy references."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestLayerBase:
    def test_parameters_registration(self):
        l = nn.Linear(3, 4)
        assert len(l.parameters()) == 2
        names = dict(l.named_parameters())
        assert "weight" in names and "bias" in names
        assert l.weight.shape == [3, 4]
        assert l.bias.shape == [4]

    def test_sublayer_nesting(self):
        net = nn.Sequential(nn.Linear(2, 3), nn.Sequential(nn.Linear(3, 4)))
        assert len(net.parameters()) == 4
        assert len(list(net.named_sublayers())) == 3

    def test_state_dict_roundtrip(self):
        a = nn.Linear(3, 4)
        b = nn.Linear(3, 4)
        b.set_state_dict(a.state_dict())
        np.testing.assert_array_equal(a.weight.numpy(), b.weight.numpy())

    def test_train_eval_propagates(self):
        net = nn.Sequential(nn.Dropout(0.5), nn.Sequential(nn.Dropout(0.5)))
        net.eval()
        assert all(not l.training for l in net.sublayers())
        net.train()
        assert all(l.training for l in net.sublayers())

    def test_apply(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
        seen = []
        net.apply(lambda l: seen.append(type(l).__name__))
        assert seen.count("Linear") == 2

    def test_forward_hooks(self):
        l = nn.Linear(2, 2)
        calls = []
        h = l.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        l(paddle.randn([1, 2]))
        assert calls == [1]
        h.remove()
        l(paddle.randn([1, 2]))
        assert calls == [1]

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(4)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_to_dtype(self):
        l = nn.Linear(2, 2).to(dtype="bfloat16")
        assert l.weight.dtype == paddle.bfloat16


class TestLayerNumerics:
    def test_linear_matches_numpy(self):
        l = nn.Linear(3, 4)
        x = np.random.rand(5, 3).astype("float32")
        out = l(paddle.to_tensor(x)).numpy()
        ref = x @ l.weight.numpy() + l.bias.numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_conv2d_matches_simple_case(self):
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        conv.weight.set_value(np.ones((1, 1, 2, 2), "float32"))
        x = np.arange(9, dtype="float32").reshape(1, 1, 3, 3)
        out = conv(paddle.to_tensor(x)).numpy()
        # each output = sum of 2x2 window
        expected = np.array([[[[0 + 1 + 3 + 4, 1 + 2 + 4 + 5],
                               [3 + 4 + 6 + 7, 4 + 5 + 7 + 8]]]],
                            dtype="float32")
        np.testing.assert_allclose(out, expected)

    def test_conv2d_grouped(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        out = conv(paddle.randn([2, 4, 5, 5]))
        assert out.shape == [2, 8, 5, 5]

    def test_conv2d_transpose_shape(self):
        deconv = nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)
        out = deconv(paddle.randn([2, 3, 8, 8]))
        assert out.shape == [2, 6, 16, 16]

    def test_layer_norm(self):
        ln = nn.LayerNorm(8)
        x = np.random.rand(4, 8).astype("float32") * 5
        out = ln(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
        np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)

    def test_batch_norm_train_and_eval(self):
        bn = nn.BatchNorm2D(3, momentum=0.5)
        x = paddle.randn([8, 3, 4, 4])
        out = bn(x)
        np.testing.assert_allclose(
            out.numpy().mean(axis=(0, 2, 3)), 0, atol=1e-4)
        # running stats moved away from init
        assert not np.allclose(bn._mean.numpy(), 0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [8, 3, 4, 4]

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor([0, 1]))
        np.testing.assert_allclose(out.numpy()[0], 0)

    def test_dropout_train_vs_eval(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([1000])
        out = d(x)
        frac_zero = float((out == 0).astype("float32").mean())
        assert 0.3 < frac_zero < 0.7
        d.eval()
        np.testing.assert_array_equal(d(x).numpy(), x.numpy())

    def test_maxpool_avgpool(self):
        x = paddle.to_tensor(
            np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
        mp = nn.MaxPool2D(2)(x)
        np.testing.assert_allclose(mp.numpy().reshape(-1), [5, 7, 13, 15])
        ap = nn.AvgPool2D(2)(x)
        np.testing.assert_allclose(ap.numpy().reshape(-1),
                                   [2.5, 4.5, 10.5, 12.5])

    def test_adaptive_avg_pool(self):
        out = nn.AdaptiveAvgPool2D(1)(paddle.randn([2, 3, 7, 7]))
        assert out.shape == [2, 3, 1, 1]

    def test_softmax_layer(self):
        out = nn.Softmax()(paddle.randn([3, 5]))
        np.testing.assert_allclose(out.numpy().sum(-1), 1, rtol=1e-5)

    def test_activations_shapes(self):
        x = paddle.randn([4, 4])
        for cls in [nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh, nn.SiLU,
                    nn.LeakyReLU, nn.ELU, nn.Hardswish, nn.Mish,
                    nn.Softplus]:
            assert cls()(x).shape == [4, 4]

    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        out = mha(paddle.randn([2, 5, 16]))
        assert out.shape == [2, 5, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        out = enc(paddle.randn([2, 5, 16]))
        assert out.shape == [2, 5, 16]

    def test_lstm_gru(self):
        out, (h, c) = nn.LSTM(4, 8)(paddle.randn([2, 6, 4]))
        assert out.shape == [2, 6, 8] and h.shape == [1, 2, 8]
        out, h = nn.GRU(4, 8, direction="bidirect")(paddle.randn([2, 6, 4]))
        assert out.shape == [2, 6, 16]

    def test_grad_flows_through_layers(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
        loss = net(paddle.randn([4, 4])).sum()
        loss.backward()
        for p in net.parameters():
            assert p.grad is not None


class TestFunctional:
    def test_cross_entropy_matches_numpy(self):
        logits = np.random.rand(4, 3).astype("float32")
        labels = np.array([0, 2, 1, 1])
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels)).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_cross_entropy_ignore_index(self):
        logits = paddle.randn([4, 3])
        labels = paddle.to_tensor([0, -100, 1, -100])
        loss = F.cross_entropy(logits, labels, ignore_index=-100)
        loss_manual = F.cross_entropy(logits[paddle.to_tensor([0, 2])],
                                      paddle.to_tensor([0, 1]))
        np.testing.assert_allclose(loss.numpy(), loss_manual.numpy(),
                                   rtol=1e-5)

    def test_mse(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([3.0, 2.0])
        assert float(F.mse_loss(a, b)) == pytest.approx(2.0)

    def test_bce_with_logits(self):
        logit = paddle.to_tensor([0.0])
        label = paddle.to_tensor([1.0])
        np.testing.assert_allclose(
            F.binary_cross_entropy_with_logits(logit, label).numpy(),
            np.log(2), rtol=1e-5)

    def test_attention_reference(self):
        q = paddle.randn([2, 4, 2, 8])
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        assert out.shape == [2, 4, 2, 8]

    def test_one_hot(self):
        out = F.one_hot(paddle.to_tensor([0, 2]), 3)
        np.testing.assert_array_equal(out.numpy(),
                                      [[1, 0, 0], [0, 0, 1]])

    def test_pad(self):
        x = paddle.ones([1, 1, 2, 2])
        out = F.pad(x, [1, 1, 1, 1])
        assert out.shape == [1, 1, 4, 4]

    def test_interpolate(self):
        x = paddle.randn([1, 3, 4, 4])
        assert F.interpolate(x, scale_factor=2).shape == [1, 3, 8, 8]
        assert F.interpolate(x, size=[2, 2], mode="bilinear").shape == \
            [1, 3, 2, 2]


class TestInitializers:
    def test_constant(self):
        l = nn.Linear(4, 4, weight_attr=nn.initializer.Constant(2.0))
        np.testing.assert_allclose(l.weight.numpy(), 2.0)

    def test_xavier_scale(self):
        import paddle_tpu.nn.initializer as I

        w = I.XavierNormal()((1000, 1000), "float32")
        assert abs(float(w.std()) - (2.0 / 2000) ** 0.5) < 1e-3

    def test_kaiming(self):
        import paddle_tpu.nn.initializer as I

        w = I.KaimingNormal()((1000, 100), "float32")
        assert abs(float(w.std()) - (2.0 / 1000) ** 0.5) < 5e-3


class TestIncubateFusedLayers:
    def test_fused_mha_matches_manual(self, rng):
        """Eval-mode fused attention == hand-computed attention with the
        same fused weights."""
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        paddle.seed(5)
        m = FusedMultiHeadAttention(embed_dim=16, num_heads=4,
                                    dropout_rate=0.0, attn_dropout_rate=0.0)
        m.eval()
        x = paddle.to_tensor(rng.standard_normal((2, 6, 16))
                             .astype(np.float32))
        out = m(x).numpy()

        xn = x.numpy()
        qkv = xn @ m.qkv_weight.numpy() + m.qkv_bias.numpy()
        q, k, v = np.split(qkv.reshape(2, 6, 3, 4, 4), 3, axis=2)
        ref = np.empty((2, 6, 4, 4), np.float32)
        for b in range(2):
            for h in range(4):
                qs, ks, vs = (t[b, :, 0, h] for t in (q, k, v))
                sc = qs @ ks.T / 2.0
                p = np.exp(sc - sc.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                ref[b, :, h] = p @ vs
        ref = ref.reshape(2, 6, 16) @ m.linear_weight.numpy() \
            + m.linear_bias.numpy()
        ref = xn + ref                       # residual (post-LN layout)
        mean = ref.mean(-1, keepdims=True)
        var = ref.var(-1, keepdims=True)
        ref = (ref - mean) / np.sqrt(var + 1e-5) * m.ln.weight.numpy() \
            + m.ln.bias.numpy()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)

    def test_fused_encoder_layer_trains(self, rng):
        from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer

        paddle.seed(6)
        layer = FusedTransformerEncoderLayer(d_model=16, nhead=4,
                                             dim_feedforward=32,
                                             dropout_rate=0.0)
        x = paddle.to_tensor(rng.standard_normal((2, 5, 16))
                             .astype(np.float32))
        out = layer(x)
        assert out.shape == [2, 5, 16]
        loss = (out * out).sum()
        loss.backward()
        g = layer.fused_attn.qkv_weight.grad
        assert g is not None and float(np.abs(g.numpy()).max()) > 0

    def test_fused_pre_ln_variant(self, rng):
        from paddle_tpu.incubate.nn import FusedFeedForward

        ffn = FusedFeedForward(8, 16, dropout_rate=0.0,
                               normalize_before=True)
        ffn.eval()
        x = paddle.to_tensor(rng.standard_normal((1, 3, 8))
                             .astype(np.float32))
        out = ffn(x).numpy()
        xn = x.numpy()
        mean = xn.mean(-1, keepdims=True)
        var = xn.var(-1, keepdims=True)
        ln = (xn - mean) / np.sqrt(var + 1e-5) * ffn.norm.weight.numpy() \
            + ffn.norm.bias.numpy()
        h = np.maximum(ln @ ffn.linear1.weight.numpy()
                       + ffn.linear1.bias.numpy(), 0)
        ref = xn + h @ ffn.linear2.weight.numpy() + ffn.linear2.bias.numpy()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)

    def test_fused_mha_no_bias(self, rng):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        m = FusedMultiHeadAttention(embed_dim=8, num_heads=2,
                                    dropout_rate=0.0, attn_dropout_rate=0.0,
                                    bias_attr=False)
        m.eval()
        x = paddle.to_tensor(rng.standard_normal((1, 4, 8))
                             .astype(np.float32))
        out = m(x)
        assert out.shape == [1, 4, 8]
