"""Per-request lifecycle spans: enqueue -> admission -> prefill -> first
token -> decode blocks -> finish / preempt / requeue.

Every span/point carries the request id in its NAME
(`serving.request[<rid>].<stage>`) and is folded into the
paddle_tpu.profiler host tracer (`add_host_span`), so a chrome-trace
export of a serving run shows scheduler decisions per request on the
same timeline as the `serving.prefill` / `serving.decode_block` /
`serving.host_drain` RecordEvent spans — and
`tools/trace_summary.py --requests` reconstructs per-request timelines
from the exported file.

The tracker also RETAINS stage transitions locally (capped per request,
so a long-running engine stays bounded) for stats/tests independent of
whether a profiler window happens to be armed; high-volume spans
(per-block decode spans) are emitted to the tracer only (`retain=False`).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

__all__ = ["LifecycleTracker"]


class LifecycleTracker:
    def __init__(self, max_events_per_request: int = 512,
                 tag: Optional[str] = None):
        self.max_events_per_request = max_events_per_request
        # deployment tag appended to every EMITTED span name
        # (`serving.request[rid].stage@tag`, e.g. tag="tp=2"); the
        # locally retained events keep the plain stage so stats/tests
        # are tag-agnostic. The host tracer's events carry only a name,
        # so the tag rides in the name by design.
        self.tag = tag
        # rid -> [(stage, t0, t1)] in emission order; points have t0 == t1
        self._events: Dict[int, List[Tuple[str, float, float]]] = {}
        self._dropped = 0

    @staticmethod
    def span_name(rid: int, stage: str) -> str:
        return f"serving.request[{rid}].{stage}"

    @property
    def dropped(self) -> int:
        return self._dropped

    def span(self, rid: int, stage: str, start: float, end: float,
             retain: bool = True) -> None:
        from ..profiler import add_host_span

        name = self.span_name(rid, stage)
        if self.tag:
            name = f"{name}@{self.tag}"
        add_host_span(name, start, end, event_type="RequestLifecycle")
        if not retain:
            return
        lst = self._events.setdefault(rid, [])
        if len(lst) < self.max_events_per_request:
            lst.append((stage, start, end))
        else:
            self._dropped += 1

    def point(self, rid: int, stage: str, t: float = None,
              retain: bool = True) -> None:
        if t is None:
            t = time.perf_counter()
        self.span(rid, stage, t, t, retain=retain)

    # ------------------------------------------------------------ queries
    def request_ids(self) -> List[int]:
        return sorted(self._events)

    def events(self, rid: int) -> List[Tuple[str, float, float]]:
        return list(self._events.get(rid, ()))

    def stages(self, rid: int) -> List[str]:
        return [stage for stage, _, _ in self._events.get(rid, ())]

    def timeline(self, rid: int) -> str:
        """Human-readable per-request timeline (ms relative to the first
        recorded event)."""
        evs = self._events.get(rid, ())
        if not evs:
            return f"request {rid}: no recorded lifecycle events"
        t0 = evs[0][1]
        lines = [f"request {rid}:"]
        for stage, a, b in evs:
            dur = f" ({(b - a) * 1e3:.3f} ms)" if b > a else ""
            lines.append(f"  +{(a - t0) * 1e3:9.3f} ms  {stage}{dur}")
        return "\n".join(lines)
